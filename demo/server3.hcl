name = "server3"
bind_addr = "127.0.0.1"
data_dir = "/tmp/nomad-tpu-demo/server3"

ports {
  http = 4648
  rpc = 4703
  serf = 4803
}

server {
  enabled = true
  bootstrap_expect = 3
  start_join = ["127.0.0.1:4801"]
}
