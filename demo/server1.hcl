name = "server1"
bind_addr = "127.0.0.1"
data_dir = "/tmp/nomad-tpu-demo/server1"

ports {
  http = 4646
  rpc = 4701
  serf = 4801
}

server {
  enabled = true
  bootstrap_expect = 3

}
