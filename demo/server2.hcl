name = "server2"
bind_addr = "127.0.0.1"
data_dir = "/tmp/nomad-tpu-demo/server2"

ports {
  http = 4647
  rpc = 4702
  serf = 4802
}

server {
  enabled = true
  bootstrap_expect = 3
  start_join = ["127.0.0.1:4801"]
}
