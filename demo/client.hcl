name = "client1"
bind_addr = "127.0.0.1"
data_dir = "/tmp/nomad-tpu-demo/client"

ports {
  http = 4650
}

client {
  enabled = true
  server_discovery_url = "http://127.0.0.1:4646"

  options {
    "driver.raw_exec.enable" = "1"
  }
}
