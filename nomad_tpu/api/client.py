"""API client library (reference: api/api.go, api/jobs.go, api/nodes.go,
api/allocations.go, api/evaluations.go, api/fs.go, api/agent.go).

Typed wrappers over the /v1 HTTP API with blocking-query support
(QueryOptions.wait_index / wait_time -> `index`/`wait` params, last index
read back from X-Nomad-Index).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from nomad_tpu.resilience.retry import Backoff, RetryPolicy
from nomad_tpu.structs import Job, from_dict, to_dict


class APIError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"Unexpected response code: {code} ({message})")
        self.code = code


class BackpressureAPIError(APIError):
    """HTTP 429: the submission was shed by QoS admission control
    (server-side QoSBackpressureError). Safe to retry — the server
    rejected BEFORE writing anything — and the client does so
    automatically with RetryPolicy (``backpressure_retries``)."""


class EventGapAPIError(APIError):
    """HTTP 416 from /v1/event/stream: the requested resume index
    precedes the server's retained event window — events were evicted
    (or predate a snapshot install) and can NEVER be replayed. Not
    retryable: the consumer must re-snapshot state via the list APIs and
    resubscribe from ``floor`` (or 0 for "live from now")."""

    def __init__(self, code: int, message: str,
                 requested: int = 0, floor: int = 0):
        super().__init__(code, message)
        self.requested = requested
        self.floor = floor


@dataclass
class QueryOptions:
    region: str = ""
    prefix: str = ""
    wait_index: int = 0
    wait_time: float = 0.0  # seconds


@dataclass
class WriteOptions:
    region: str = ""


@dataclass
class QueryMeta:
    last_index: int = 0
    known_leader: bool = False


class Client:
    def __init__(self, address: str = "http://127.0.0.1:4646",
                 region: str = "", retries: int = 3,
                 backpressure_retries: int = 4):
        self.address = address.rstrip("/")
        self.region = region
        # Transient-transport retry budget for idempotent reads (an agent
        # mid-restart, a briefly unreachable listener). Writes never
        # retry automatically: re-sending a register is not idempotent
        # from the caller's perspective (duplicate evals).
        self.retries = max(1, retries)
        # QoS backpressure (HTTP 429) retry budget — applies to writes
        # too: a shed submission was rejected BEFORE any server write, so
        # re-sending cannot duplicate anything. 1 disables (the 429
        # surfaces as BackpressureAPIError).
        self.backpressure_retries = max(1, backpressure_retries)
        self.jobs = Jobs(self)
        self.nodes = Nodes(self)
        self.allocations = Allocations(self)
        self.evaluations = Evaluations(self)
        self.agent = Agent(self)
        self.regions = Regions(self)
        self.services = Services(self)
        self.system = System(self)
        self.alloc_fs = AllocFS(self)

    # ------------------------------------------------------------ plumbing
    def _url(self, path: str, params: Optional[Dict[str, str]] = None) -> str:
        url = self.address + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        return url

    def _params(self, q: Optional[QueryOptions]) -> Dict[str, str]:
        params: Dict[str, str] = {}
        region = (q.region if q else "") or self.region
        if region:
            params["region"] = region
        if q is not None:
            if q.prefix:
                params["prefix"] = q.prefix
            if q.wait_index:
                params["index"] = str(q.wait_index)
            if q.wait_time:
                params["wait"] = f"{q.wait_time}s"
        return params

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                body: Any = None,
                timeout: float = 330.0) -> Tuple[Any, QueryMeta]:
        def once() -> Tuple[Any, QueryMeta]:
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(self._url(path, params), data=data,
                                         method=method)
            if data is not None:
                req.add_header("Content-Type", "application/json")
            req.add_header("Accept-Encoding", "gzip")
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    raw = resp.read()
                    if resp.headers.get("Content-Encoding") == "gzip":
                        import gzip

                        raw = gzip.decompress(raw)
                    meta = QueryMeta(
                        last_index=int(resp.headers.get("X-Nomad-Index", 0)),
                        known_leader=resp.headers.get(
                            "X-Nomad-KnownLeader", "") == "true")
                    return (json.loads(raw) if raw else None), meta
            except urllib.error.HTTPError as e:
                body_text = e.read().decode(errors="replace")
                if e.code == 429:
                    raise BackpressureAPIError(e.code, body_text) from e
                raise APIError(e.code, body_text) from e

        if method != "GET" or self.retries <= 1:
            if self.backpressure_retries <= 1:
                return once()
            # Writes retry ONLY typed backpressure (QoS admission shed):
            # nothing was written server-side, so a jittered re-send is
            # safe where a blind transport retry would not be.
            policy = RetryPolicy(max_attempts=self.backpressure_retries,
                                 backoff=Backoff(base=0.25, cap=3.0),
                                 retry_on=(BackpressureAPIError,))
            return policy.call(once)

        def transient(exc: BaseException) -> bool:
            # A timed-out request already waited the full budget; against
            # a wedged (accepting-but-silent) agent, re-waiting it
            # retries-times over turns one hang into several. Only
            # connection-level failures (refused/reset mid-restart) are
            # worth re-trying.
            return not isinstance(getattr(exc, "reason", exc),
                                  TimeoutError)

        # HTTPError never reaches the policy (mapped to APIError above),
        # so retry_on=URLError is purely transport-level failures.
        policy = RetryPolicy(max_attempts=self.retries,
                             backoff=Backoff(base=0.1, cap=2.0),
                             retry_on=(urllib.error.URLError,
                                       ConnectionError),
                             should_retry=transient)
        return policy.call(once)

    def event_stream(self, topics: Optional[List[str]] = None,
                     from_index: int = 0, fanout: bool = False,
                     heartbeat: float = 10.0,
                     yield_heartbeats: bool = False,
                     reconnect_attempts: Optional[int] = None):
        """Follow /v1/event/stream: yields event frames
        ``{"Index": N, "Events": [...]}`` in raft-index order, forever.

        Resume is automatic: the iterator tracks the last delivered
        index, and a transport drop mid-stream (agent restart, leader
        kill, broker reset) reconnects with ``index=<last seen>`` under
        a jittered RetryPolicy — the server replays its retained window
        after that index, so the consumer observes a gapless,
        duplicate-free continuation. A resume that falls off the
        retained window raises :class:`EventGapAPIError` (HTTP 416);
        that is not retried — the consumer must re-snapshot state.

        ``topics`` entries are ``"Topic"`` or ``"Topic:key"`` selectors;
        ``fanout=True`` asks the server to expand AllocationBatch events
        into per-alloc rows; heartbeats (empty frames proving liveness)
        are swallowed unless ``yield_heartbeats``.
        """
        last = int(from_index)
        attempts = (self.retries if reconnect_attempts is None
                    else reconnect_attempts)

        def connect():
            params: List[Tuple[str, str]] = []
            if self.region:
                params.append(("region", self.region))
            for t in (topics or ()):
                params.append(("topic", t))
            params.append(("index", str(last)))
            if fanout:
                params.append(("fanout", "true"))
            params.append(("heartbeat", str(heartbeat)))
            url = (self.address + "/v1/event/stream?"
                   + urllib.parse.urlencode(params))
            req = urllib.request.Request(url, method="GET")
            try:
                # Read timeout must comfortably exceed the heartbeat
                # cadence — a healthy-but-quiet stream is not a hang.
                return urllib.request.urlopen(
                    req, timeout=max(30.0, heartbeat * 3))
            except urllib.error.HTTPError as e:
                body_text = e.read().decode(errors="replace")
                if e.code == 416:
                    try:
                        info = json.loads(body_text)
                    except ValueError:
                        info = {}
                    raise EventGapAPIError(
                        e.code, body_text,
                        requested=int(info.get("Requested", last)),
                        floor=int(info.get("Floor", 0))) from e
                raise APIError(e.code, body_text) from e

        policy = RetryPolicy(max_attempts=max(1, attempts),
                             backoff=Backoff(base=0.25, cap=5.0),
                             retry_on=(urllib.error.URLError,
                                       ConnectionError))
        # lint: allow(retry, reconnect loop around RetryPolicy-backed
        # connects — each successful frame resets the budget by design)
        while True:
            resp = policy.call(connect)
            try:
                for line in resp:
                    line = line.strip()
                    if not line:
                        continue
                    frame = json.loads(line)
                    if frame.get("Closed"):
                        # Broker reset/shutdown: reconnect and resume
                        # from the last delivered index; a real gap
                        # surfaces as EventGapAPIError on reconnect.
                        break
                    if "Events" not in frame:
                        if yield_heartbeats:
                            yield frame
                        continue
                    last = int(frame.get("Index", last))
                    yield frame
            except (urllib.error.URLError, ConnectionError, OSError):
                pass  # transport drop mid-stream: resume from `last`
            finally:
                try:
                    resp.close()
                except OSError:
                    pass

    def get(self, path: str, q: Optional[QueryOptions] = None):
        return self.request("GET", path, self._params(q))

    def put(self, path: str, body: Any = None,
            w: Optional[WriteOptions] = None,
            params: Optional[Dict[str, str]] = None):
        merged = self._params(None)
        if params:
            merged.update(params)
        return self.request("PUT", path, merged, body)

    def delete(self, path: str):
        return self.request("DELETE", path, self._params(None))


class Jobs:
    """(reference: api/jobs.go)"""

    def __init__(self, c: Client):
        self.c = c

    def register(self, job: Job, enforce_index: Optional[int] = None
                 ) -> Tuple[str, QueryMeta]:
        eval_id, _, meta = self.register_with_warnings(job, enforce_index)
        return eval_id, meta

    def register_with_warnings(
            self, job: Job, enforce_index: Optional[int] = None
    ) -> Tuple[str, List[str], QueryMeta]:
        """Register, also returning server-side validation warnings
        (reference: JobRegisterResponse.Warnings — e.g. accepted-but-
        ignored driver config keys)."""
        body: Dict[str, Any] = {"Job": to_dict(job)}
        if enforce_index is not None:
            body["EnforceIndex"] = True
            body["JobModifyIndex"] = enforce_index
        out, meta = self.c.put("/v1/jobs", body)
        return out.get("EvalID", ""), list(out.get("Warnings") or ()), meta

    def list(self, q: Optional[QueryOptions] = None):
        return self.c.get("/v1/jobs", q)

    def info(self, job_id: str, q: Optional[QueryOptions] = None) -> Tuple[Job, QueryMeta]:
        out, meta = self.c.get(f"/v1/job/{urllib.parse.quote(job_id)}", q)
        return from_dict(Job, out), meta

    def deregister(self, job_id: str) -> Tuple[str, QueryMeta]:
        out, meta = self.c.delete(f"/v1/job/{urllib.parse.quote(job_id)}")
        return out.get("EvalID", ""), meta

    def plan(self, job: Job, diff: bool = True
             ) -> Tuple["JobPlanResponse", QueryMeta]:
        """Dry-run scheduling (reference: api/jobs.go:144-160 Jobs.Plan)."""
        from nomad_tpu.structs import JobPlanResponse
        from nomad_tpu.structs.diff import JobDiff

        body = {"Job": to_dict(job), "Diff": diff}
        out, meta = self.c.put(
            f"/v1/job/{urllib.parse.quote(job.ID)}/plan", body)
        resp = from_dict(JobPlanResponse, out)
        if resp.Diff is not None:
            resp.Diff = from_dict(JobDiff, resp.Diff)
        return resp, meta

    def allocations(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/job/{urllib.parse.quote(job_id)}/allocations", q)

    def evaluations(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/job/{urllib.parse.quote(job_id)}/evaluations", q)

    def force_evaluate(self, job_id: str) -> Tuple[str, QueryMeta]:
        out, meta = self.c.put(f"/v1/job/{urllib.parse.quote(job_id)}/evaluate")
        return out.get("EvalID", ""), meta

    def periodic_force(self, job_id: str):
        return self.c.put(
            f"/v1/job/{urllib.parse.quote(job_id)}/periodic/force")


class Nodes:
    """(reference: api/nodes.go)"""

    def __init__(self, c: Client):
        self.c = c

    def list(self, q: Optional[QueryOptions] = None):
        return self.c.get("/v1/nodes", q)

    def info(self, node_id: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/node/{node_id}", q)

    def allocations(self, node_id: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/node/{node_id}/allocations", q)

    def toggle_drain(self, node_id: str, drain: bool):
        return self.c.put(f"/v1/node/{node_id}/drain",
                          params={"enable": "true" if drain else "false"})

    def force_evaluate(self, node_id: str):
        return self.c.put(f"/v1/node/{node_id}/evaluate")


class Allocations:
    def __init__(self, c: Client):
        self.c = c

    def list(self, q: Optional[QueryOptions] = None):
        return self.c.get("/v1/allocations", q)

    def info(self, alloc_id: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/allocation/{alloc_id}", q)

    def stats(self, alloc_id: str):
        """Live task resource usage from the client agent running the alloc
        (reference: /v1/client/allocation/<id>/stats)."""
        return self.c.get(f"/v1/client/allocation/{alloc_id}/stats")


class Evaluations:
    def __init__(self, c: Client):
        self.c = c

    def list(self, q: Optional[QueryOptions] = None):
        return self.c.get("/v1/evaluations", q)

    def info(self, eval_id: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/evaluation/{eval_id}", q)

    def allocations(self, eval_id: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/evaluation/{eval_id}/allocations", q)


class AllocFS:
    """(reference: api/fs.go)"""

    def __init__(self, c: Client):
        self.c = c

    def list(self, alloc_id: str, path: str = "/"):
        return self.c.request("GET", f"/v1/client/fs/ls/{alloc_id}",
                              {"path": path})[0]

    def stat(self, alloc_id: str, path: str):
        return self.c.request("GET", f"/v1/client/fs/stat/{alloc_id}",
                              {"path": path})[0]

    def cat(self, alloc_id: str, path: str) -> str:
        return self.c.request("GET", f"/v1/client/fs/cat/{alloc_id}",
                              {"path": path})[0]

    def read_at(self, alloc_id: str, path: str, offset: int, limit: int) -> str:
        return self.c.request("GET", f"/v1/client/fs/readat/{alloc_id}",
                              {"path": path, "offset": str(offset),
                               "limit": str(limit)})[0]


class Agent:
    def __init__(self, c: Client):
        self.c = c

    def self(self):
        return self.c.get("/v1/agent/self")[0]

    def members(self):
        return self.c.get("/v1/agent/members")[0]

    def metrics(self):
        """Telemetry snapshot (gauges/counters/samples)."""
        return self.c.get("/v1/agent/metrics")[0]

    def join(self, addresses):
        """(reference: api/agent.go Join)"""
        qs = "&".join("address=" + urllib.parse.quote(a) for a in addresses)
        return self.c.request("PUT", f"/v1/agent/join?{qs}")[0]

    def force_leave(self, node: str):
        """(reference: api/agent.go ForceLeave)"""
        qs = "node=" + urllib.parse.quote(node)
        return self.c.request("PUT", f"/v1/agent/force-leave?{qs}")[0]

    def servers(self):
        return self.c.get("/v1/agent/servers")[0]

    # Fault-injection control (debug-gated; resilience/failpoints.py)
    def faults(self):
        return self.c.get("/v1/agent/debug/faults")[0]

    def arm_faults(self, spec: str):
        return self.c.put("/v1/agent/debug/faults", {"Spec": spec})[0]

    def disarm_faults(self):
        return self.c.delete("/v1/agent/debug/faults")[0]

    def sched_stats(self):
        """Scheduling-pipeline stage timers/counters (debug-gated)."""
        return self.c.get("/v1/agent/debug/sched-stats")[0]

    # Evaluation-lifecycle tracing (debug-gated; telemetry/trace.py)
    def traces(self, limit: Optional[int] = None, after: str = ""):
        """Status + summaries of retained traces. ``limit`` caps the
        page; ``after`` is the TraceID cursor from the previous page's
        ``NextAfter`` (present only when the listing was truncated)."""
        params: Dict[str, str] = {}
        if limit is not None:
            params["limit"] = str(limit)
        if after:
            params["after"] = after
        return self.c.request("GET", "/v1/agent/debug/trace",
                              params or None)[0]

    def trace(self, trace_id: str, chrome: bool = False):
        """One full trace; ``chrome=True`` returns Chrome trace-event
        JSON loadable in Perfetto."""
        params = {"id": trace_id}
        if chrome:
            params["format"] = "chrome"
        return self.c.request("GET", "/v1/agent/debug/trace", params)[0]

    def trace_export(self):
        """Chrome trace-event JSON of every retained trace."""
        return self.c.request("GET", "/v1/agent/debug/trace",
                              {"format": "chrome"})[0]

    def configure_trace(self, enabled=None, sample_ratio=None, ring=None):
        body = {}
        if enabled is not None:
            body["Enabled"] = bool(enabled)
        if sample_ratio is not None:
            body["SampleRatio"] = float(sample_ratio)
        if ring is not None:
            body["Ring"] = int(ring)
        return self.c.put("/v1/agent/debug/trace", body)[0]

    def clear_traces(self):
        return self.c.delete("/v1/agent/debug/trace")[0]


class Services:
    """Service registry queries (/v1/services, /v1/service/<name>)."""

    def __init__(self, c: Client):
        self.c = c

    def list(self, q: Optional[QueryOptions] = None):
        return self.c.get("/v1/services", q)

    def get(self, name: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/service/{urllib.parse.quote(name)}", q)


class Regions:
    def __init__(self, c: Client):
        self.c = c

    def list(self):
        return self.c.get("/v1/regions")[0]


class System:
    def __init__(self, c: Client):
        self.c = c

    def garbage_collect(self):
        return self.c.put("/v1/system/gc")
