"""Typed HTTP API client (reference: api/)."""

from .client import (  # noqa: F401
    APIError,
    Agent as AgentAPI,
    AllocFS,
    Allocations,
    BackpressureAPIError,
    Client,
    Evaluations,
    EventGapAPIError,
    Jobs,
    Nodes,
    QueryOptions,
    Regions,
    System,
    WriteOptions,
)
