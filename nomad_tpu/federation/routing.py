"""Cross-region forwarding, hardened (ISSUE 14 satellite).

The original ``Endpoints._forward_region`` was a single raw
``pool.call`` to one random peer of the target region: a dead WAN link
meant the submitter ate a raw ConnError, a dead peer was re-picked on
every call, and a response lost AFTER delivery could not be retried
safely (a replayed Job.Register would mint a duplicate evaluation).

:class:`RegionForwarder` fixes all three per the repo's resilience
conventions:

- **RetryPolicy** drives the attempt loop (decorrelated jitter, bounded
  attempts) across the region's peer set — a different peer per attempt
  when gossip knows more than one.
- A per-peer **CircuitBreaker** (the rpcproxy quarantine pattern,
  resilience/retry.py) sidelines a dead region server so it costs one
  probe per reset window instead of one timeout per forward.
- Every forwarded WRITE is stamped with a ``ForwardID``; the receiving
  region's :class:`ForwardDedup` replays the stored response for a
  retried ID instead of re-executing the handler — so the ambiguous
  failure (request delivered, response lost on the WAN) retries to
  EXACTLY-ONCE registration, no duplicate evals. The cache is
  in-memory/best-effort by design: it converts the *common* retry race
  into exactly-once; a simultaneous receiving-leader failover falls back
  to at-least-once, which the broker's per-job serialization and the
  duplicate-blocked-eval reaper already tolerate.

Failure seam ``rpc.forward_region`` (KNOWN_SITES): ``error`` = link
failed before the request left (safe retry), ``delay`` = slow WAN hop,
``drop`` = request DELIVERED but the response black-holed — the
ambiguous half that exercises the dedupe path. The chaos schedule in
tests/test_chaos_schedules.py kills a region link mid-forward and
asserts exactly-once registration.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from nomad_tpu.analysis import guarded_by
from nomad_tpu.resilience import failpoints
from nomad_tpu.resilience.retry import Backoff, CircuitBreaker, RetryPolicy
from nomad_tpu.structs import generate_uuid
from nomad_tpu.telemetry import metrics

from .config import FederationConfig

# Writes that may be replayed by a forward retry and must therefore
# dedupe on the receiving side (reads are naturally idempotent).
FORWARD_DEDUPED = frozenset({
    "Job.Register", "Job.Deregister", "Job.Evaluate", "Periodic.Force",
})

# Bounded replay memory on the receiving side. A retry lands within the
# forwarder's attempt loop (seconds); 4096 entries is hours of headroom
# at any realistic cross-region write rate.
_DEDUP_CAP = 4096


class ForwardDedup:
    """Receiving-side replay cache: ForwardID -> stored response.

    Entries are two-state: IN-PROGRESS (the first delivery is still
    executing its handler — a `threading.Event` parks replays) and DONE
    (response stored). The in-progress state closes the race the cache
    exists for: a retry whose original request is STILL running (the WAN
    broke after delivery, the retry landed before the raft apply
    finished) must wait for that execution's answer, not start a second
    concurrent one."""

    _concurrency = guarded_by("_lock", "_seen")

    # Sentinel wrapper so a stored None response is distinguishable from
    # an in-progress event.
    class _Running:
        __slots__ = ("event",)

        def __init__(self):
            self.event = threading.Event()

    def __init__(self, cap: int = _DEDUP_CAP):
        self._lock = threading.Lock()
        self._seen: "OrderedDict[str, Any]" = OrderedDict()
        self._cap = cap

    def begin(self, forward_id: str, timeout: float = 30.0):
        """(hit, response). A miss RESERVES the id — the caller MUST
        resolve it with put() (success) or abort() (handler raised). A
        replay arriving while the original delivery is still executing
        parks until it resolves: put -> replay answers from the cache;
        abort -> the replay takes over the reservation and re-executes
        (the original never committed). A wait past `timeout` raises —
        surfacing an error to the submitter is safe, re-executing a
        possibly-committing write is not."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                if forward_id not in self._seen:
                    self._seen[forward_id] = self._Running()
                    while len(self._seen) > self._cap:
                        # Never evict a running entry: its event is the
                        # replay-parking contract (cap >> plausible
                        # concurrent forwards).
                        oldest = next(iter(self._seen))
                        if isinstance(self._seen[oldest], self._Running):
                            break
                        self._seen.popitem(last=False)
                    return False, None
                entry = self._seen[forward_id]
                if not isinstance(entry, self._Running):
                    self._seen.move_to_end(forward_id)
                    metrics.incr_counter(("nomad", "rpc", "forward",
                                          "dedup_hit"))
                    return True, entry
                waiter = entry.event
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not waiter.wait(remaining):
                raise RuntimeError(
                    f"forward {forward_id} replayed while the original "
                    f"delivery is still executing")

    def get(self, forward_id: str):
        """(hit, response) for a RESOLVED entry — hit distinguishes a
        stored None response; an in-progress entry reads as a miss."""
        with self._lock:
            if forward_id in self._seen:
                entry = self._seen[forward_id]
                if not isinstance(entry, self._Running):
                    self._seen.move_to_end(forward_id)
                    metrics.incr_counter(("nomad", "rpc", "forward",
                                          "dedup_hit"))
                    return True, entry
            return False, None

    def put(self, forward_id: str, response) -> None:
        with self._lock:
            prior = self._seen.get(forward_id)
            self._seen[forward_id] = response
            self._seen.move_to_end(forward_id)
            while len(self._seen) > self._cap:
                oldest = next(iter(self._seen))
                if isinstance(self._seen[oldest], self._Running):
                    break
                self._seen.popitem(last=False)
        if isinstance(prior, self._Running):
            prior.event.set()

    def abort(self, forward_id: str) -> None:
        """Clear a reservation whose handler raised: parked replays wake
        and RE-EXECUTE (nothing committed; at-least-once is correct)."""
        with self._lock:
            prior = self._seen.pop(forward_id, None)
        if isinstance(prior, self._Running):
            prior.event.set()


class NoRegionPathError(Exception):
    """No live, non-quarantined server is known for the target region."""


class RegionForwarder:
    """Retrying, breaker-guarded cross-region RPC forwarding."""

    _concurrency = guarded_by("_lock", "_breakers")

    def __init__(self, pool, route: Callable[[str], List[str]],
                 fed: Optional[FederationConfig] = None):
        """``route(region)`` returns every known live rpc addr of the
        region (the gossip peer table; a static single-addr router wraps
        into a one-element list). Remote-shed health is NOT consulted
        here — the ingress endpoint gates through
        AdmissionController.admit_forward BEFORE calling forward()."""
        self.pool = pool
        self.route = route
        self.fed = fed or FederationConfig()
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}

    def _breaker(self, addr: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(addr)
            if br is None:
                br = self._breakers[addr] = CircuitBreaker(
                    failure_threshold=self.fed.forward_breaker_threshold,
                    reset_timeout=self.fed.forward_breaker_reset_s)
            return br

    def _pick(self, region: str, tried: set) -> Optional[str]:
        """Next candidate: an untried breaker-admitted peer first, then
        a tried-but-admitted one (a transient link error retries the
        SAME peer when the region has only one). None when every known
        peer is quarantined — failing fast IS the breaker working; the
        half-open probe re-admits one call per reset window."""
        addrs = self.route(region) or []
        for addr in addrs:
            if addr not in tried and self._breaker(addr).allow():
                return addr
        for addr in addrs:
            if self._breaker(addr).allow():
                return addr
        return None

    def breaker_state(self, addr: str) -> str:
        return self._breaker(addr).state

    def forward(self, region: str, method: str,
                body: Dict[str, Any]) -> Any:
        """Forward one RPC to a server of ``region``. Writes are stamped
        with a ForwardID (once, surviving retries) so the receiving side
        can dedupe a replay; transport failures retry across peers, remote
        handler errors surface immediately (they ARE the answer)."""
        from nomad_tpu.rpc.pool import ConnError, RPCError

        body = dict(body)
        if method in FORWARD_DEDUPED and not body.get("ForwardID"):
            body["ForwardID"] = generate_uuid()
        tried: set = set()
        t0 = time.monotonic()
        metrics.incr_counter(("nomad", "rpc", "forward", "request"))

        def attempt():
            addr = self._pick(region, tried)
            if addr is None:
                known = self.route(region) or []
                raise NoRegionPathError(
                    f"no path to region {region}"
                    + (f" ({len(known)} peer(s) quarantined)"
                       if known else ""))
            tried.add(addr)
            breaker = self._breaker(addr)
            try:
                act = failpoints.fire("rpc.forward_region")
                if act == "error":
                    # Link failed before the request left: the safe-retry
                    # half of the seam.
                    raise ConnError(
                        f"region link to {addr} failed (failpoint)")
                resp = self.pool.call(addr, method, body)
                if act == "drop":
                    # Request DELIVERED, response black-holed: the
                    # ambiguous WAN failure. The retry replays the same
                    # ForwardID and the receiver's dedupe answers it.
                    raise ConnError(
                        f"region link to {addr} dropped mid-forward "
                        f"(failpoint)")
            except RPCError:
                # The remote handler ran and answered with an error —
                # that IS the forward's result; never retried, and the
                # link itself is healthy.
                breaker.record_success()
                raise
            except (ConnError, OSError, TimeoutError,
                    failpoints.FailpointError):
                breaker.record_failure()
                metrics.incr_counter(("nomad", "rpc", "forward", "retry"))
                raise
            breaker.record_success()
            return resp

        policy = RetryPolicy(
            max_attempts=max(1, self.fed.forward_attempts),
            backoff=Backoff(base=0.01, cap=0.25),
            retry_on=(ConnError, OSError, TimeoutError,
                      failpoints.FailpointError))
        try:
            return policy.call(attempt)
        except NoRegionPathError:
            metrics.incr_counter(("nomad", "rpc", "forward", "fail"))
            raise
        except Exception:
            metrics.incr_counter(("nomad", "rpc", "forward", "fail"))
            raise
        finally:
            metrics.measure_since(("nomad", "rpc", "forward"), t0)
