"""Federated multi-region scheduling (ISSUE 14 / ROADMAP item 3).

Three layers over the single-leader serving pipeline (README
"Federation"):

1. **Follower-snapshot scheduling** (snapshots.py) — workers place
   against staleness-bounded shared snapshots of their LOCAL replica
   instead of all pinning fresh watermarks on the leader's live store;
   the plan applier's optimistic re-verification (plus an explicit
   staleness reject) keeps the Omega model sound across replicas.
2. **Region-local placement + cross-region forwarding** (routing.py) —
   each region is its own raft domain with its own node table and
   TensorIndex; a job whose Region differs forwards at ingress, before
   any raft write, through a retrying/breaker-guarded/deduped WAN hop.
3. **Federated QoS** (qos.py) — per-region tier queues with a polled
   global admission/SLO-burn view, so one region's storm sheds in ITS
   region and cross-region forwards into a shedding region bounce at
   the local edge.

Everything is behind ``ServerConfig(federation=FederationConfig(
enabled=True))``; the default (None) path is bit-identical to the
pre-federation pipeline (tests/test_federation_equivalence.py).
"""

from .config import FederationConfig, federation_enabled  # noqa: F401
from .qos import FederationHealth, health_payload  # noqa: F401
from .routing import (  # noqa: F401
    FORWARD_DEDUPED,
    ForwardDedup,
    NoRegionPathError,
    RegionForwarder,
)
from .snapshots import SnapshotSource, StaleSnapshotError  # noqa: F401
