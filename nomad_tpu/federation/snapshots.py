"""Follower-snapshot scheduling: staleness-bounded snapshots for workers.

The pre-federation pipeline takes a fresh ``state.snapshot()`` per window
per worker — every one a live-store lock round pinning a new MVCC
watermark, all on the leader. The reference's Omega model (PAPER.md:
optimistically-concurrent workers placing against state *snapshots*) says
scheduling READS don't need the live store at all: the plan applier
re-verifies every placement against settled state before commit, so a
worker may place against any snapshot that (a) contains the eval's own
release point and (b) is younger than a staleness bound.

:class:`SnapshotSource` is that bound made concrete. One instance serves
all workers of a server against that server's LOCAL replica — the leader's
own store in dev/leader mode, the follower's replicated store for
distributed workers (whose dequeue RPC already returns a per-eval release
floor instead of the leader's latest index when federation is on, see
EvalBroker.release_floor) — so scheduling reads leave the leader entirely.
A snapshot is shared across windows and workers until it ages past
``max_staleness_s`` or a caller needs a newer watermark; the observed age
is recorded per handout as ``nomad.federation.staleness_ms``.

A plan built from a sourced snapshot carries its birth time
(``plan._fed_born``); the plan applier rejects plans older than
``reject_after_s`` with :class:`StaleSnapshotError` and the worker nacks,
so the broker redelivers the eval exactly once onto a fresh snapshot —
the same machinery killed windows and chaos faults ride.

``pin()`` is the deliberate-staleness test seam: the equivalence gate
pins a pre-aged snapshot to prove the reject/redeliver path end to end.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from nomad_tpu.analysis import guarded_by
from nomad_tpu.telemetry import metrics

from .config import FederationConfig


class StaleSnapshotError(Exception):
    """A plan was built against a snapshot older than the federation
    staleness bound and rejected by the plan applier before verification.
    Retryable by REDELIVERY, not in place: the worker nacks, the broker
    redelivers the eval exactly once, and the re-run dequeues a fresh
    snapshot from the source."""


class SnapshotSource:
    """Shared, staleness-bounded scheduling snapshots over one replica."""

    _concurrency = guarded_by("_lock", "_snap", "_born", "_pinned",
                              "reused", "refreshed")

    def __init__(self, state, fed: FederationConfig,
                 clock=time.monotonic):
        self.state = state
        self.fed = fed
        self.clock = clock
        self._lock = threading.Lock()
        self._snap = None
        self._born: float = 0.0
        # (snapshot, born) pinned by tests to force deliberate staleness;
        # get() serves it unconditionally until unpin().
        self._pinned: Optional[Tuple[object, float]] = None
        self.reused = 0
        self.refreshed = 0

    def get(self, min_index: int = 0) -> Tuple[object, float]:
        """A scheduling snapshot whose watermark covers ``min_index`` and
        whose age is within the staleness bound — shared when possible,
        refreshed otherwise. Returns ``(snapshot, born)``; callers stamp
        ``born`` onto the plans they build from it."""
        with self._lock:
            if self._pinned is not None:
                snap, born = self._pinned
                self._observe(born)
                return snap, born
            now = self.clock()
            snap = self._snap
            if (snap is None
                    or now - self._born > self.fed.max_staleness_s
                    or snap.watermark < min_index):
                self._snap = snap = self.state.snapshot()
                self._born = now
                self.refreshed += 1
                metrics.incr_counter(
                    ("nomad", "federation", "snapshot_refresh"))
            else:
                self.reused += 1
                metrics.incr_counter(
                    ("nomad", "federation", "snapshot_reuse"))
            self._observe(self._born)
            return snap, self._born

    def _observe(self, born: float) -> None:
        metrics.add_sample(("nomad", "federation", "staleness_ms"),
                           (self.clock() - born) * 1e3)

    def pin(self, snap, born: Optional[float] = None) -> None:
        """Test seam: serve exactly this (snapshot, born) until unpin().
        ``born`` defaults to now; pass an old timestamp to simulate a
        worker placing against a snapshot far past the staleness bound."""
        with self._lock:
            self._pinned = (snap, born if born is not None else self.clock())

    def unpin(self) -> None:
        with self._lock:
            self._pinned = None
            # Drop the cache too: the next get() observes fresh state
            # immediately instead of a snapshot predating the pin window.
            self._snap = None

    def invalidate(self) -> None:
        """Drop the cached snapshot (leadership change / restore): the
        next get() re-snapshots the — possibly rebuilt — store."""
        with self._lock:
            self._snap = None

    def stats(self) -> dict:
        with self._lock:
            return {"Reused": self.reused, "Refreshed": self.refreshed,
                    "AgeMs": round((self.clock() - self._born) * 1e3, 2)
                    if self._snap is not None else None,
                    "Pinned": self._pinned is not None}
