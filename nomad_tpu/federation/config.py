"""Federation knobs: one config shared by the snapshot source, the region
forwarder, the broker's region routing, and the admission controller's
global view (README "Federation").

``enabled=False`` (and ``ServerConfig.federation=None``, the default) must
leave the served path bit-identical to the pre-federation behavior — every
consumer guards on :func:`federation_enabled` before touching federation
logic, the same discipline as QoS and the columnar service commits
(tests/test_federation_equivalence.py holds the line).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class FederationConfig:
    """Read-only after boot; shared by broker, workers, applier,
    endpoints, and the admission controller."""

    enabled: bool = False
    # Follower-snapshot scheduling (snapshots.py): False keeps region
    # routing/forwarding/QoS-view on but has every worker pin a fresh
    # live-store watermark per window — the all-on-leader baseline the
    # bench's config7_federation A/B measures the snapshot source
    # against (the ONLY delta between the two sides).
    follower_snapshots: bool = True
    # Staleness bound (seconds) on the shared scheduling snapshot:
    # enforced at DEQUEUE — a worker asking for a snapshot older than
    # this gets a fresh one; younger snapshots are shared across windows
    # and workers instead of each window pinning its own watermark on
    # the live store. Observed per plan as nomad.federation.staleness_ms.
    max_staleness_s: float = 0.25
    # Applier-side hard bound (seconds): a plan built against a snapshot
    # older than this at VERIFY time is rejected outright
    # (StaleSnapshotError) and its eval redelivered through the normal
    # nack machinery — the Omega backstop for a worker that sat on a
    # pinned/wedged snapshot far past the dequeue bound. Must be several
    # multiples of max_staleness_s (a healthy window legitimately ages
    # its snapshot by the dispatch+drain+build pipeline depth); 0
    # disables the applier check.
    reject_after_s: float = 2.0
    # Cross-region forwarding resilience (rpc/endpoints.py via
    # federation/routing.py): attempts across region peers, and the
    # per-peer circuit breaker that quarantines a dead region server so
    # it costs one connect timeout per reset window, not one per call.
    forward_attempts: int = 3
    forward_breaker_threshold: int = 3
    forward_breaker_reset_s: float = 5.0
    # Shed a cross-region forward at the LOCAL edge when the target
    # region's cached health view shows the submission's tier already
    # being shed there (saves the WAN hop; the submitter gets the same
    # typed 429-retryable backpressure the home region would return).
    remote_shed: bool = True
    # Leader-loop poll period for the per-region health view
    # (Federation.Health RPC over the gossip region table).
    health_interval_s: float = 1.0
    # Cached health entries older than this are ignored (a partitioned
    # region must not be shed forever on a stale verdict).
    health_ttl_s: float = 10.0


def federation_enabled(fed: Optional[FederationConfig]) -> bool:
    """The one guard every consumer uses: federation logic only runs
    behind an explicit opt-in, so the disabled path stays bit-identical
    to the pre-federation behavior."""
    return fed is not None and fed.enabled
