"""Federated QoS: the global admission/SLO-burn view (ISSUE 14 layer 3).

Each region runs its own tier queues (its EvalBroker) and its own
admission controller — that isolation IS the headline property: a storm
saturating region A's low tier burns and sheds in region A's broker,
while region B's high tier keeps draining its own queues untouched.

What federation adds on top is a VIEW: every server answers
``Federation.Health`` with its region's per-tier depths, SLO burn, and
whether admission is currently shedding; the leader polls its gossip
region table on a short interval and caches the answers here. Two
consumers:

- **Remote-shed at the forwarding edge** (qos/admission.py
  ``admit_forward``): a cross-region submission whose HOME region is
  already shedding its tier is shed locally with the same typed
  QoSBackpressureError — the client gets its 429-and-retry without the
  WAN hop, and the storm region's ingress never sees the doomed forward.
- **Operator surface**: the sched-stats endpoint reports the whole
  federation's tier health next to the local broker's.

Entries expire after ``health_ttl_s`` — a partitioned region must not be
shed forever on a stale verdict; an expired entry means "assume healthy,
forward, let the home region decide".
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from nomad_tpu.analysis import guarded_by
from nomad_tpu.qos.tiers import N_TIERS

from .config import FederationConfig


class FederationHealth:
    """Cached per-region QoS health, fed by the leader's poll loop (and
    directly by tests/benches that skip gossip)."""

    _concurrency = guarded_by("_lock", "_regions")

    def __init__(self, fed: Optional[FederationConfig] = None,
                 clock=time.monotonic):
        self.fed = fed or FederationConfig()
        self.clock = clock
        self._lock = threading.Lock()
        # region -> (payload dict, stamped monotonic time)
        self._regions: Dict[str, tuple] = {}

    def update(self, region: str, payload: Dict) -> None:
        with self._lock:
            self._regions[region] = (dict(payload), self.clock())

    def get(self, region: str) -> Optional[Dict]:
        """The region's last health payload, or None when unknown or
        older than the TTL (stale = assume healthy)."""
        with self._lock:
            entry = self._regions.get(region)
            if entry is None:
                return None
            payload, stamped = entry
            if self.clock() - stamped > self.fed.health_ttl_s:
                return None
            return dict(payload)

    def snapshot(self) -> Dict[str, Dict]:
        """All live entries plus their age — the sched-stats view."""
        with self._lock:
            now = self.clock()
            return {
                region: {**payload,
                         "AgeS": round(now - stamped, 2),
                         "Stale": now - stamped > self.fed.health_ttl_s}
                for region, (payload, stamped) in self._regions.items()
            }

    def region_shedding(self, region: str, tier: int) -> Optional[str]:
        """Reason string when the region's cached health says a
        submission of ``tier`` would be shed there, else None. Mirrors
        AdmissionController.admit's two rules (depth + higher-tier burn)
        against the REMOTE numbers, so edge and home agree."""
        h = self.get(region)
        if h is None:
            return None
        depths = h.get("TierDepths") or [0] * N_TIERS
        limits = h.get("AdmitDepth") or [0] * N_TIERS
        if tier < len(limits) and limits[tier] \
                and depths[tier] >= limits[tier]:
            return (f"region {region} tier backlog "
                    f"{depths[tier]} >= {limits[tier]}")
        burn = h.get("SLOBurn") or [0.0] * N_TIERS
        burn_shed = h.get("BurnShed", 1.1)
        for higher in range(min(tier, len(burn))):
            if burn[higher] > burn_shed and depths[higher]:
                return (f"region {region} {higher}-tier burning SLO "
                        f"({burn[higher]:.0%})")
        return None


def health_payload(server) -> Dict:
    """One server's Federation.Health answer: its region's tier state in
    the shape region_shedding() consumes. Cheap — broker introspection
    plus two config tuples — and safe on a follower (the broker is just
    empty there; callers poll whichever region peer answers)."""
    broker = server.eval_broker
    qos = server.qos
    payload = {
        "Region": server.config.region,
        "TierDepths": broker.tier_depths(),
        "SLOBurn": [round(b, 4) for b in broker.slo_burn()],
        "QoSEnabled": bool(qos is not None and qos.enabled),
        "Nodes": len(server.tindex.nt.row_of),
    }
    if qos is not None and qos.enabled:
        payload["AdmitDepth"] = list(qos.admit_depth)
        payload["BurnShed"] = qos.burn_shed
    return payload
