"""Checker framework: per-file AST contexts with caching, a checker
registry, and the runner behind both the CLI and the tier-1 gate.

One parse per file per process (keyed on path + mtime/size), shared by
every checker — the lint subcommand and the test gate both complete in
one walk of the tree.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, Iterator, List, Optional, Tuple, Type

import nomad_tpu

from .findings import Finding, is_suppressed, parse_suppressions

PKG_ROOT = os.path.dirname(os.path.abspath(nomad_tpu.__file__))


class FileContext:
    """Parsed view of one source file, cached across runs."""

    __slots__ = ("path", "source", "tree", "allows")

    def __init__(self, path: str, source: str, tree: ast.AST,
                 allows: Dict[int, set]):
        self.path = path
        self.source = source
        self.tree = tree
        self.allows = allows

    def rel(self, root: str = PKG_ROOT) -> str:
        return os.path.relpath(self.path, root)


# (path) -> (mtime_ns, size, FileContext)
_CACHE: Dict[str, Tuple[int, int, FileContext]] = {}


def load_file(path: str) -> Optional[FileContext]:
    """Parse (or fetch from cache) one file; None if it doesn't parse —
    syntax errors are the interpreter's job, not the linter's."""
    path = os.path.abspath(path)
    try:
        st = os.stat(path)
    except OSError:
        return None
    cached = _CACHE.get(path)
    if cached is not None and cached[0] == st.st_mtime_ns \
            and cached[1] == st.st_size:
        return cached[2]
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError):
        return None
    ctx = FileContext(path, source, tree, parse_suppressions(source))
    _CACHE[path] = (st.st_mtime_ns, st.st_size, ctx)
    return ctx


def iter_py_files(root: str) -> Iterator[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


class Checker:
    """Base checker. Subclasses set `id` and implement `check_file`;
    checkers needing cross-file state override `finalize` too."""

    id: str = ""
    description: str = ""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finalize(self, full_tree: bool) -> Iterable[Finding]:
        """Called once after every file; `full_tree` is True when the scan
        covered the whole package (registry-completeness checks only make
        sense there)."""
        return ()


_REGISTRY: List[Type[Checker]] = []


def register(cls: Type[Checker]) -> Type[Checker]:
    _REGISTRY.append(cls)
    return cls


def all_checkers() -> List[Type[Checker]]:
    from . import checkers as _  # noqa: F401  (populate the registry)

    return list(_REGISTRY)


def run_checks(paths: Optional[List[str]] = None,
               checker_ids: Optional[List[str]] = None,
               include_suppressed: bool = False) -> List[Finding]:
    """Run checkers over `paths` (files or directories; default: the
    installed nomad_tpu tree). Suppressed findings are dropped unless
    `include_suppressed`, in which case they carry suppressed=True."""
    full_tree = not paths
    files: List[str] = []
    for p in (paths or [PKG_ROOT]):
        p = os.path.abspath(p)
        if os.path.isdir(p):
            files.extend(iter_py_files(p))
        else:
            files.append(p)

    classes = all_checkers()
    if checker_ids is not None:
        unknown = set(checker_ids) - {c.id for c in classes}
        if unknown:
            raise ValueError(f"unknown checker ids: {sorted(unknown)}")
        classes = [c for c in classes if c.id in checker_ids]
    instances = [cls() for cls in classes]

    raw: List[Finding] = []
    contexts = [ctx for ctx in (load_file(f) for f in files)
                if ctx is not None]
    for checker in instances:
        for ctx in contexts:
            raw.extend(checker.check_file(ctx))
        raw.extend(checker.finalize(full_tree))

    out: List[Finding] = []
    for f in raw:
        ctx = _CACHE.get(os.path.abspath(f.path))
        allows = ctx[2].allows if ctx is not None else {}
        if is_suppressed(allows, f.checker, f.line):
            if include_suppressed:
                f.suppressed = True
                out.append(f)
        else:
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.checker))
    return out
