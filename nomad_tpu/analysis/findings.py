"""Finding objects + the suppression-comment grammar.

A finding is one checker hit at one source location. Suppression is a
trailing comment on the offending line (or the line directly above)::

    # lint: allow(<checker-id>, <free-text reason>)

The reason is mandatory by grammar — an allow() without a reason does
not parse, so every suppression documents itself.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Set, Tuple

_ALLOW_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*([a-z0-9_-]+)\s*,\s*([^)]+?)\s*\)")


@dataclass
class Finding:
    checker: str            # checker id, e.g. "swallow"
    path: str               # absolute path of the offending file
    line: int               # 1-indexed line number
    message: str
    suppressed: bool = False

    def to_dict(self) -> Dict[str, object]:
        return {"checker": self.checker, "path": self.path,
                "line": self.line, "message": self.message,
                "suppressed": self.suppressed}

    def render(self, relative_to: str = "") -> str:
        path = self.path
        if relative_to and path.startswith(relative_to):
            path = path[len(relative_to):].lstrip("/")
        sup = " (suppressed)" if self.suppressed else ""
        return f"{path}:{self.line}: [{self.checker}] {self.message}{sup}"


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map of line number -> checker ids allowed on that line."""
    allows: Dict[int, Set[str]] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        if "lint:" not in text:
            continue
        for m in _ALLOW_RE.finditer(text):
            allows.setdefault(lineno, set()).add(m.group(1))
    return allows


def parse_suppression_details(source: str
                              ) -> List[Tuple[int, str, str]]:
    """Every allow() in `source` as (line, checker id, reason) — the
    purity-boundary audit behind `nomad-tpu lint -suppressions`."""
    out: List[Tuple[int, str, str]] = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        if "lint:" not in text:
            continue
        for m in _ALLOW_RE.finditer(text):
            out.append((lineno, m.group(1), m.group(2)))
    return out


def is_suppressed(allows: Dict[int, Set[str]], checker: str,
                  line: int) -> bool:
    """A finding at `line` is suppressed by an allow() for its checker on
    the same line or the line directly above it."""
    for candidate in (line, line - 1):
        if checker in allows.get(candidate, ()):
            return True
    return False
