"""Zero-runtime-cost concurrency annotations.

These exist for the static checkers (and the human reader): the
``guarded_by`` class-attribute registry declares which mutable fields a
lock protects, and ``requires_lock`` marks a method whose CALLER must
hold the lock (the ``mu must be held`` doc-comment convention of the
reference Go codebase, made machine-checkable). Neither does anything at
runtime — the lint pass reads them syntactically.

Usage::

    class EvalBroker:
        _concurrency = guarded_by(
            "_lock", "_enabled", "_evals", "_unack")

        @requires_lock("_lock")
        def _enqueue_locked(self, ev): ...

Methods whose name ends in ``_locked`` are treated by the checker as if
decorated with ``requires_lock`` for every lock the class registers.
"""

from __future__ import annotations

from typing import Callable, Tuple


class GuardedBy:
    """Declaration that ``fields`` may only be read/written while holding
    ``self.<lock>`` (checked statically; carries no runtime behavior)."""

    __slots__ = ("lock", "fields")

    def __init__(self, lock: str, fields: Tuple[str, ...]):
        self.lock = lock
        self.fields = fields

    def __repr__(self) -> str:
        return f"guarded_by({self.lock!r}, fields={self.fields!r})"


def guarded_by(lock: str, *fields: str) -> GuardedBy:
    return GuardedBy(lock, tuple(fields))


def requires_lock(*locks: str) -> Callable:
    """Decorator marking a method that must be entered with ``self.<lock>``
    already held. Identity at runtime."""

    def deco(fn: Callable) -> Callable:
        return fn

    return deco
