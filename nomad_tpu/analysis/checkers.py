"""The hosted checkers.

Concurrency discipline (the Eraser/lockset lineage, applied statically):

  guarded_by   — fields declared via `guarded_by("_lock", ...)` must only
                 be touched inside `with self.<lock>` in the owning class
                 (methods named *_locked or decorated @requires_lock are
                 lock-held contexts supplied by their caller).
  lock_blocking— blocking primitives (sleep, socket/RPC sends, subprocess,
                 device transfers) lexically inside a lock's `with` body.
  retry        — hand-rolled `time.sleep` retry/poll loops outside
                 nomad_tpu/resilience (use RetryPolicy / Event.wait).
  thread       — `threading.Thread` without a descriptive name=, or a
                 non-daemon thread nobody retains a handle to (unjoinable).
  swallow      — broad `except Exception:` handlers that neither log,
                 re-raise, fire a failpoint, nor carry a suppression.

Telemetry key discipline (migrated from tests/test_telemetry_lint.py):

  failpoint_site — every fired failpoint literal declared in KNOWN_SITES
                   and (full-tree scans only) vice versa.
  metric_key     — metric key literals follow the nomad.* dotted scheme.
  trace_key      — span name literals follow the subsystem.operation
                   scheme.
  event_schema   — event topic/type literals exist in the events schema
                   registry and agree with each other.

Replica determinism:

  apply_pure     — call-graph closure from the FSM apply handlers,
                   StateStore mutators, Restore, and the event builders
                   must not reach the nondeterminism taxonomy (wall
                   clock, randomness, process identity, unordered set
                   iteration, thread spawns, I/O); declared local-only
                   sites carry `# lint: allow(apply_pure, <reason>)`.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .findings import Finding
from .framework import Checker, FileContext, PKG_ROOT, register

# Attribute / variable names that look like a mutual-exclusion primitive.
_LOCKISH_RE = re.compile(r"(lock|cond|mutex|mtx|mu)$")


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _receiver(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return func.value.id
    return ""


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' when node is the expression `self.X`, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _with_lock_names(node: ast.With) -> List[str]:
    """Lock-ish names acquired by a `with` statement: `self.X` items and
    bare-name items whose name looks like a lock."""
    out = []
    for item in node.items:
        expr = item.context_expr
        attr = _self_attr(expr)
        if attr is not None and _LOCKISH_RE.search(attr):
            out.append(attr)
        elif isinstance(expr, ast.Name) and _LOCKISH_RE.search(expr.id):
            out.append(expr.id)
    return out


# --------------------------------------------------------------- guarded_by
@register
class GuardedByChecker(Checker):
    id = "guarded_by"
    description = ("access to a guarded_by()-declared field outside a "
                   "`with self.<lock>` block in the owning class")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(ctx, node))
        return findings

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        # field -> lock guarding it, from guarded_by() class attributes.
        guarded: Dict[str, str] = {}
        for stmt in cls.body:
            if not isinstance(stmt, ast.Assign) \
                    or not isinstance(stmt.value, ast.Call) \
                    or _call_name(stmt.value) != "guarded_by":
                continue
            args = [a.value for a in stmt.value.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str)]
            if len(args) >= 2:
                for f in args[1:]:
                    guarded[f] = args[0]
        if not guarded:
            return ()
        all_locks = frozenset(guarded.values())

        findings: List[Finding] = []
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name in ("__init__", "__del__"):
                continue  # construction/teardown precede or outlive sharing
            held = self._initial_held(stmt, all_locks)
            for sub in stmt.body:
                self._scan(sub, held, guarded, cls.name, ctx, findings)
        return findings

    @staticmethod
    def _initial_held(fn, all_locks: FrozenSet[str]) -> FrozenSet[str]:
        held: Set[str] = set()
        if fn.name.endswith("_locked"):
            held |= all_locks
        for deco in fn.decorator_list:
            if isinstance(deco, ast.Call) \
                    and _call_name(deco) == "requires_lock":
                held |= {a.value for a in deco.args
                         if isinstance(a, ast.Constant)
                         and isinstance(a.value, str)}
        return frozenset(held)

    def _scan(self, node: ast.AST, held: FrozenSet[str],
              guarded: Dict[str, str], cls_name: str, ctx: FileContext,
              findings: List[Finding]) -> None:
        if isinstance(node, ast.With):
            acquired = frozenset(a for item in node.items
                                 for a in [_self_attr(item.context_expr)]
                                 if a is not None)
            for item in node.items:
                self._scan(item.context_expr, held, guarded, cls_name, ctx,
                           findings)
            inner = held | acquired
            for sub in node.body:
                self._scan(sub, inner, guarded, cls_name, ctx, findings)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested function: lexical position doesn't prove the lock is
            # held when it eventually runs — restart from its own markers.
            inner = self._initial_held(node, frozenset(guarded.values()))
            for sub in node.body:
                self._scan(sub, inner, guarded, cls_name, ctx, findings)
            return
        if isinstance(node, ast.ClassDef):
            return
        attr = _self_attr(node)
        if attr is not None and attr in guarded \
                and guarded[attr] not in held:
            findings.append(Finding(
                self.id, ctx.path, node.lineno,
                f"{cls_name}.{attr} is guarded by self.{guarded[attr]} "
                f"but accessed without holding it"))
            # fall through: still scan children (subscripts etc.)
        for child in ast.iter_child_nodes(node):
            self._scan(child, held, guarded, cls_name, ctx, findings)


# ------------------------------------------------------------ lock_blocking
_BLOCKING_RECEIVER_CALLS = {
    # receiver name -> blocked methods
    "time": {"sleep"}, "_time": {"sleep"},
    "subprocess": {"run", "Popen", "call", "check_call", "check_output"},
}
# Method names that block on the network regardless of receiver.
_BLOCKING_METHODS = {"sendall", "sendto", "recv", "recvfrom", "accept",
                     "connect", "send_frame", "recv_frame", "device_get"}
# Bare function names that block.
_BLOCKING_NAMES = {"send_frame", "recv_frame", "device_get"}


@register
class BlockingUnderLockChecker(Checker):
    id = "lock_blocking"
    description = ("blocking call (sleep / socket send / subprocess / "
                   "device transfer) lexically inside a lock's with body")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.With) and _with_lock_names(node):
                lock = _with_lock_names(node)[0]
                for sub in node.body:
                    self._scan(sub, lock, ctx, findings)
        return findings

    def _scan(self, node: ast.AST, lock: str, ctx: FileContext,
              findings: List[Finding]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # runs later, not necessarily under the lock
        if isinstance(node, ast.Call):
            name = _call_name(node)
            recv = _receiver(node)
            blocked = (name in _BLOCKING_RECEIVER_CALLS.get(recv, ())
                       or (isinstance(node.func, ast.Attribute)
                           and name in _BLOCKING_METHODS
                           and not _LOCKISH_RE.search(recv))
                       or (isinstance(node.func, ast.Name)
                           and name in _BLOCKING_NAMES))
            if blocked:
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    f"blocking call {recv + '.' if recv else ''}{name}() "
                    f"inside `with self.{lock}` — move it outside the "
                    f"critical section"))
        for child in ast.iter_child_nodes(node):
            self._scan(child, lock, ctx, findings)


# -------------------------------------------------------------------- retry
@register
class HandRolledRetryChecker(Checker):
    id = "retry"
    description = ("time.sleep inside a loop outside nomad_tpu/resilience "
                   "— use RetryPolicy or a shutdown-aware Event.wait")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        rel = ctx.rel()
        if rel.startswith("resilience" + os.sep):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.While, ast.For)):
                continue
            for sub in node.body + node.orelse:
                self._scan(sub, ctx, findings)
        return findings

    def _scan(self, node: ast.AST, ctx: FileContext,
              findings: List[Finding]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.While, ast.For)):
            return  # the outer ast.walk visits nested loops itself —
            #         descending here would double-report their sleeps
        if isinstance(node, ast.Call):
            name = _call_name(node)
            recv = _receiver(node)
            if name == "sleep" and (recv in ("time", "_time")
                                    or isinstance(node.func, ast.Name)):
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    "hand-rolled sleep loop — use resilience.retry."
                    "RetryPolicy (or a shutdown Event's .wait for pacing)"))
        for child in ast.iter_child_nodes(node):
            self._scan(child, ctx, findings)


# ------------------------------------------------------------------- thread
@register
class ThreadLifecycleChecker(Checker):
    id = "thread"
    description = ("threading.Thread without name=, or a non-daemon "
                   "thread with no retained handle to join")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        assigned_calls: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and self._is_thread_call(node.value):
                assigned_calls.add(id(node.value))
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and self._is_thread_call(node)):
                continue
            kwargs = {kw.arg for kw in node.keywords}
            if "name" not in kwargs:
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    "thread spawned without name= — SIGUSR1 dumps and "
                    "trace events cannot attribute it"))
            daemon = next((kw.value for kw in node.keywords
                           if kw.arg == "daemon"), None)
            is_daemon = (isinstance(daemon, ast.Constant)
                         and daemon.value is True)
            if not is_daemon and id(node) not in assigned_calls:
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    "non-daemon thread with no retained handle — nothing "
                    "can join it (assign it, or pass daemon=True)"))
        return findings

    @staticmethod
    def _is_thread_call(node: ast.Call) -> bool:
        func = node.func
        if isinstance(func, ast.Attribute):
            return (func.attr == "Thread"
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "threading")
        return isinstance(func, ast.Name) and func.id == "Thread"


# ------------------------------------------------------------------ swallow
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}


@register
class SilentSwallowChecker(Checker):
    id = "swallow"
    description = ("broad except handler that neither logs, re-raises, "
                   "nor fires a failpoint")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if self._handles(node):
                continue
            findings.append(Finding(
                self.id, ctx.path, node.lineno,
                "broad except swallows the error silently — log it at "
                "debug with context, or mark intent with "
                "`# lint: allow(swallow, <reason>)`"))
        return findings

    @staticmethod
    def _is_broad(type_node: Optional[ast.AST]) -> bool:
        def broad(n: ast.AST) -> bool:
            return isinstance(n, ast.Name) and n.id in ("Exception",
                                                        "BaseException")
        if type_node is None:
            return True
        if broad(type_node):
            return True
        if isinstance(type_node, ast.Tuple):
            return any(broad(e) for e in type_node.elts)
        return False

    @staticmethod
    def _handles(handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name in _LOG_METHODS \
                        and isinstance(node.func, ast.Attribute):
                    return True
                if name in ("print", "fire"):
                    return True
        return False


# ----------------------------------------------------------- failpoint_site
@register
class FailpointSiteChecker(Checker):
    id = "failpoint_site"
    description = ("failpoints.fire() literals must be declared in "
                   "KNOWN_SITES, and declared sites must still fire "
                   "somewhere in the tree")

    def __init__(self) -> None:
        self._fired: Dict[str, Tuple[str, int]] = {}

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        from nomad_tpu.resilience import failpoints

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) \
                    or _call_name(node) != "fire":
                continue
            if _receiver(node) not in ("failpoints", ""):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                site = node.args[0].value
                self._fired.setdefault(site, (ctx.path, node.lineno))
                if site not in failpoints.KNOWN_SITES:
                    findings.append(Finding(
                        self.id, ctx.path, node.lineno,
                        f"failpoint site {site!r} fired here but not "
                        f"declared in failpoints.KNOWN_SITES"))
        return findings

    def finalize(self, full_tree: bool) -> Iterable[Finding]:
        if not full_tree:
            return ()  # partial scans can't prove a site never fires
        from nomad_tpu.resilience import failpoints

        fp_path = os.path.abspath(failpoints.__file__)
        try:
            with open(fp_path, encoding="utf-8") as f:
                fp_lines = f.read().splitlines()
        except OSError:
            fp_lines = []
        findings = []
        for site in sorted(set(failpoints.KNOWN_SITES) - set(self._fired)):
            line = next((i for i, text in enumerate(fp_lines, start=1)
                         if f'"{site}"' in text), 1)
            findings.append(Finding(
                self.id, fp_path, line,
                f"KNOWN_SITES declares {site!r} but no source location "
                f"fires it (renamed seam?)"))
        return findings


# --------------------------------------------------------------- metric_key
_METRIC_FNS = {"set_gauge", "incr_counter", "add_sample", "measure",
               "measure_since"}
_SEGMENT_RE = re.compile(r"^[a-z0-9_]+$")


@register
class MetricKeyChecker(Checker):
    id = "metric_key"
    description = "metric key literals must follow the nomad.* scheme"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) \
                    or _call_name(node) not in _METRIC_FNS:
                continue
            if _receiver(node) not in ("metrics", "telemetry", "registry",
                                       "reg", ""):
                continue
            if not node.args or not isinstance(node.args[0], ast.Tuple):
                continue
            elts = node.args[0].elts
            consts = [e.value for e in elts
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, str)]
            if not consts:
                continue
            if isinstance(elts[0], ast.Constant) and consts[0] != "nomad":
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    f"metric key {tuple(consts)}: first segment must be "
                    f"'nomad'"))
                continue
            # Dynamic trailing segments (ev.Type, RPC method names) are
            # exempt; every CONSTANT segment must match the scheme.
            for seg in consts:
                if seg != "nomad" and not all(
                        _SEGMENT_RE.match(p) for p in seg.split(".")):
                    findings.append(Finding(
                        self.id, ctx.path, node.lineno,
                        f"metric key {tuple(consts)}: segment {seg!r} "
                        f"breaks [a-z0-9_]"))
                    break
        return findings


# ---------------------------------------------------------------- trace_key
_TRACE_SPAN_FNS = {"span", "root_span", "resume", "start_from"}
_SPAN_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[A-Za-z][A-Za-z0-9_]*)+$")


@register
class TraceKeyChecker(Checker):
    id = "trace_key"
    description = ("trace span name literals must follow the "
                   "subsystem.operation scheme")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel() == os.path.join("telemetry", "trace.py"):
            return ()  # the implementation's docstrings/internals
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name_arg = None
            fn = _call_name(node)
            recv = _receiver(node)
            if recv not in ("trace", "_trace"):
                continue
            if fn in _TRACE_SPAN_FNS:
                # span(name)/root_span(name) take name first;
                # resume/start_from take (carrier, name).
                idx = 0 if fn in ("span", "root_span") else 1
                if len(node.args) > idx:
                    name_arg = node.args[idx]
            elif fn == "record_span" and len(node.args) > 1:
                name_arg = node.args[1]
            if name_arg is None or not isinstance(name_arg, ast.Constant) \
                    or not isinstance(name_arg.value, str):
                continue  # dynamic names ("rpc." + method) are exempt
            if not _SPAN_NAME_RE.match(name_arg.value):
                findings.append(Finding(
                    self.id, ctx.path, node.lineno,
                    f"span name {name_arg.value!r} breaks the "
                    f"subsystem.operation scheme"))
        return findings


# --------------------------------------------------------------- event_schema
@register
class EventSchemaChecker(Checker):
    id = "event_schema"
    description = ("event topic/type literals must exist in the events "
                   "schema registry (TOPICS / EVENT_TYPES) and agree "
                   "with each other")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel() == os.path.join("events", "schema.py"):
            return ()  # the registry itself defines the literals
        findings: List[Finding] = []
        from nomad_tpu.events.schema import EVENT_TYPES, TOPICS

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and _call_name(node) == "new_event":
                # new_event(topic, etype, ...): both literals must be
                # registered, and the type must publish on that topic.
                # Dynamic args (rebroadcast of an existing event) are
                # exempt — the constructor re-validates at runtime.
                lits = [a.value if isinstance(a, ast.Constant)
                        and isinstance(a.value, str) else None
                        for a in node.args[:2]]
                topic, etype = (lits + [None, None])[:2]
                if topic is not None and topic not in TOPICS:
                    findings.append(Finding(
                        self.id, ctx.path, node.lineno,
                        f"event topic {topic!r} is not declared in "
                        f"events.schema.TOPICS"))
                elif etype is not None and etype not in EVENT_TYPES:
                    findings.append(Finding(
                        self.id, ctx.path, node.lineno,
                        f"event type {etype!r} is not declared in "
                        f"events.schema.EVENT_TYPES"))
                elif topic is not None and etype is not None \
                        and EVENT_TYPES[etype] != topic:
                    findings.append(Finding(
                        self.id, ctx.path, node.lineno,
                        f"event type {etype!r} publishes on topic "
                        f"{EVENT_TYPES[etype]!r}, not {topic!r}"))
            elif isinstance(node, ast.Compare):
                # `ev["Topic"] == "X"` routing comparisons: the literal
                # side must name a real topic (a renamed topic would
                # otherwise make the branch silently dead).
                sides = [node.left] + list(node.comparators)
                if not any(
                        isinstance(s, ast.Subscript)
                        and isinstance(s.slice, ast.Constant)
                        and s.slice.value == "Topic" for s in sides):
                    continue
                for side in sides:
                    if isinstance(side, ast.Constant) \
                            and isinstance(side.value, str) \
                            and side.value not in TOPICS:
                        findings.append(Finding(
                            self.id, ctx.path, node.lineno,
                            f"comparison against unknown event topic "
                            f"{side.value!r}"))
        return findings


# ----------------------------------------------------------------- apply_pure
@register
class ApplyPurityChecker(Checker):
    id = "apply_pure"
    description = ("nondeterministic call (wall clock, randomness, "
                   "process identity, unordered set iteration, threads, "
                   "I/O) reachable from the replicated apply path")

    def __init__(self) -> None:
        from .callgraph import CallGraph

        self._graph = CallGraph()

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        # Whole-graph analysis: files accumulate here, findings land in
        # finalize once reachability is known.
        self._graph.add_file(ctx)
        return ()

    def finalize(self, full_tree: bool) -> Iterable[Finding]:
        findings: List[Finding] = []
        for imp in self._graph.impurities():
            hops = " -> ".join(imp.chain)
            findings.append(Finding(
                self.id, imp.path, imp.lineno,
                f"{imp.category}: {imp.label} reachable from the apply "
                f"path via {hops} — replicas diverge; make it a "
                f"function of the entry, or mark the site local-only "
                f"with `# lint: allow(apply_pure, <reason>)`"))
        return findings
