"""Runtime lock-order detector (the dynamic half of the concurrency
pass; lineage: Eraser's lockset discipline, ThreadSanitizer's dynamic
annotations, the kernel's lockdep lock-class graph).

``install()`` — wired by ``NOMAD_TPU_DEBUG_LOCKS=1`` through
tests/conftest.py — swaps ``threading.Lock``/``threading.RLock`` for
:class:`DebugLock`/:class:`DebugRLock`. Every lock constructed AFTER the
swap is identified by its construction site (file:line — the lockdep
"lock class": all instances from one site share one identity, so an
A->B/B->A inversion is caught even across distinct object pairs). The
wrappers maintain:

* a per-thread stack of held locks,
* a process-wide ordering graph (edges: "held A while acquiring B");
  a new edge whose reverse is already reachable is a potential deadlock
  and reports a ``lock_order_inversion``,
* per-acquisition hold timing; holds over ``NOMAD_TPU_LOCK_HOLD_MS``
  (default 500) report a ``long_hold``,
* a patched ``time.sleep`` that reports ``blocking_under_lock`` when
  called with any lock held.

Findings are appended to an in-process list (:func:`runtime_findings`),
logged at WARNING, counted on ``nomad.analysis.<kind>`` metrics, and —
when tracing is active — attached to the current span as an
``analysis.<kind>`` event. Nothing raises into the instrumented path.

Default-off: with the env var unset nothing is patched and the cost is
zero.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

LOG = logging.getLogger("nomad.analysis.locks")

ENV_VAR = "NOMAD_TPU_DEBUG_LOCKS"
HOLD_THRESHOLD_MS_VAR = "NOMAD_TPU_LOCK_HOLD_MS"


@dataclass
class RuntimeFinding:
    kind: str                    # lock_order_inversion | long_hold |
    #                              blocking_under_lock
    detail: str
    locks: Tuple[str, ...]
    thread: str
    when: float = field(default_factory=time.monotonic)

    def __str__(self) -> str:
        return (f"[{self.kind}] {self.detail} "
                f"(locks={list(self.locks)}, thread={self.thread})")


# Saved originals (populated by install()).
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_SLEEP = time.sleep

_installed = False
_tls = threading.local()

# Module state guarded by _state_lock (always a REAL lock, never a
# DebugLock — the detector must not watch itself).
_state_lock = threading.Lock()
_order: Dict[str, Set[str]] = {}           # site -> sites acquired under it
_edge_seen: Set[Tuple[str, str]] = set()
_findings: List[RuntimeFinding] = []
_MAX_FINDINGS = 1024


def _read_hold_threshold() -> float:
    try:
        return float(os.environ.get(HOLD_THRESHOLD_MS_VAR, "500")) / 1000.0
    except ValueError:
        return 0.5


# Cached at import and refreshed by install(): _pop runs on EVERY lock
# release, and an os.environ lookup + float parse there would inflate the
# very hold times being measured. Tests override via monkeypatch.setattr.
hold_threshold_s = _read_hold_threshold()


def _hold_threshold() -> float:
    return hold_threshold_s


def _held() -> List[Tuple[Any, float]]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _caller_site() -> str:
    """file:line of the frame that constructed the lock, skipping this
    module and threading internals — the lock's 'class' identity."""
    import sys

    frame = sys._getframe(2)
    here = os.path.dirname(os.path.abspath(__file__))
    while frame is not None:
        fn = frame.f_code.co_filename
        if not fn.startswith(here) and "threading" not in fn:
            rel = os.path.basename(os.path.dirname(fn)) + "/" \
                + os.path.basename(fn)
            return f"{rel}:{frame.f_lineno}"
        frame = frame.f_back
    return "<unknown>"


def _report(kind: str, detail: str, locks: Tuple[str, ...]) -> None:
    if getattr(_tls, "reporting", False):
        return  # a finding raised while reporting a finding: drop it
    _tls.reporting = True
    try:
        finding = RuntimeFinding(kind, detail, locks,
                                 threading.current_thread().name)
        with _state_lock:
            if len(_findings) < _MAX_FINDINGS:
                _findings.append(finding)
        LOG.warning("debug-locks: %s", finding)
        try:
            from nomad_tpu.telemetry import metrics, trace

            metrics.incr_counter(("nomad", "analysis", kind), 1)
            trace.add_event(f"analysis.{kind}", detail=detail,
                            locks=",".join(locks))
        # lint: allow(swallow, detector must never raise into the watched path)
        except Exception:
            pass
    finally:
        _tls.reporting = False


def runtime_findings(kind: Optional[str] = None) -> List[RuntimeFinding]:
    with _state_lock:
        out = list(_findings)
    return [f for f in out if kind is None or f.kind == kind]


def clear_findings() -> None:
    with _state_lock:
        _findings.clear()
        _order.clear()
        _edge_seen.clear()


def _reachable(frm: str, to: str) -> bool:
    """DFS over the ordering graph; caller holds _state_lock."""
    seen: Set[str] = set()
    stack = [frm]
    while stack:
        cur = stack.pop()
        if cur == to:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(_order.get(cur, ()))
    return False


def _note_acquire(lock: "DebugLock") -> None:
    """Record ordering edges BEFORE blocking on the inner acquire — the
    point of a deadlock detector is to fire on the attempt."""
    held = _held()
    if not held:
        return
    for other, _t0 in held:
        a, b = other.name, lock.name
        if a == b:
            continue
        with _state_lock:
            if (a, b) in _edge_seen:
                continue
            inversion = _reachable(b, a)
            _edge_seen.add((a, b))
            _order.setdefault(a, set()).add(b)
        if inversion:
            _report("lock_order_inversion",
                    f"acquiring {b} while holding {a}, but the reverse "
                    f"order was also observed (potential deadlock)",
                    (a, b))


def _push(lock: "DebugLock") -> None:
    _held().append((lock, time.monotonic()))


def _pop(lock: "DebugLock") -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lock:
            _, t0 = held.pop(i)
            dur = time.monotonic() - t0
            if dur > _hold_threshold():
                _report("long_hold",
                        f"{lock.name} held for {dur * 1e3:.0f}ms "
                        f"(threshold {_hold_threshold() * 1e3:.0f}ms)",
                        (lock.name,))
            return


class DebugLock:
    """Instrumented stand-in for ``threading.Lock``."""

    __slots__ = ("_inner", "name")

    def __init__(self, name: Optional[str] = None):
        self._inner = _REAL_LOCK()
        self.name = name or _caller_site()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            _note_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _push(self)
        return ok

    def release(self) -> None:
        _pop(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib fork handlers (concurrent.futures, threading) re-arm
        # module locks in the child through this hook.
        self._inner._at_fork_reinit()
        _tls.__dict__.clear()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name}>"


class DebugRLock:
    """Instrumented stand-in for ``threading.RLock``. Only the outermost
    acquire/release touches the held stack; the ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` trio keeps ``Condition.wait``
    honest about what is really held while waiting."""

    __slots__ = ("_inner", "name")

    def __init__(self, name: Optional[str] = None):
        self._inner = _REAL_RLOCK()
        self.name = name or _caller_site()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        first = not self._inner._is_owned()
        if blocking and first:
            _note_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok and first:
            _push(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        if not self._inner._is_owned():
            _pop(self)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # Condition integration: wait() fully releases via _release_save.
    def _release_save(self) -> Any:
        _pop(self)
        return self._inner._release_save()

    def _acquire_restore(self, state: Any) -> None:
        self._inner._acquire_restore(state)
        _push(self)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()
        _tls.__dict__.clear()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name}>"


def _checked_sleep(secs: float) -> None:
    held = _held()
    if held and not getattr(_tls, "reporting", False):
        names = tuple(lk.name for lk, _ in held)
        _report("blocking_under_lock",
                f"time.sleep({secs!r}) while holding {', '.join(names)}",
                names)
    _REAL_SLEEP(secs)


def install() -> None:
    """Swap the threading lock factories + time.sleep. Idempotent. Locks
    constructed BEFORE install (import-time singletons) stay raw — the
    detector watches the per-object locks the system creates at runtime."""
    global _installed, _REAL_LOCK, _REAL_RLOCK, _REAL_SLEEP
    if _installed:
        return
    _REAL_LOCK = threading.Lock
    _REAL_RLOCK = threading.RLock
    _REAL_SLEEP = time.sleep
    global hold_threshold_s
    hold_threshold_s = _read_hold_threshold()
    threading.Lock = DebugLock          # type: ignore[assignment]
    threading.RLock = DebugRLock        # type: ignore[assignment]
    time.sleep = _checked_sleep         # type: ignore[assignment]
    _installed = True
    LOG.info("debug-locks: installed (hold threshold %.0fms)",
             _hold_threshold() * 1e3)


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _REAL_LOCK         # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK       # type: ignore[assignment]
    time.sleep = _REAL_SLEEP            # type: ignore[assignment]
    _installed = False


def installed() -> bool:
    return _installed


def install_from_env() -> bool:
    if os.environ.get(ENV_VAR, "") == "1":
        install()
        return True
    return False
