"""Cross-replica state-digest verification (the runtime half of the
replica-determinism contract; the static half is callgraph.py).

Every successful FSM apply folds a canonical encoding of
(index, msg_type, mutation effect) into a rolling blake2b HASH CHAIN:

    chain_i = blake2b(chain_{i-1} || encode(index, type, effect))

A chain VALUE is the whole history in 16 bytes, and — unlike a live
hasher object — it is transferable: snapshots carry the chain value at
their watermark, so a freshly-installed follower reseeds and keeps
folding, and the chain stays CANONICAL (the value at index i is the
same whether a replica replayed the full log from genesis or restored
any intermediate snapshot).

The "effect" is a cheap canonical READBACK of what the entry changed
(node/eval/alloc ids + statuses re-read from the store after the
handler ran) — readback is what makes real store corruption visible,
not just payload divergence. Columnar ApplySweepBatch entries digest
their column arrays directly (ids, rows, delta — dtype/shape/tobytes),
never materializing a row.

Every `interval` folds the chain value is recorded as a checkpoint.
The leader piggybacks its latest checkpoint on AppendEntries; a
follower that folded the same index compares and, on mismatch, raises
the typed :class:`ReplicaDivergenceError`, bumps
``nomad.fsm.digest.diverged``, and is quarantined by the raft layer to
snapshot-reinstall recovery. Dev mode folds (the bench measures the
cost and sched-stats shows the chain) but never exchanges — concurrent
dev applies can fold out of index order, which is harmless because
nothing compares the value.

Stats keys: ``nomad.fsm.digest.{folds,exchanged,diverged,verify_ms}``.
"""

from __future__ import annotations

import hashlib
import struct
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from nomad_tpu.telemetry import metrics

# Digest width: 16 bytes is plenty for corruption detection (this is an
# integrity chain, not an adversarial MAC) and halves the snapshot /
# RPC footprint vs blake2b's default 64.
_DIGEST_SIZE = 16
_GENESIS = b"\x00" * _DIGEST_SIZE

# How many recent checkpoints a replica retains for verification. The
# leader only ever piggybacks its newest one; a handful of buckets of
# slack covers followers that lag a few heartbeats behind.
_CHECKPOINT_KEEP = 8


class ReplicaDivergenceError(Exception):
    """A follower's state digest disagrees with the leader's at the same
    applied index: this replica's FSM is no longer a function of the
    log. The raft layer quarantines the replica to snapshot-reinstall
    recovery when this surfaces."""

    def __init__(self, index: int, expected: str, actual: str):
        super().__init__(
            f"replica state digest diverged at index {index}: "
            f"leader={expected} local={actual}")
        self.index = index
        self.expected = expected
        self.actual = actual


# ------------------------------------------------------ canonical encoding
def _fold_obj(h, obj: Any) -> None:
    """Fold one value with unambiguous type tags. Dicts fold in sorted
    key order; ndarrays fold dtype/shape/raw bytes (no materialization,
    no Python-object hashing — nothing process-local)."""
    if obj is None:
        h.update(b"N")
    elif obj is True:
        h.update(b"T")
    elif obj is False:
        h.update(b"F")
    elif isinstance(obj, int):
        h.update(b"I" + str(obj).encode())
    elif isinstance(obj, float):
        h.update(b"D" + struct.pack("<d", obj))
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        h.update(b"S" + str(len(b)).encode() + b":")
        h.update(b)
    elif isinstance(obj, bytes):
        h.update(b"B" + str(len(obj)).encode() + b":")
        h.update(obj)
    elif isinstance(obj, np.ndarray):
        h.update(b"A" + str(obj.dtype).encode() + b"|"
                 + str(obj.shape).encode() + b"|")
        h.update(np.ascontiguousarray(obj).tobytes())
    elif isinstance(obj, (list, tuple)):
        h.update(b"L" + str(len(obj)).encode() + b":")
        for item in obj:
            _fold_obj(h, item)
    elif isinstance(obj, dict):
        h.update(b"M" + str(len(obj)).encode() + b":")
        for key in sorted(obj):
            _fold_obj(h, key)
            _fold_obj(h, obj[key])
    else:
        # Unknown leaf (an already-constructed struct riding a dev-mode
        # payload): fold its type name only — replicated entries are
        # always plain msgpack types, so this never reaches exchange.
        h.update(b"O" + type(obj).__name__.encode())


class ReplicaDigest:
    """Rolling apply-effect hash chain with bounded checkpoints."""

    def __init__(self, interval: int = 64):
        self.interval = max(1, int(interval))
        self._lock = threading.Lock()
        self._chain = _GENESIS
        self._last_index = 0
        self._bucket = 0            # last checkpointed index // interval
        self._checkpoints: "OrderedDict[int, str]" = OrderedDict()
        self._verified_index = 0    # newest index already compared
        self._synced = True         # False: fold but never verify
        self._unsynced_reason = ""
        self._folds = 0
        self._exchanged = 0
        self._diverged = 0

    # ------------------------------------------------------------- folding
    def fold(self, index: int, msg_type: int, effect: Any) -> None:
        """Fold one applied entry's effect into the chain. Called with
        the apply path serialized (raft's FSM lock / DevRaft callers);
        the internal lock only protects readers on other threads."""
        h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
        with self._lock:
            h.update(self._chain)
            _fold_obj(h, index)
            _fold_obj(h, msg_type)
            _fold_obj(h, effect)
            self._chain = h.digest()
            self._last_index = index
            self._folds += 1
            bucket = index // self.interval
            if bucket > self._bucket:
                self._bucket = bucket
                self._checkpoints[index] = self._chain.hex()
                while len(self._checkpoints) > _CHECKPOINT_KEEP:
                    self._checkpoints.popitem(last=False)
        metrics.incr_counter(("nomad", "fsm", "digest", "folds"))

    # ------------------------------------------------------------ exchange
    def checkpoint(self) -> Optional[Tuple[int, str]]:
        """Newest (index, chain hex) checkpoint — what the leader
        piggybacks on AppendEntries. None until `interval` applies."""
        with self._lock:
            if not self._checkpoints or not self._synced:
                return None
            index = next(reversed(self._checkpoints))
            return index, self._checkpoints[index]

    def verify(self, index: int, expected_hex: str) -> Optional[bool]:
        """Compare the leader's checkpoint against ours at `index`.

        Returns True on a real match, None when there is nothing to
        compare (not folded that far, checkpoint aged out, already
        verified, or this replica is unsynced) — and raises
        ReplicaDivergenceError on mismatch.
        """
        t0 = time.monotonic()
        with self._lock:
            if not self._synced or index <= self._verified_index:
                return None
            mine = self._checkpoints.get(index)
            if mine is None:
                return None
            self._verified_index = index
            self._exchanged += 1
            ok = mine == expected_hex
            if not ok:
                self._diverged += 1
        metrics.incr_counter(("nomad", "fsm", "digest", "exchanged"))
        metrics.measure_since(("nomad", "fsm", "digest", "verify_ms"), t0)
        if not ok:
            metrics.incr_counter(("nomad", "fsm", "digest", "diverged"))
            raise ReplicaDivergenceError(index, expected_hex, mine)
        return True

    # ----------------------------------------------------- snapshot seams
    def snapshot_state(self) -> Dict[str, Any]:
        """Chain value pinned for a snapshot (capture under the same
        lock discipline as the FSM pin so it matches the watermark)."""
        with self._lock:
            return {"index": self._last_index,
                    "digest": self._chain.hex()}

    def reseed(self, index: int, digest_hex: str) -> None:
        """Adopt a snapshot's chain value: folding resumes from the
        snapshot watermark and the chain stays canonical."""
        with self._lock:
            self._chain = bytes.fromhex(digest_hex)
            self._last_index = int(index)
            self._bucket = int(index) // self.interval
            self._checkpoints.clear()
            self._verified_index = int(index)
            self._synced = True
            self._unsynced_reason = ""

    def reset(self) -> None:
        """Back to genesis (quarantine wiped the FSM; a full log replay
        from index 1 re-derives the canonical chain)."""
        with self._lock:
            self._chain = _GENESIS
            self._last_index = 0
            self._bucket = 0
            self._checkpoints.clear()
            self._verified_index = 0
            self._synced = True
            self._unsynced_reason = ""

    def mark_unsynced(self, reason: str) -> None:
        """Stop verifying (keep folding) — e.g. a restored snapshot
        predates digests, or an injected fold fault broke the chain.
        Prevents false divergence alarms; the next reseed re-syncs."""
        with self._lock:
            self._synced = False
            self._unsynced_reason = reason

    # --------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "Interval": self.interval,
                "LastIndex": self._last_index,
                "Chain": self._chain.hex(),
                "Checkpoints": dict(self._checkpoints),
                "VerifiedIndex": self._verified_index,
                "Synced": self._synced,
                "UnsyncedReason": self._unsynced_reason,
                "Folds": self._folds,
                "Exchanged": self._exchanged,
                "Diverged": self._diverged,
            }


# ------------------------------------------------------- effect summaries
def effect_of(state, index: int, msg_type: int,
              payload: Dict[str, Any]) -> Any:
    """Canonical post-apply effect summary for one entry: cheap readbacks
    of the rows the handler touched (ids + the status fields replicas
    must agree on). Message types are matched by INT VALUE so this stays
    import-light; the mapping mirrors fsm.MessageType."""
    if msg_type in (0, 2, 3):      # NodeRegister / status / drain updates
        node = state.node_by_id(payload["NodeID"]) \
            if "NodeID" in payload else state.node_by_id(
                payload["Node"]["ID"] if isinstance(payload["Node"], dict)
                else payload["Node"].ID)
        if node is None:
            return ("node", None)
        return ("node", node.ID, node.Status, bool(node.Drain),
                node.ModifyIndex)
    if msg_type == 1:              # NodeDeregister
        return ("node_del", payload["NodeID"])
    if msg_type == 4:              # JobRegister
        job_id = payload["Job"]["ID"] if isinstance(payload["Job"], dict) \
            else payload["Job"].ID
        job = state.job_by_id(job_id)
        return ("job", job_id, None if job is None else job.Status)
    if msg_type == 5:              # JobDeregister
        return ("job_del", payload["JobID"])
    if msg_type == 6:              # EvalUpdate
        out = []
        for e in payload["Evals"]:
            eid = e["ID"] if isinstance(e, dict) else e.ID
            ev = state.eval_by_id(eid)
            out.append((eid, None if ev is None else ev.Status))
        return ("evals", out)
    if msg_type == 7:              # EvalDelete
        return ("eval_del", sorted(payload.get("Evals", ())),
                sorted(payload.get("Allocs", ())))
    if msg_type == 8:              # AllocUpdate
        return ("allocs", _alloc_effects(state, payload))
    if msg_type == 9:              # AllocClientUpdate
        out = []
        for a in payload["Alloc"]:
            aid = a["ID"] if isinstance(a, dict) else a.ID
            alloc = state.alloc_by_id(aid)
            out.append((aid,
                        None if alloc is None else alloc.ClientStatus))
        return ("client", out)
    if msg_type in (10, 11):       # PeriodicLaunch upsert / delete
        launch = payload.get("Launch")
        if launch is not None:
            return ("launch",
                    launch["ID"] if isinstance(launch, dict) else launch.ID)
        return ("launch_del", payload["JobID"])
    if msg_type == 12:             # ServiceSync
        ups = [(r["ID"] if isinstance(r, dict) else r.ID)
               for r in payload.get("Upserts", ())]
        return ("services", sorted(ups),
                sorted(payload.get("Deletes", ())))
    if msg_type == 13:             # ApplySweepBatch — columns, raw
        return ("sweep", _sweep_effects(state, payload))
    return ("other", msg_type)


def _alloc_effects(state, payload: Dict[str, Any]) -> list:
    groups = payload.get("Batch")
    if groups is None:
        groups = [payload]
    out = []
    for group in groups:
        for a in group.get("Alloc", ()):
            aid = a["ID"] if isinstance(a, dict) else a.ID
            alloc = state.alloc_by_id(aid)
            if alloc is None:
                out.append((aid, None))
            else:
                out.append((aid, alloc.DesiredStatus, alloc.ClientStatus,
                            alloc.ModifyIndex))
    return out


def _sweep_effects(state, payload: Dict[str, Any]) -> list:
    """Columnar groups digest their column arrays directly — ids, rows,
    counts, usage delta — plus readbacks for any object co-groups. No
    row is ever materialized for the digest."""
    groups = payload.get("Batch")
    if groups is None:
        groups = [payload]
    out = []
    for group in groups:
        sweep = group.get("Sweep")
        if sweep is None:
            for a in group.get("Alloc", ()):
                aid = a["ID"] if isinstance(a, dict) else a.ID
                alloc = state.alloc_by_id(aid)
                out.append((aid, None if alloc is None
                            else alloc.DesiredStatus))
            continue
        out.append((
            list(sweep["AllocIDs"]),
            list(sweep["RowNodeIDs"]),
            np.asarray(sweep["Counts"], dtype=np.int64),
            np.asarray(sweep["Rows"], dtype=np.int64),
            np.asarray(sweep["Delta"], dtype=np.float32),
            sweep.get("Kind", "system"),
        ))
    return out


def chaos_corrupt(state, index: int, msg_type: int,
                  payload: Dict[str, Any]) -> bool:
    """`fsm.digest.mutate` drop-mode: silently corrupt the row this entry
    just wrote, IN PLACE and bypassing indexes — the exact failure the
    digest exists to catch. The corruption lands BEFORE the effect
    readback, so this replica folds the corrupt value while healthy
    replicas fold the clean one. Returns True when something mutated."""
    if msg_type == 6 and payload.get("Evals"):
        e = payload["Evals"][0]
        ev = state.eval_by_id(e["ID"] if isinstance(e, dict) else e.ID)
        if ev is not None:
            ev.Status = "chaos-diverged"
            return True
    if msg_type in (0, 2) :
        nid = payload.get("NodeID")
        if nid is None and "Node" in payload:
            nid = payload["Node"]["ID"] if isinstance(payload["Node"], dict) \
                else payload["Node"].ID
        node = state.node_by_id(nid) if nid else None
        if node is not None:
            node.Status = "chaos-diverged"
            return True
    if msg_type == 8:
        for aid, _ in ((a["ID"] if isinstance(a, dict) else a.ID, a)
                       for g in (payload.get("Batch") or [payload])
                       for a in g.get("Alloc", ())):
            alloc = state.alloc_by_id(aid)
            if alloc is not None:
                alloc.DesiredStatus = "chaos-diverged"
                return True
    return False
