"""Static concurrency/telemetry lint + runtime lock diagnostics.

The static side (``framework``/``checkers``) is an AST pass over the
package tree hosting the lock-discipline, retry-discipline, thread-
lifecycle, exception-swallow, and telemetry-key checkers behind the
``nomad-tpu lint`` CLI and the tier-1 ``tests/test_analysis_lint.py``
gate (the Python analogue of the `go vet` pass the reference leans on).

The runtime side (``debug_locks``) is an opt-in lock-order detector in
the Eraser/ThreadSanitizer lineage: ``NOMAD_TPU_DEBUG_LOCKS=1`` swaps
``threading.Lock``/``RLock`` for wrappers that maintain a process-wide
lock-order graph and report order inversions, over-long holds, and
blocking primitives invoked under a lock.
"""

from .annotations import guarded_by, requires_lock
from .findings import Finding
from .framework import all_checkers, run_checks

__all__ = ["Finding", "all_checkers", "guarded_by", "requires_lock",
           "run_checks"]
