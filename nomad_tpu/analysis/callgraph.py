"""Project-wide AST call graph + apply-path purity analysis.

Replica determinism rests on one invariant: every code path reachable
from the FSM apply handlers, the StateStore mutators, snapshot restore,
and the event builders is a deterministic function of the committed raft
entry. This module makes that invariant *checkable*: it builds a call
graph over the framework's cached per-file parses, computes the
transitive closure from the apply-path roots, and classifies every
reachable call against a declared nondeterminism taxonomy:

  wall_clock   time.time/monotonic/perf_counter(_ns), datetime.now/...
  randomness   random.*, uuid1/uuid4, os.urandom, secrets.*
  identity     id(), hash() — process-local values leaking into state
  unordered    iteration directly over a set display / set() call whose
               order could reach a replicated write or event list
  thread       thread/timer spawns inside the apply path
  io           open/subprocess/socket — external effects under apply

Resolution is deliberately conservative and name-based (Python has no
static types to lean on):

  * bare names resolve through the file's import table, then to
    same-module functions;
  * ``self.meth()`` resolves to the enclosing class's method, falling
    back to a project-wide method-name match (method dispatch);
  * ``obj.meth()`` resolves by method-name match across scanned classes
    RESTRICTED to the calling file and the modules it imports (a file
    cannot invoke a method of a class it has no path to), EXCLUDING
    common container/str method names (a denylist) so
    `items.append(...)` never drags in an unrelated `append`.

Declared observer seams are traversal BOUNDARIES: the telemetry package
(metrics/trace stamping is replica-local by contract) and the failpoint
registry (disarmed in production; armed only under chaos schedules).
Calls INTO them never flag; direct taxonomy calls in apply-path files
still do, and carry `# lint: allow(apply_pure, <reason>)` suppressions
where they are intentionally local-only.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .framework import FileContext, PKG_ROOT

# --------------------------------------------------------------- taxonomy
_WALL_CLOCK_TIME = {"time", "time_ns", "monotonic", "monotonic_ns",
                    "perf_counter", "perf_counter_ns"}
_WALL_CLOCK_DATETIME = {"now", "utcnow", "today", "fromtimestamp"}
_RANDOM_UUID = {"uuid1", "uuid4"}
_THREAD_SPAWNS = {"Thread", "Timer"}
_IO_SUBPROCESS = {"run", "Popen", "call", "check_call", "check_output"}

# Method names too generic to resolve across classes: builtin container /
# str methods plus ubiquitous local-only verbs. Without this, every
# `watch_items.add(...)` would edge into every project class defining
# `add`.
_DENY_METHODS = {
    "get", "set", "add", "append", "extend", "insert", "remove", "pop",
    "clear", "keys", "values", "items", "update", "setdefault", "sort",
    "reverse", "join", "split", "strip", "startswith", "endswith",
    "format", "encode", "decode", "copy", "count", "index", "lower",
    "upper", "replace", "read", "close", "discard", "union", "wait",
    "notify", "notify_all", "acquire", "release", "put", "get_nowait",
    "tolist", "astype", "item", "fill", "any", "all", "sum", "max",
    "min", "isoformat", "total_seconds", "groups", "group", "match",
    "search", "finditer", "findall",
}

# Files that are declared traversal boundaries (relative to PKG_ROOT):
# replica-local observer seams whose internals are not apply-path state.
_BOUNDARY_PREFIXES = ("telemetry" + os.sep,)
_BOUNDARY_FILES = {os.path.join("resilience", "failpoints.py")}


@dataclass
class Impurity:
    """One nondeterministic call reachable from an apply-path root."""

    category: str      # taxonomy bucket, e.g. "wall_clock"
    label: str         # rendered call, e.g. "time.time()"
    path: str          # absolute path of the offending file
    lineno: int
    func: str          # qualname of the function containing the call
    chain: Tuple[str, ...]  # root -> ... -> func qualnames


class _FuncInfo:
    __slots__ = ("key", "path", "qualname", "cls", "name", "lineno",
                 "node", "boundary")

    def __init__(self, key, path, qualname, cls, name, lineno, node,
                 boundary):
        self.key = key
        self.path = path
        self.qualname = qualname
        self.cls = cls          # enclosing class name or None
        self.name = name        # bare function/method name
        self.lineno = lineno
        self.node = node        # the ast.FunctionDef
        self.boundary = boundary


def _rel(path: str) -> Optional[str]:
    """Path relative to the package root, or None for external files."""
    rel = os.path.relpath(path, PKG_ROOT)
    return None if rel.startswith("..") else rel


def _module_path(module: str) -> Optional[str]:
    """nomad_tpu.x.y -> absolute source path (or None for externals)."""
    if module == "nomad_tpu":
        return os.path.join(PKG_ROOT, "__init__.py")
    if not module.startswith("nomad_tpu."):
        return None
    parts = module.split(".")[1:]
    path = os.path.join(PKG_ROOT, *parts)
    if os.path.isdir(path):
        return os.path.join(path, "__init__.py")
    return path + ".py"


def _dotted(func: ast.AST) -> Optional[List[str]]:
    """['self', 'state', 'upsert_node'] for self.state.upsert_node; None
    for calls through subscripts/calls (resolved by name-match instead)."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


class CallGraph:
    """Call graph over a set of scanned FileContexts."""

    def __init__(self) -> None:
        self._funcs: Dict[Tuple[str, str], _FuncInfo] = {}
        # method name -> keys of every class method with that name
        self._methods: Dict[str, List[Tuple[str, str]]] = {}
        # (path, name) -> key, for module-level functions
        self._module_funcs: Dict[Tuple[str, str], Tuple[str, str]] = {}
        # path -> {local name: module} from `import X [as Y]`
        self._imports: Dict[str, Dict[str, str]] = {}
        # path -> {local name: (module, attr)} from `from X import Y`
        self._from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        # path -> project source paths its imports can reach (the
        # visibility set for method-name fallback resolution)
        self._visible: Dict[str, Set[str]] = {}
        self._paths: Set[str] = set()

    # ---------------------------------------------------------- indexing
    def add_file(self, ctx: FileContext) -> None:
        path = ctx.path
        if path in self._paths:
            return
        self._paths.add(path)
        rel = _rel(path)
        boundary = rel is not None and (
            rel in _BOUNDARY_FILES
            or any(rel.startswith(p) for p in _BOUNDARY_PREFIXES))
        imports: Dict[str, str] = {}
        from_imports: Dict[str, Tuple[str, str]] = {}
        self._imports[path] = imports
        self._from_imports[path] = from_imports

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    from_imports[alias.asname or alias.name] = \
                        (node.module, alias.name)
            elif isinstance(node, ast.ImportFrom) and node.level > 0 \
                    and rel is not None:
                # Relative import inside the package: resolve against rel.
                base = rel.replace(os.sep, ".")[:-3]
                pkg = base.rsplit(".", node.level)[0] if "." in base \
                    else ""
                module = "nomad_tpu" + ("." + pkg if pkg else "") \
                    + ("." + node.module if node.module else "")
                for alias in node.names:
                    from_imports[alias.asname or alias.name] = \
                        (module, alias.name)

        def index(node: ast.AST, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{cls}.{child.name}" if cls else child.name
                    key = (path, qual)
                    self._funcs[key] = _FuncInfo(
                        key, path, qual, cls, child.name, child.lineno,
                        child, boundary)
                    if cls:
                        self._methods.setdefault(child.name, []).append(key)
                    else:
                        self._module_funcs[(path, child.name)] = key
                    # Nested defs fold into the enclosing function; do
                    # not index them separately.
                elif isinstance(child, ast.ClassDef):
                    index(child, child.name)
                else:
                    index(child, cls)

        index(ctx.tree, None)

        visible = {path}
        for module in imports.values():
            p = _module_path(module)
            if p is not None:
                visible.add(p)
        for module, attr in from_imports.values():
            for candidate in (module, module + "." + attr):
                p = _module_path(candidate)
                if p is not None:
                    visible.add(p)
        self._visible[path] = visible

    def functions(self) -> Iterable[_FuncInfo]:
        return self._funcs.values()

    # -------------------------------------------------------- resolution
    def _classify_module_call(self, module: str, attr: str,
                              ) -> Optional[Tuple[str, str]]:
        """(category, label) when module.attr() is a taxonomy leaf."""
        tail = module.split(".")[-1]
        if tail == "time" and attr in _WALL_CLOCK_TIME:
            return ("wall_clock", f"time.{attr}()")
        if tail == "datetime" and attr in _WALL_CLOCK_DATETIME:
            return ("wall_clock", f"datetime.{attr}()")
        if tail == "random":
            return ("randomness", f"random.{attr}()")
        if tail == "uuid" and attr in _RANDOM_UUID:
            return ("randomness", f"uuid.{attr}()")
        if tail == "os" and attr == "urandom":
            return ("randomness", "os.urandom()")
        if tail == "secrets":
            return ("randomness", f"secrets.{attr}()")
        if tail == "threading" and attr in _THREAD_SPAWNS:
            return ("thread", f"threading.{attr}()")
        if tail == "subprocess" and attr in _IO_SUBPROCESS:
            return ("io", f"subprocess.{attr}()")
        if tail == "socket":
            return ("io", f"socket.{attr}()")
        return None

    def _classify_bare(self, name: str,
                       from_imports: Dict[str, Tuple[str, str]],
                       ) -> Optional[Tuple[str, str]]:
        if name in ("id", "hash"):
            return ("identity", f"{name}()")
        if name == "open":
            return ("io", "open()")
        if name in from_imports:
            module, attr = from_imports[name]
            return self._classify_module_call(module, attr)
        return None

    def _project_edge(self, module: str, attr: str,
                      ) -> Optional[Tuple[str, str]]:
        path = _module_path(module)
        if path is None:
            return None
        return self._module_funcs.get((path, attr))

    def resolve(self, info: _FuncInfo) -> Tuple[
            List[Tuple[str, str]], List[Tuple[str, str, int]]]:
        """(callee keys, taxonomy leaves [(category, label, lineno)]) for
        every call lexically inside `info` (nested defs included)."""
        edges: List[Tuple[str, str]] = []
        leaves: List[Tuple[str, str, int]] = []
        imports = self._imports.get(info.path, {})
        from_imports = self._from_imports.get(info.path, {})

        for node in ast.walk(info.node):
            if isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if isinstance(it, ast.Set) or (
                        isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Name)
                        and it.func.id in ("set", "frozenset")):
                    lineno = getattr(node, "lineno", None) \
                        or getattr(it, "lineno", info.lineno)
                    leaves.append(("unordered",
                                   "iteration over a set (hash order)",
                                   lineno))
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name):
                name = func.id
                leaf = self._classify_bare(name, from_imports)
                if leaf is not None:
                    leaves.append((leaf[0], leaf[1], node.lineno))
                    continue
                if name in from_imports:
                    module, attr = from_imports[name]
                    edge = self._project_edge(module, attr)
                    if edge is not None:
                        edges.append(edge)
                    continue
                edge = self._module_funcs.get((info.path, name))
                if edge is not None:
                    edges.append(edge)
                continue
            parts = _dotted(func)
            attr = func.attr if isinstance(func, ast.Attribute) else ""
            if parts is None:
                # obj[...] .meth() / chained-call receivers: name-match.
                edges.extend(self._method_edges(attr, info))
                continue
            head = parts[0]
            if head == "self":
                if len(parts) == 2 and info.cls is not None:
                    own = (info.path, f"{info.cls}.{attr}")
                    if own in self._funcs:
                        edges.append(own)
                        continue
                edges.extend(self._method_edges(attr, info))
                continue
            if head in imports:
                module = imports[head]
                # `datetime.datetime.now()` and plain `time.time()` both
                # classify off the dotted tail.
                tail_mod = module if len(parts) == 2 \
                    else module + "." + ".".join(parts[1:-1])
                leaf = self._classify_module_call(tail_mod, attr)
                if leaf is not None:
                    leaves.append((leaf[0], leaf[1], node.lineno))
                    continue
                edge = self._project_edge(module, attr)
                if edge is not None:
                    edges.append(edge)
                continue
            if head in from_imports and len(parts) >= 2:
                module, sub = from_imports[head]
                leaf = self._classify_module_call(module + "." + sub, attr)
                if leaf is not None:
                    leaves.append((leaf[0], leaf[1], node.lineno))
                    continue
                edge = self._project_edge(module + "." + sub, attr)
                if edge is not None:
                    edges.append(edge)
                    continue
                edges.extend(self._method_edges(attr, info))
                continue
            edges.extend(self._method_edges(attr, info))
        return edges, leaves

    def _method_edges(self, attr: str,
                      info: _FuncInfo) -> List[Tuple[str, str]]:
        if not attr or attr in _DENY_METHODS:
            return []
        visible = self._visible.get(info.path, ())
        return [key for key in self._methods.get(attr, ())
                if key[0] in visible]

    # ------------------------------------------------------------- roots
    def apply_roots(self) -> List[Tuple[str, str]]:
        """The declared apply-path entry points.

        Inside the package: FSM apply/restore, StateStore mutators, the
        Restore loader, and the event builders. External files (the lint
        fixture, ad-hoc scans) root at apply/restore-named functions so
        the checker is provable outside the tree too — but that loose
        rule deliberately does NOT apply in-package (RaftBackend.apply
        wraps transport I/O that is not replicated-apply work).
        """
        roots: List[Tuple[str, str]] = []
        fsm_path = os.path.join(PKG_ROOT, "server", "fsm.py")
        store_path = os.path.join(PKG_ROOT, "state", "state_store.py")
        builders_path = os.path.join(PKG_ROOT, "events", "builders.py")
        for key, info in self._funcs.items():
            path, qual = key
            if path == fsm_path and info.cls == "FSM" and (
                    info.name == "apply"
                    or info.name.startswith("_apply_")
                    or info.name in ("restore", "restore_chunks")):
                roots.append(key)
            elif path == store_path and info.cls == "StateStore" and (
                    info.name.startswith(("upsert_", "delete_", "update_"))
                    or info.name == "apply_sweep_segment"):
                roots.append(key)
            elif path == store_path and info.cls == "Restore":
                roots.append(key)
            elif path == builders_path and info.cls is None:
                roots.append(key)
            elif _rel(path) is None and (
                    info.name == "apply"
                    or info.name.startswith("_apply_")
                    or info.name.startswith("restore")):
                roots.append(key)
        return roots

    # ------------------------------------------------------ reachability
    def impurities(self, roots: Optional[List[Tuple[str, str]]] = None,
                   ) -> List[Impurity]:
        """Taxonomy leaves in the transitive closure of `roots` (BFS;
        shortest chain wins when a site is reachable several ways)."""
        if roots is None:
            roots = self.apply_roots()
        parent: Dict[Tuple[str, str], Optional[Tuple[str, str]]] = {}
        queue: List[Tuple[str, str]] = []
        for r in roots:
            if r not in parent:
                parent[r] = None
                queue.append(r)
        resolved: Dict[Tuple[str, str], Tuple[list, list]] = {}
        order: List[Tuple[str, str]] = []
        while queue:
            key = queue.pop(0)
            info = self._funcs.get(key)
            if info is None or info.boundary:
                continue
            order.append(key)
            edges, leaves = self.resolve(info)
            resolved[key] = (edges, leaves)
            for callee in edges:
                if callee not in parent:
                    parent[callee] = key
                    queue.append(callee)

        def chain(key: Tuple[str, str]) -> Tuple[str, ...]:
            out: List[str] = []
            cur: Optional[Tuple[str, str]] = key
            while cur is not None:
                out.append(self._funcs[cur].qualname)
                cur = parent[cur]
            return tuple(reversed(out))

        seen: Set[Tuple[str, int, str]] = set()
        out: List[Impurity] = []
        for key in order:
            info = self._funcs[key]
            for category, label, lineno in resolved[key][1]:
                dedup = (info.path, lineno, label)
                if dedup in seen:
                    continue
                seen.add(dedup)
                out.append(Impurity(category, label, info.path, lineno,
                                    info.qualname, chain(key)))
        out.sort(key=lambda i: (i.path, i.lineno))
        return out


def build_graph(contexts: Iterable[FileContext]) -> CallGraph:
    graph = CallGraph()
    for ctx in contexts:
        graph.add_file(ctx)
    return graph
