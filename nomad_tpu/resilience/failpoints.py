"""Failpoints: a thread-safe registry of named fault-injection sites
(reference technique: freebsd/fail.h style failpoints and the
testutil/chaos hooks scattered through hashicorp's suites — here one
first-class subsystem instead of per-test monkeypatching).

A production code path declares a site by calling ``fire("site.name")``
at its failure seam. Disarmed — the normal state — that is one module
attribute read and a falsy check; no lock, no dict lookup, no
allocation. Armed, the site can:

  raise   — raise :class:`FailpointError` (an injected hard failure)
  delay   — sleep for a configured duration, then proceed
  drop    — return ``"drop"``; the site discards the operation the way
            its real network would (a lost datagram, a black-holed RPC)

Each armed spec composes two modifiers: ``probability`` (trigger on a
coin flip per hit) and ``count`` (disarm automatically after N
triggers; ``count=1`` is the classic "once" failpoint).

Arming surfaces:
  * env var   — ``NOMAD_TPU_FAILPOINTS="raft.fsync=error;rpc.pool.call=
                delay(0.2):p=0.5:count=3"`` (parsed at import)
  * Python    — :func:`arm` / :func:`disarm` / :func:`disarm_all`
  * HTTP/CLI  — ``/v1/agent/debug/faults`` + ``nomad-tpu faults``
                (agent/http.py, cli/commands.py), both speaking the same
                spec grammar via :func:`arm_from_spec`.
"""

from __future__ import annotations

import os
import random
import re
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "FailpointError", "fire", "arm", "disarm", "disarm_all",
    "arm_from_spec", "arm_from_env", "snapshot", "known_sites",
    "ENV_VAR",
]

ENV_VAR = "NOMAD_TPU_FAILPOINTS"

# Sites threaded through the codebase, so the faults endpoint can list
# what is armable even before any site has fired. Keep alphabetical.
KNOWN_SITES: Dict[str, str] = {
    "broker.admission": "server: QoS admission check at submission "
                        "ingress (drop=forced shed -> typed backpressure "
                        "to the submitter; error=failed submission; "
                        "delay=slow admission)",
    "client.alloc_sync": "client: batched alloc status push to servers",
    "client.heartbeat": "client: node heartbeat to the leader",
    "client.register": "client: node registration RPC",
    "driver.docker.exec": "docker driver: container launch/exec calls",
    "events.publish": "server: event-broker publish of one applied raft "
                      "entry's batch (drop/error=subscriber-visible loss "
                      "— stream coverage still advances and the "
                      "equivalence fold must surface the missing events; "
                      "delay=slow publish on the apply path; NEVER "
                      "FSM-visible — a consensus-committed entry must "
                      "apply even when its events are lost)",
    "fsm.digest.mutate": "server: post-handler seam of the replica "
                         "state-digest fold (drop=silent IN-PLACE store "
                         "corruption of the row the entry just wrote, "
                         "bypassing indexes, on NON-LEADER replicas only "
                         "— the corrupted replica folds the corrupt "
                         "readback while the leader folds the clean one, "
                         "and the next checkpoint exchange must flag "
                         "divergence and quarantine it to snapshot-"
                         "reinstall; error=injected fold failure — "
                         "contained: the entry stays applied and the "
                         "digest goes unsynced instead of alarming; "
                         "delay=slow fold on the apply path)",
    "gossip.probe": "gossip: direct ping of the probe target",
    "gossip.send": "gossip: outbound UDP datagram (drop=lost packet)",
    "plan.apply.commit": "server: plan applier's consensus commit",
    "plan.preempt.commit": "server: consensus commit of a plan group "
                           "carrying alloc preemptions (kill the applier "
                           "mid-preemption; workers must nack, the broker "
                           "redeliver exactly once, and evictions never "
                           "commit without their placement)",
    "raft.append_entries": "raft: leader->peer AppendEntries send",
    "raft.fsync": "raft: durable log append fsync",
    "raft.install_snapshot": "raft: one chunk hop of a streamed "
                             "InstallSnapshot send (error=failed send; "
                             "delay=slow install; drop=lost chunk — the "
                             "follower's staged stream goes stale, rejects, "
                             "and the leader restarts from chunk 0; a "
                             "partial stream must never install)",
    "raft.request_vote": "raft: candidate->peer RequestVote send",
    "raft.snapshot.chunk": "raft: one chunk of a streaming snapshot "
                           "persist (error=failed chunk write; delay=slow "
                           "persist; drop=torn stream — the persist aborts "
                           "wholesale, the PREVIOUS snapshot stays intact "
                           "on disk and in memory, and the threshold "
                           "counter re-arms so the next apply retries)",
    "raft.snapshot.persist": "raft: state snapshot persist to the log store",
    "raft.snapshot.restore": "raft/state: FSM restore from snapshot blob",
    "state.store.commit": "server: columnar sweep-batch bulk commit (fires "
                          "in the plan applier BEFORE the consensus entry "
                          "is proposed, so a killed commit never enters "
                          "the raft log — the worker nacks, the broker "
                          "redelivers exactly once, no duplicate allocs "
                          "even across restart/replay, never a torn "
                          "batch)",
    "server.blocked.unblock": "server: blocked-evals capacity wakeup "
                              "(drop=lost wakeup event)",
    "rpc.forward_region": "rpc: one cross-region forward attempt "
                          "(federation/routing.py; error=link failed "
                          "before send — safe retry onto another region "
                          "peer; delay=slow WAN hop; drop=request "
                          "DELIVERED but response lost — the ambiguous "
                          "failure: the retry replays the same ForwardID "
                          "and the receiving region's dedupe cache must "
                          "answer it, yielding exactly-once registration "
                          "and no duplicate evals)",
    "rpc.pool.call": "rpc: pooled client call over the wire",
    "sched.system.emit": "scheduler: system sweep's bulk placement emit "
                         "(kill a sweep before anything is submitted; the "
                         "worker must nack and the broker redeliver the "
                         "eval exactly once with no duplicate allocs)",
    "rpc.server.handle": "rpc: server-side endpoint dispatch",
    "services.sync": "client: service-registry sync push to the servers "
                     "(drop=lost batch; retried next flush)",
    "tensor.mesh.exchange": "scheduler: sharded mesh winner-row exchange "
                            "(the per-shard candidate packets' hop to the "
                            "lead device in a cold keyed window; kill it "
                            "mid-storm and the worker must nack the "
                            "window, the ChainArbiter rebase the chain, "
                            "and the broker redeliver every eval exactly "
                            "once with no duplicate allocs)",
    "worker.dequeue": "server: scheduling worker eval dequeue",
    "worker.window.drain": "server: pipelined worker's window drain fetch "
                           "(kill a worker's window mid-flight; the broker "
                           "must redeliver its evals exactly once and the "
                           "chain rebase recover)",
}

MODES = ("error", "delay", "drop")


class FailpointError(Exception):
    """Raised by an armed ``error``-mode failpoint. Deliberately a plain
    Exception subclass: sites sit inside code that maps unexpected
    exceptions to its own failure handling, which is exactly the path
    under test."""

    def __init__(self, site: str, message: str = ""):
        super().__init__(message or f"failpoint {site!r} triggered")
        self.site = site


class _Spec:
    __slots__ = ("mode", "delay", "probability", "remaining", "message",
                 "hits")

    def __init__(self, mode: str, delay: float = 0.0,
                 probability: float = 1.0,
                 count: Optional[int] = None, message: str = ""):
        if mode not in MODES:
            raise ValueError(f"unknown failpoint mode {mode!r} "
                             f"(want one of {MODES})")
        if not (0.0 < probability <= 1.0):
            raise ValueError("probability must be in (0, 1]")
        if count is not None and count <= 0:
            raise ValueError("count must be positive")
        if delay < 0:
            raise ValueError("delay must be non-negative")
        self.mode = mode
        self.delay = float(delay)
        self.probability = float(probability)
        self.remaining = count
        self.message = message
        self.hits = 0

    def describe(self) -> Dict[str, Any]:
        return {"mode": self.mode, "delay": self.delay,
                "probability": self.probability,
                "remaining": self.remaining, "hits": self.hits}


_lock = threading.Lock()
_armed: Dict[str, _Spec] = {}
# Disarmed fast path: one module attribute read. Maintained strictly
# under _lock as "any site armed"; readers tolerate the benign race (a
# site arming mid-call fires on the NEXT hit).
_active = False
# Lifetime trigger counts per site, kept across disarm for the faults
# endpoint ("did my chaos schedule actually hit the seam?").
_fired: Dict[str, int] = {}


def fire(site: str) -> Optional[str]:
    """Declare + evaluate the failpoint ``site``. Returns ``"drop"`` when
    the caller should discard the operation, ``None`` otherwise. Raises
    :class:`FailpointError` in ``error`` mode. The disarmed cost is this
    one truthiness check."""
    if not _active:
        return None
    return _fire_armed(site)


def _fire_armed(site: str) -> Optional[str]:
    with _lock:
        spec = _armed.get(site)
        if spec is None:
            return None
        if spec.probability < 1.0 and random.random() >= spec.probability:
            return None
        spec.hits += 1
        _fired[site] = _fired.get(site, 0) + 1
        if spec.remaining is not None:
            spec.remaining -= 1
            if spec.remaining <= 0:
                del _armed[site]
                _refresh_active_locked()
        mode, delay, message = spec.mode, spec.delay, spec.message
    # Resilience <-> tracing: a triggered fault annotates the active span
    # (and retains the trace via the error tail rule), so "which failpoint
    # did this evaluation hit?" reads straight off its timeline.
    from nomad_tpu.telemetry import trace as _trace

    _trace.add_event("failpoint", site=site, mode=mode)
    # Act outside the lock: a delay must not serialize every other site.
    if mode == "error":
        raise FailpointError(site, message)
    if mode == "delay":
        time.sleep(delay)
        return None
    return "drop"


def _refresh_active_locked() -> None:
    global _active
    _active = bool(_armed)


def arm(site: str, mode: str, delay: float = 0.0, probability: float = 1.0,
        count: Optional[int] = None, message: str = "") -> None:
    """Arm ``site``. Unknown site names are accepted (tests may declare
    ad-hoc sites), but a typo'd name simply never fires — ``snapshot()``
    shows hits=0, which is the debugging signal."""
    spec = _Spec(mode, delay=delay, probability=probability, count=count,
                 message=message)
    with _lock:
        _armed[site] = spec
        _refresh_active_locked()


def disarm(site: str) -> bool:
    with _lock:
        existed = _armed.pop(site, None) is not None
        _refresh_active_locked()
    return existed


def disarm_all() -> None:
    with _lock:
        _armed.clear()
        _refresh_active_locked()


# --------------------------------------------------------------- spec text
# site=mode[(arg)][:p=<float>][:count=<int>] joined by ";"
#   modes: error / error(message) / delay(seconds) / drop / off
_SPEC_RE = re.compile(r"^(?P<mode>error|delay|drop|off)"
                      r"(?:\((?P<arg>[^)]*)\))?$")


def arm_from_spec(text: str) -> List[str]:
    """Parse + apply the compact spec grammar shared by the env var, the
    CLI and the HTTP endpoint. Returns the site names touched. ``off``
    as a mode disarms the site. Raises ValueError on malformed input
    (the HTTP layer maps that to a 400) — and applies NOTHING in that
    case: a 400 response must mean no fault was left armed, so every
    clause is validated before any clause takes effect."""
    planned: List[tuple] = []  # (site, _Spec-or-None for disarm)
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        site, sep, rest = part.partition("=")
        site = site.strip()
        if not sep or not site or not rest.strip():
            raise ValueError(f"bad failpoint spec {part!r} "
                             "(want site=mode[:p=..][:count=..])")
        tokens = rest.strip().split(":")
        m = _SPEC_RE.match(tokens[0].strip())
        if m is None:
            raise ValueError(f"bad failpoint mode {tokens[0]!r}")
        mode, arg = m.group("mode"), m.group("arg")
        probability, count = 1.0, None
        for tok in tokens[1:]:
            key, _, val = tok.strip().partition("=")
            try:
                if key == "p":
                    probability = float(val)
                elif key == "count":
                    count = int(val)
                elif key == "once" and not val:
                    count = 1
                else:
                    raise ValueError
            except ValueError:
                raise ValueError(f"bad failpoint modifier {tok!r}")
        if mode == "off":
            planned.append((site, None))
        elif mode == "delay":
            try:
                delay = float(arg or "")
            except ValueError:
                raise ValueError(
                    f"delay needs a seconds argument: {part!r}")
            planned.append((site, _Spec("delay", delay=delay,
                                        probability=probability,
                                        count=count)))
        elif mode == "error":
            planned.append((site, _Spec("error", probability=probability,
                                        count=count, message=arg or "")))
        else:  # drop
            planned.append((site, _Spec("drop", probability=probability,
                                        count=count)))
    with _lock:
        for site, spec in planned:
            if spec is None:
                _armed.pop(site, None)
            else:
                _armed[site] = spec
        _refresh_active_locked()
    return [site for site, _ in planned]


def arm_from_env(environ=os.environ) -> List[str]:
    text = environ.get(ENV_VAR, "")
    if not text:
        return []
    return arm_from_spec(text)


# ------------------------------------------------------------ introspection
def snapshot() -> Dict[str, Any]:
    """State for the faults endpoint: every known/armed site with its
    spec (None when disarmed) and lifetime trigger count."""
    with _lock:
        names = set(KNOWN_SITES) | set(_armed) | set(_fired)
        return {
            name: {
                "description": KNOWN_SITES.get(name, ""),
                "armed": (_armed[name].describe()
                          if name in _armed else None),
                "fired": _fired.get(name, 0),
            }
            for name in sorted(names)
        }


def known_sites() -> List[str]:
    with _lock:
        return sorted(set(KNOWN_SITES) | set(_armed))


# Env arming at import: a process started under NOMAD_TPU_FAILPOINTS has
# its faults armed before any subsystem thread spins up. A malformed
# spec must not take down every entry point (even `faults --disarm-all`
# imports this module) — warn loudly and keep whatever parsed; the
# snapshot's hits=0 on the intended site is the debugging signal.
try:
    arm_from_env()
except ValueError as _exc:
    import sys as _sys

    print(f"nomad-tpu: ignoring malformed {ENV_VAR}: {_exc}",
          file=_sys.stderr)
