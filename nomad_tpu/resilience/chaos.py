"""Declarative chaos schedules: drive a cluster through a timeline of
failpoint arm/heal events and check the invariants that must hold anyway
(reference intent: nomad's leader-loss suites, generalized from one
hand-scripted test into a reusable family).

A schedule is a list of :class:`ChaosEvent` — "at t=1.0s arm
``raft.fsync=error:count=5``, at t=3.0s heal it" — executed by a
background thread while the test applies load. The invariant checkers
mirror the cluster-chaos suite's assertions: every evaluation terminal,
no lost or duplicated allocations, no node oversubscribed, state indexes
monotonic, convergence after heal.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from . import failpoints

__all__ = ["ChaosEvent", "ChaosSchedule", "IndexProbe",
           "check_invariants", "assert_invariants"]


@dataclass
class ChaosEvent:
    """One point on the fault timeline. ``spec`` uses the shared
    failpoint grammar (``"site=mode:p=..;other=off"``); ``action`` is an
    arbitrary callable for faults failpoints can't express (killing a
    server, partitioning gossip)."""

    at: float
    spec: str = ""
    action: Optional[Callable[[], None]] = None
    name: str = ""

    def fire(self) -> None:
        if self.spec:
            failpoints.arm_from_spec(self.spec)
        if self.action is not None:
            self.action()


@dataclass
class ChaosSchedule:
    """Run events at their offsets on a background thread. Use as a
    context manager so every armed failpoint is disarmed even when the
    test body throws — a leaked armed site would fail every later test
    in the process."""

    events: List[ChaosEvent] = field(default_factory=list)
    name: str = "chaos"
    heal_at_end: bool = True

    def __post_init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.fired: List[str] = []  # event names, in firing order

    # ------------------------------------------------------------- building
    def arm(self, at: float, spec: str, name: str = "") -> "ChaosSchedule":
        self.events.append(ChaosEvent(at=at, spec=spec,
                                      name=name or spec))
        return self

    def heal(self, at: float, *sites: str) -> "ChaosSchedule":
        spec = ";".join(f"{s}=off" for s in sites)
        self.events.append(ChaosEvent(at=at, spec=spec,
                                      name=f"heal {','.join(sites)}"))
        return self

    def call(self, at: float, action: Callable[[], None],
             name: str = "") -> "ChaosSchedule":
        self.events.append(ChaosEvent(at=at, action=action,
                                      name=name or "action"))
        return self

    # -------------------------------------------------------------- running
    def start(self) -> "ChaosSchedule":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"chaos-{self.name}")
        self._thread.start()
        return self

    def _run(self) -> None:
        start = time.monotonic()
        for ev in sorted(self.events, key=lambda e: e.at):
            wait = ev.at - (time.monotonic() - start)
            if wait > 0 and self._stop.wait(wait):
                return
            if self._stop.is_set():
                return
            ev.fire()
            self.fired.append(ev.name)

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def stop(self) -> None:
        self._stop.set()
        self.join(5.0)

    def __enter__(self) -> "ChaosSchedule":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
        if self.heal_at_end:
            failpoints.disarm_all()


class IndexProbe:
    """Asserts state-store index monotonicity across samples — a raft
    FSM must never observe its latest index move backwards, chaos or
    not."""

    def __init__(self) -> None:
        self.high = 0
        self.violations: List[str] = []

    def sample(self, state) -> int:
        idx = state.latest_index()
        if idx < self.high:
            self.violations.append(
                f"latest_index regressed: {self.high} -> {idx}")
        self.high = max(self.high, idx)
        return idx


def check_invariants(state, jobs: Sequence = (), per_job: int = 0,
                     eval_ids: Sequence[str] = ()) -> List[str]:
    """Return invariant violations (empty list = converged & consistent).
    ``state`` is a server's state store (typically the current leader's
    after healing); ``jobs`` the submitted Job objects; ``per_job`` the
    expected live allocation count per job."""
    from nomad_tpu.structs.structs import (
        EvalStatusCancelled,
        EvalStatusComplete,
        EvalStatusFailed,
    )

    terminal = (EvalStatusComplete, EvalStatusFailed, EvalStatusCancelled)
    problems: List[str] = []

    for eid in eval_ids:
        ev = state.eval_by_id(eid)
        if ev is None:
            problems.append(f"eval {eid} lost")
        elif ev.Status not in terminal:
            problems.append(f"eval {eid} not terminal: {ev.Status}")

    for job in jobs:
        live = [a for a in state.allocs_by_job(job.ID)
                if not a.terminal_status()]
        if per_job and len(live) != per_job:
            problems.append(f"job {job.ID}: {len(live)} live allocs, "
                            f"want {per_job}")
        if len({a.ID for a in live}) != len(live):
            problems.append(f"job {job.ID}: duplicated alloc IDs")

    problems.extend(_oversubscription(state))
    return problems


def _oversubscription(state) -> List[str]:
    import numpy as np

    from nomad_tpu.tensor.node_table import (
        RES_DIMS,
        alloc_vec,
        resources_vec,
    )

    cap = {n.ID: resources_vec(n.Resources) for n in state.nodes()}
    used = {}
    for a in state.allocs():
        if a.terminal_status():
            continue
        u = used.setdefault(a.NodeID, np.zeros(RES_DIMS, dtype=np.float64))
        u += alloc_vec(a)
    out = []
    for nid, u in used.items():
        capacity = cap.get(nid)
        if capacity is None:
            out.append(f"alloc on unknown node {nid}")
        elif not (u <= capacity + 1e-6).all():
            out.append(f"node {nid} oversubscribed: {u} > {capacity}")
    return out


def assert_invariants(state, jobs: Sequence = (), per_job: int = 0,
                      eval_ids: Sequence[str] = ()) -> None:
    problems = check_invariants(state, jobs, per_job, eval_ids)
    if problems:
        raise AssertionError("cluster invariants violated:\n  "
                             + "\n  ".join(problems))
