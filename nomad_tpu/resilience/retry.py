"""The one retry/backoff implementation (reference: the
`retryMax`/`resetTimer` loops in nomad's client and rpc layers, unified —
every subsystem here used to hand-roll its own ``time.sleep`` loop).

Three pieces:

  :class:`Backoff`        — decorrelated-jitter delay sequence. Jitter is
                            not cosmetic: synchronized retry loops across
                            a fleet of clients re-converge into thundering
                            herds on the exact cadence of the outage that
                            scattered them.
  :class:`RetryPolicy`    — attempts + deadline + backoff + on-retry hook
                            around any callable.
  :class:`CircuitBreaker` — closed/open/half-open quarantine for a
                            repeatedly-failing target, so a dead server
                            is probed occasionally instead of re-tried in
                            rotation on every call.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Optional, Tuple, Type

__all__ = ["Backoff", "RetryPolicy", "CircuitBreaker", "RetryError"]


class RetryError(Exception):
    """Deadline/attempts exhausted without the operation succeeding and
    without a terminal exception to re-raise (loop-style use)."""


class Backoff:
    """Decorrelated jitter: ``sleep = min(cap, uniform(base, prev * 3))``
    (the AWS "exponential backoff and jitter" result — better tail
    behavior than full-jitter-on-exponential under contention). Not
    thread-safe; each retrying call site owns one."""

    def __init__(self, base: float = 0.05, cap: float = 5.0,
                 rng: Optional[random.Random] = None):
        if base <= 0 or cap < base:
            raise ValueError("need 0 < base <= cap")
        self.base = base
        self.cap = cap
        self._rng = rng or random
        self._prev = 0.0

    def next(self) -> float:
        prev = self._prev if self._prev > 0 else self.base
        delay = min(self.cap, self._rng.uniform(self.base, prev * 3))
        self._prev = delay
        return delay

    def reset(self) -> None:
        self._prev = 0.0


class RetryPolicy:
    """Retry a callable under an attempts bound, a wall-clock deadline,
    and a backoff sequence.

    ``sleep`` is injectable for two reasons: tests, and shutdown-aware
    call sites — pass a ``threading.Event.wait`` bound method and a set
    event aborts the retry loop immediately (the loop treats a truthy
    sleep return as "stop now")."""

    def __init__(self, max_attempts: Optional[int] = 3,
                 deadline: Optional[float] = None,
                 backoff: Optional[Backoff] = None,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 should_retry: Optional[
                     Callable[[BaseException], bool]] = None,
                 on_retry: Optional[
                     Callable[[BaseException, int, float], None]] = None,
                 sleep: Callable[[float], Any] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 trace_events: bool = True):
        if max_attempts is None and deadline is None:
            raise ValueError("need max_attempts or deadline (or both)")
        self.max_attempts = max_attempts
        self.deadline = deadline
        self.backoff = backoff or Backoff()
        self.retry_on = retry_on
        self.should_retry = should_retry
        self.on_retry = on_retry
        self.sleep = sleep
        self.clock = clock
        # High-frequency POLL-style policies (ms-cadence waits under a
        # deadline) must opt out: one lagging wait would otherwise append
        # hundreds of "retry" events to the active span.
        self.trace_events = trace_events

    def call(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        """Run ``fn`` until it returns, the retry budget runs out (the
        last exception re-raises), or an exception outside ``retry_on`` /
        rejected by ``should_retry`` surfaces immediately."""
        self.backoff.reset()
        deadline_at = (self.clock() + self.deadline
                       if self.deadline is not None else None)
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except self.retry_on as exc:
                if self.should_retry is not None \
                        and not self.should_retry(exc):
                    raise
                if self.max_attempts is not None \
                        and attempt >= self.max_attempts:
                    raise
                delay = self.backoff.next()
                if deadline_at is not None:
                    remaining = deadline_at - self.clock()
                    if remaining <= 0:
                        raise
                    delay = min(delay, remaining)
                # Resilience <-> tracing: every retry of a traced
                # operation lands on its active span (one truthiness
                # check when tracing is disarmed).
                if self.trace_events:
                    from nomad_tpu.telemetry import trace as _trace

                    _trace.add_event("retry", attempt=attempt,
                                     error=type(exc).__name__,
                                     delay=round(delay, 4))
                if self.on_retry is not None:
                    self.on_retry(exc, attempt, delay)
                if self.sleep(delay):
                    raise  # shutdown-aware sleep asked us to stop


class CircuitBreaker:
    """Per-target failure quarantine (reference intent: rpcproxy marking
    servers failed and rebalancing away — here with an explicit
    open/half-open probe cycle so a dead server costs one connect timeout
    per ``reset_timeout``, not one per call).

    closed     — all calls allowed; ``failure_threshold`` consecutive
                 failures trips to open.
    open       — calls refused until ``reset_timeout`` elapses.
    half-open  — one probe call allowed through; success closes the
                 breaker, failure re-opens it (and restarts the timer).

    Thread-safe; ``allow()`` + ``record_success()/record_failure()`` are
    the whole surface."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(self, failure_threshold: int = 3,
                 reset_timeout: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _maybe_half_open_locked(self) -> None:
        if (self._state == self.OPEN
                and self.clock() - self._opened_at >= self.reset_timeout):
            self._state = self.HALF_OPEN
            self._probing = False

    def allow(self) -> bool:
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN and not self._probing:
                self._probing = True  # exactly one concurrent probe
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                # Failed probe: straight back to open, timer restarted.
                self._state = self.OPEN
                self._opened_at = self.clock()
                self._probing = False
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._state = self.OPEN
                self._opened_at = self.clock()
