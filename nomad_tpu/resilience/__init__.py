"""Resilience subsystem: named fault-injection sites (failpoints), the
unified retry/backoff policy, and the declarative chaos-schedule harness.

The three pieces compose: production code paths call
``failpoints.fire("site.name")`` at their failure seams and wrap remote
calls in one shared :class:`~nomad_tpu.resilience.retry.RetryPolicy`;
chaos schedules arm failpoints on a timeline and assert the cluster
invariants afterwards. Everything is a no-op until a failpoint is armed
(env var, Python API, or the /v1/agent/debug/faults endpoint).
"""

from .failpoints import (  # noqa: F401
    FailpointError,
    arm,
    arm_from_env,
    arm_from_spec,
    disarm,
    disarm_all,
    fire,
    known_sites,
    snapshot,
)
from .retry import Backoff, CircuitBreaker, RetryPolicy  # noqa: F401
