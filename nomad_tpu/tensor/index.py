"""TensorIndex: keeps the device-resident NodeTensor in sync with the store.

Subscribes to StateStore change events and applies delta updates (node
upserts, alloc usage transitions) to the NodeTensor — the tensor analogue of
go-memdb's indexing, and the mechanism that keeps scheduling from ever
re-shipping the full node table to the device (SURVEY §7.3).

An alloc contributes usage while non-terminal; transitions are derived from
(old, new) pairs so the accounting is exact.
"""

from __future__ import annotations

from typing import Optional

from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import Allocation, Node

import numpy as np

from .node_table import NodeTensor, alloc_vec


class TensorIndex:
    def __init__(self, nt: Optional[NodeTensor] = None):
        self.nt = nt or NodeTensor()
        # True when subscribed to a store's change feed (stays in sync and
        # must not be discarded on state refresh).
        self.attached = False
        # Mirrors ServerConfig.host_placement: False forces every stack
        # sharing this index onto the device kernels, including the
        # per-eval slow path (the multichip dry run relies on it).
        self.allow_host_select = True

    @staticmethod
    def attach(store: StateStore) -> "TensorIndex":
        """Production mode: subscribe to store changes and stay in sync."""
        idx = TensorIndex()
        idx.attached = True
        for node in store.nodes():
            idx.nt.upsert_node(node)
        for alloc in store.allocs():
            if not alloc.terminal_status():
                idx.nt.add_alloc_usage(alloc)
        # The index object itself is the listener: _emit prefers its
        # on_change_batch; __call__ keeps the per-event contract.
        store.add_change_listener(idx)
        return idx

    @staticmethod
    def from_state(state) -> "TensorIndex":
        """One-shot build from any read API (snapshot) — test/simple mode."""
        idx = TensorIndex()
        for node in state.nodes():
            idx.nt.upsert_node(node)
        for alloc in state.allocs():
            if not alloc.terminal_status():
                idx.nt.add_alloc_usage(alloc)
        return idx

    def _on_change(self, kind: str, old, new) -> None:
        if kind == "node":
            self._on_node(old, new)
        elif kind == "alloc":
            self._on_alloc(old, new)

    # Listener protocol: callable per-event, batch-capable via
    # on_change_batch (preferred by state_store._emit).
    __call__ = _on_change

    def on_change_batch(self, events) -> None:
        """Batch form the state store prefers (state_store._emit): alloc
        usage transitions collapse into one scatter-add under one tensor
        lock; node events keep their per-event path (rare)."""
        node_ids = []
        vecs = []
        for kind, old, new in events:
            if kind == "node":
                self._on_node(old, new)
                continue
            if kind != "alloc":
                continue
            was = old is not None and not old.terminal_status()
            now = new is not None and not new.terminal_status()
            if was:
                node_ids.append(old.NodeID)
                vecs.append(-alloc_vec(old))
            if now:
                node_ids.append(new.NodeID)
                vecs.append(alloc_vec(new))
        if node_ids:
            self.nt.apply_usage_deltas(
                node_ids, np.stack(vecs).astype(np.float32))

    def _on_node(self, old: Optional[Node], new: Optional[Node]) -> None:
        if new is None:
            if old is not None:
                self.nt.remove_node(old.ID)
            return
        self.nt.upsert_node(new)

    def _on_alloc(self, old: Optional[Allocation], new: Optional[Allocation]) -> None:
        was_counted = old is not None and not old.terminal_status()
        now_counted = new is not None and not new.terminal_status()
        if was_counted and not now_counted:
            self.nt.remove_alloc_usage(old)
        elif not was_counted and now_counted:
            self.nt.add_alloc_usage(new)
        elif was_counted and now_counted:
            # Resources may have changed (in-place update): re-account.
            self.nt.remove_alloc_usage(old)
            self.nt.add_alloc_usage(new)
