"""TensorIndex: keeps the device-resident NodeTensor in sync with the store.

Subscribes to StateStore change events and applies delta updates (node
upserts, alloc usage transitions) to the NodeTensor — the tensor analogue of
go-memdb's indexing, and the mechanism that keeps scheduling from ever
re-shipping the full node table to the device (SURVEY §7.3).

An alloc contributes usage while non-terminal; transitions are derived from
(old, new) pairs so the accounting is exact.
"""

from __future__ import annotations

import threading
from typing import Optional

from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import Allocation, Node

import numpy as np

from .node_table import NodeTensor, alloc_vec, resources_vec

# shared_elig's per-job view caches are unbounded across a long-lived
# server (one entry per job id ever swept); past this many entries the
# views are dropped and rebuilt lazily from the signature cache.
_ELIG_JOB_CACHE_CAP = 8192


class TensorIndex:
    def __init__(self, nt: Optional[NodeTensor] = None):
        self.nt = nt or NodeTensor()
        # True when subscribed to a store's change feed (stays in sync and
        # must not be discarded on state refresh).
        self.attached = False
        # Mirrors ServerConfig.host_placement: False forces every stack
        # sharing this index onto the device kernels, including the
        # per-eval slow path (the multichip dry run relies on it).
        self.allow_host_select = True
        # System-sweep eligibility: ONE ClassEligibility over the whole
        # node table, shared by every system evaluation until the node
        # population changes (nt.node_version). Building it walks every
        # node once; without the cache a 50-job system storm pays that
        # O(cluster) walk 50 times.
        self._elig_lock = threading.Lock()
        self._elig_cache: Optional[tuple] = None  # (node_version, elig)

    def shared_elig(self, state):
        """Shared, node-version-keyed ClassEligibility over ALL table rows.

        Safe to share across jobs and DCs: the datacenter is part of the
        computed class (structs/node_class.py), so any class representative
        is exact for every member, and per-job masks AND against the
        caller's ready/DC row mask. Concurrent workers may race to build
        one — the loser's copy is simply dropped (values are identical)."""
        from .constraints import ClassEligibility

        with self._elig_lock:
            ver = self.nt.node_version
            cached = self._elig_cache
            if cached is not None and cached[0] == ver:
                elig = cached[1]
                if len(elig._job_cache) > _ELIG_JOB_CACHE_CAP:
                    # The signature cache holds the actual [n_rows] mask
                    # arrays — clearing only the per-job views would keep
                    # every mask alive; all three regenerate on demand.
                    elig._job_cache.clear()
                    elig._tg_cache.clear()
                    elig._sig_cache.clear()
                return elig
        elig = ClassEligibility(self.nt, list(state.nodes()))
        with self._elig_lock:
            # Re-check: the population may have moved while we built.
            if self.nt.node_version == ver:
                self._elig_cache = (ver, elig)
        return elig

    def _seed_from(self, state) -> None:
        """Seed the tensor from any read API: every node a row, usage =
        the non-terminal allocs. The ONE copy of the seeding semantics
        (attach / from_state / on_restore all build through here)."""
        for node in state.nodes():
            self.nt.upsert_node(node)
        for alloc in state.allocs():
            if not alloc.terminal_status():
                self.nt.add_alloc_usage(alloc)

    @staticmethod
    def attach(store: StateStore) -> "TensorIndex":
        """Production mode: subscribe to store changes and stay in sync."""
        idx = TensorIndex()
        idx.attached = True
        idx._seed_from(store)
        # The index object itself is the listener: _emit prefers its
        # on_change_batch; __call__ keeps the per-event contract.
        store.add_change_listener(idx)
        return idx

    @staticmethod
    def from_state(state) -> "TensorIndex":
        """One-shot build from any read API (snapshot) — test/simple mode."""
        idx = TensorIndex()
        idx._seed_from(state)
        return idx

    def on_restore(self, store) -> None:
        """Listener hook fired by Restore.commit() after a snapshot
        restore swapped the store's tables wholesale: the incremental
        change feed never saw the staged writes, so the tensor rebuilds
        from the restored world. Row identities change (row_epoch bumps
        inside reset), forcing in-flight usage chains to rebase."""
        self.nt.reset()
        self._seed_from(store)

    def resync_usage(self, state) -> int:
        """Warm-failover usage re-seed: recompute every node's usage from
        the replicated store (reserved + live alloc vectors), correct any
        row that drifted, and reconcile membership (a node the change
        feed missed is upserted; a departed one is removed). Returns the
        number of corrected rows — a new leader term calls this before
        serving so its placement kernels never start on drifted usage."""
        nt = self.nt
        nodes = list(state.nodes())
        live_by_node = {}
        for alloc in state.allocs():
            if not alloc.terminal_status():
                live_by_node.setdefault(alloc.NodeID, []).append(alloc)
        fixed = 0
        with nt._lock:
            seen = set()
            for node in nodes:
                seen.add(node.ID)
                if node.ID not in nt.row_of:
                    nt.upsert_node(node)
                    fixed += 1
            for node_id in [n for n in nt.row_of if n not in seen]:
                nt.remove_node(node_id)
                fixed += 1
            for node in nodes:
                row = nt.row_of[node.ID]
                expected = resources_vec(node.Reserved).copy()
                for alloc in live_by_node.get(node.ID, ()):
                    expected += alloc_vec(alloc)
                if not np.allclose(nt.usage[row], expected, atol=1e-3):
                    nt.usage[row] = expected
                    nt._usage_dirty.add(row)
                    fixed += 1
        return fixed

    def _on_change(self, kind: str, old, new) -> None:
        if kind == "node":
            self._on_node(old, new)
        elif kind == "alloc":
            self._on_alloc(old, new)

    # Listener protocol: callable per-event, batch-capable via
    # on_change_batch (preferred by state_store._emit).
    __call__ = _on_change

    def on_sweep_batch(self, node_ids, rows, delta, epoch: int) -> None:
        """Columnar sweep-commit listener (state_store.apply_sweep_segment):
        the batch's per-row demand lands as ONE scatter-add. Row-addressed
        when the tensor epoch still matches emit time (no dict lookups at
        all); id-addressed otherwise (rows may have changed identity)."""
        delta = np.asarray(delta, dtype=np.float32)
        if rows is not None and self.nt.apply_row_usage_deltas(
                np.asarray(rows, dtype=np.int64), delta, epoch):
            return
        self.nt.apply_usage_deltas(list(node_ids), delta)

    def on_change_batch(self, events) -> None:
        """Batch form the state store prefers (state_store._emit): alloc
        usage transitions collapse into one scatter-add under one tensor
        lock; node events keep their per-event path (rare)."""
        node_ids = []
        vecs = []
        for kind, old, new in events:
            if kind == "node":
                self._on_node(old, new)
                continue
            if kind != "alloc":
                continue
            was = old is not None and not old.terminal_status()
            now = new is not None and not new.terminal_status()
            if was:
                node_ids.append(old.NodeID)
                vecs.append(-alloc_vec(old))
            if now:
                node_ids.append(new.NodeID)
                vecs.append(alloc_vec(new))
        if node_ids:
            self.nt.apply_usage_deltas(
                node_ids, np.stack(vecs).astype(np.float32))

    def _on_node(self, old: Optional[Node], new: Optional[Node]) -> None:
        if new is None:
            if old is not None:
                self.nt.remove_node(old.ID)
            return
        self.nt.upsert_node(new)

    def _on_alloc(self, old: Optional[Allocation], new: Optional[Allocation]) -> None:
        was_counted = old is not None and not old.terminal_status()
        now_counted = new is not None and not new.terminal_status()
        if was_counted and not now_counted:
            self.nt.remove_alloc_usage(old)
        elif not was_counted and now_counted:
            self.nt.add_alloc_usage(new)
        elif was_counted and now_counted:
            # Resources may have changed (in-place update): re-account.
            self.nt.remove_alloc_usage(old)
            self.nt.add_alloc_usage(new)
