"""Host-side constraint compiler: per-class evaluation, per-node gather.

Semantics mirror the reference's ConstraintChecker (reference:
scheduler/feasible.go:244-452): target interpolation (${node.*}, ${attr.*},
${meta.*}), operands (= == is, != not, lexical < <= > >=, version, regexp),
and the computed-class memoization with the unique.* escape hatch (reference:
scheduler/feasible.go:454-568, scheduler/context.go:150-331).

Regex/version work is not expressible in XLA; it runs here once per computed
node class (classes << nodes), yielding a [C] bool table that the node axis
gathers through class_ids — the tensorized form of the reference's
EvalEligibility cache.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nomad_tpu.structs import Constraint, Node, escaped_constraints
from nomad_tpu.structs.structs import ConstraintDistinctHosts
from nomad_tpu.structs.version import check_version_constraint

from .node_table import NodeTensor

_REGEX_CACHE: Dict[str, Optional[re.Pattern]] = {}


def resolve_target(target: str, node: Node):
    """Interpolate a constraint target against a node; returns (value, ok)."""
    if not target.startswith("${"):
        return target, True
    if target == "${node.unique.id}":
        return node.ID, True
    if target == "${node.datacenter}":
        return node.Datacenter, True
    if target == "${node.unique.name}":
        return node.Name, True
    if target == "${node.class}":
        return node.NodeClass, True
    if target.startswith("${attr."):
        attr = target[len("${attr."):]
        attr = attr[:-1] if attr.endswith("}") else attr
        if attr in node.Attributes:
            return node.Attributes[attr], True
        return None, False
    if target.startswith("${meta."):
        meta = target[len("${meta."):]
        meta = meta[:-1] if meta.endswith("}") else meta
        if meta in node.Meta:
            return node.Meta[meta], True
        return None, False
    return None, False


def check_constraint(operand: str, l_val, r_val) -> bool:
    """Operand evaluation (reference: feasible.go:327-350)."""
    if operand == ConstraintDistinctHosts:
        return True  # handled by the placement kernel, not per-node
    if operand in ("=", "==", "is"):
        return l_val == r_val
    if operand in ("!=", "not"):
        return l_val != r_val
    if operand in ("<", "<=", ">", ">="):
        if not isinstance(l_val, str) or not isinstance(r_val, str):
            return False
        return {"<": l_val < r_val, "<=": l_val <= r_val,
                ">": l_val > r_val, ">=": l_val >= r_val}[operand]
    if operand == "version":
        # The reference converts an integer lVal to its decimal string
        # (feasible.go checkVersionConstraint's int fallback).
        if isinstance(l_val, int) and not isinstance(l_val, bool):
            l_val = str(l_val)
        if not isinstance(l_val, str) or not isinstance(r_val, str):
            return False
        return check_version_constraint(l_val, r_val)
    if operand == "regexp":
        if not isinstance(l_val, str) or not isinstance(r_val, str):
            return False
        pat = _REGEX_CACHE.get(r_val, False)
        if pat is False:
            try:
                pat = re.compile(r_val)
            except re.error:
                pat = None
            _REGEX_CACHE[r_val] = pat
        return pat is not None and bool(pat.search(l_val))
    return False


def constraint_sig(constraints: Sequence[Constraint]) -> tuple:
    """Value identity of a constraint list. THE single definition: every
    cache keyed on "same constraints" (class-eligibility masks, shared
    prepared batches) must use this so a future constraint field can't be
    forgotten in one of them."""
    return tuple((c.LTarget, c.Operand, c.RTarget) for c in constraints)


def node_meets_constraints(node: Node, constraints: Sequence[Constraint]) -> bool:
    for c in constraints:
        l_val, l_ok = resolve_target(c.LTarget, node)
        r_val, r_ok = resolve_target(c.RTarget, node)
        if not l_ok or not r_ok:
            return False
        if not check_constraint(c.Operand, l_val, r_val):
            return False
    return True


def node_has_drivers(node: Node, drivers: Sequence[str]) -> bool:
    """DriverChecker (reference: feasible.go:91-143): `driver.<name>` node
    attribute must parse as a true boolean — Go strconv.ParseBool
    semantics, so "1", "t", "T", "true", "TRUE", "True" all pass."""
    for d in drivers:
        raw = node.Attributes.get(f"driver.{d}", "")
        if raw not in ("1", "t", "T", "true", "TRUE", "True"):
            return False
    return True


class ClassEligibility:
    """Per-eval cache of class-level job/TG eligibility (the tensorized
    EvalEligibility, reference: scheduler/context.go:150-331).

    For each computed class we keep one representative node; job- and
    task-group-level constraints are evaluated once per class against the
    representative and cached. Escaped constraints (targets under unique.*)
    are evaluated per node. The result is a [N] bool mask over the node
    tensor's rows.
    """

    def __init__(self, nt: NodeTensor, nodes: Sequence[Node]):
        self.nt = nt
        self.representatives: Dict[int, Node] = {}
        self.nodes_by_row: Dict[int, Node] = {}
        for node in nodes:
            row = nt.row_of.get(node.ID)
            if row is None:
                continue
            self.nodes_by_row[row] = node
            cid = nt.class_vocab.get(node.ComputedClass)
            if cid is not None and cid not in self.representatives:
                self.representatives[cid] = node
        self._job_cache: Dict[str, Tuple[np.ndarray, bool]] = {}
        self._tg_cache: Dict[Tuple[str, str], np.ndarray] = {}
        # Cross-job memo keyed by the constraint SIGNATURE: a registration
        # storm of many jobs with identical constraints (the C1M shape) pays
        # the per-class evaluation once, not once per job. The per-job-id
        # caches above stay — blocked-eval reporting introspects them — but
        # they become views onto these shared entries.
        self._sig_cache: Dict[tuple, Tuple[np.ndarray, np.ndarray, bool]] = {}

    # ---- reporting for blocked evals (reference: Evaluation.ClassEligibility)
    def class_eligibility_report(self, mask_by_class: np.ndarray) -> Dict[str, bool]:
        out = {}
        for cid, ok in enumerate(mask_by_class):
            if cid < len(self.nt.class_names):
                out[self.nt.class_names[cid]] = bool(ok)
        return out

    def _class_table(self, constraints: Sequence[Constraint]) -> np.ndarray:
        """[C] bool: class representative satisfies the memoizable constraints."""
        n_classes = len(self.nt.class_names)
        table = np.zeros(n_classes, dtype=bool)
        for cid, rep in self.representatives.items():
            table[cid] = node_meets_constraints(rep, constraints)
        return table

    def _escaped_mask(self, constraints: Sequence[Constraint]) -> Optional[np.ndarray]:
        """[N] bool over rows for constraints that escape class memoization."""
        if not constraints:
            return None
        mask = np.zeros(self.nt.n_rows, dtype=bool)
        for row, node in self.nodes_by_row.items():
            mask[row] = node_meets_constraints(node, constraints)
        return mask

    @staticmethod
    def _sig(constraints: Sequence[Constraint],
             drivers: Sequence[str] = ()) -> tuple:
        return (constraint_sig(constraints), tuple(drivers))

    def job_mask(self, job_id: str, constraints: Sequence[Constraint],
                 ) -> Tuple[np.ndarray, np.ndarray, bool]:
        """Returns ([N] row mask, [C] class table, escaped?)."""
        cached = self._job_cache.get(job_id)
        if cached is None:
            sig = ("job",) + self._sig(constraints)
            cached = self._sig_cache.get(sig)
            if cached is None:
                esc = escaped_constraints(list(constraints))
                memo = [c for c in constraints if c not in esc]
                table = self._class_table(memo)
                mask = table[self.nt.class_ids]
                esc_mask = self._escaped_mask(esc)
                if esc_mask is not None:
                    mask = mask & esc_mask
                cached = (mask, table, bool(esc))
                self._sig_cache[sig] = cached
            self._job_cache[job_id] = cached
        return cached

    def tg_mask(self, job_id: str, tg_name: str,
                constraints: Sequence[Constraint],
                drivers: Sequence[str]) -> Tuple[np.ndarray, np.ndarray, bool]:
        """Task-group-level mask: constraints + driver availability."""
        key = (job_id, tg_name)
        cached = self._tg_cache.get(key)
        if cached is None:
            sig = ("tg",) + self._sig(constraints, drivers)
            cached = self._sig_cache.get(sig)
            if cached is None:
                esc = escaped_constraints(list(constraints))
                memo = [c for c in constraints if c not in esc]
                n_classes = len(self.nt.class_names)
                table = np.zeros(n_classes, dtype=bool)
                for cid, rep in self.representatives.items():
                    table[cid] = (node_meets_constraints(rep, memo)
                                  and node_has_drivers(rep, drivers))
                mask = table[self.nt.class_ids]
                esc_mask = self._escaped_mask(esc)
                if esc_mask is not None:
                    mask = mask & esc_mask
                cached = (mask, table, bool(esc))
                self._sig_cache[sig] = cached
            self._tg_cache[key] = cached
        return cached
