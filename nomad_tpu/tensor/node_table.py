"""Device-resident node table with incremental updates.

Columns (float32, resource dims R=5): cpu MHz, memory MB, disk MB, iops,
network mbits. Three persistent arrays:

  capacity  [N, R]  total node resources (the fit bound — reserved counts as
                    usage, matching reference AllocsFit, funcs.go:44-100)
  score_cap [N, 2]  (cpu, mem) minus reserved — the ScoreFit denominator
                    (funcs.go:105-117)
  usage     [N, R]  reserved + sum of non-terminal committed allocs

Rows are stable per node for the node's lifetime (free-list reuse), the array
is padded to power-of-two buckets so jit caches stay warm, and host numpy
mirrors are authoritative: device copies are refreshed by row-scatter of dirty
rows just before a scheduling kernel runs (SURVEY §7.3: keep the node tensor
resident, delta-scatter updates, never re-ship the table).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from nomad_tpu.analysis import guarded_by
from nomad_tpu.structs import Allocation, Node, Resources
from nomad_tpu.structs.structs import NodeStatusReady

RES_DIMS = 5  # cpu, mem, disk, iops, mbits
DIM_NAMES = ("cpu", "memory", "disk", "iops", "bandwidth")
_MIN_CAP = 64
# Dirty-row device refresh chunks (fixed shapes -> bounded compile count:
# trickle, steady, storm, and rebase-after-storm buckets).
_REFRESH_CHUNKS = (8, 128, 2048, 16384)


def resources_vec(r: Optional[Resources]) -> np.ndarray:
    out = np.zeros(RES_DIMS, dtype=np.float32)
    if r is None:
        return out
    out[0] = r.CPU
    out[1] = r.MemoryMB
    out[2] = r.DiskMB
    out[3] = r.IOPS
    out[4] = sum(n.MBits for n in r.Networks)
    return out


def alloc_vec(alloc: Allocation) -> np.ndarray:
    """Resource vector of an allocation, memoized on the instance: the
    commit path reads it three times per alloc (usage listener, vectorized
    plan verify, optimistic overlay). Allocations are value-frozen once
    built — anything that changes resources replaces the object — so the
    memo cannot go stale. Callers must not mutate the returned array."""
    vec = getattr(alloc, "_resvec_cache", None)
    if vec is not None:
        return vec
    if alloc.Resources is not None:
        out = resources_vec(alloc.Resources)
    else:
        out = np.zeros(RES_DIMS, dtype=np.float32)
        for r in alloc.TaskResources.values():
            out += resources_vec(r)
    alloc._resvec_cache = out
    return out


class NodeTensor:
    """Mutable host mirror + lazily synced device arrays of the node table."""

    def __init__(self, capacity_hint: int = _MIN_CAP):
        n = max(_MIN_CAP, _next_pow2(capacity_hint))
        self._lock = threading.RLock()
        self.n_rows = n
        self.capacity = np.zeros((n, RES_DIMS), dtype=np.float32)
        self.score_cap = np.ones((n, 2), dtype=np.float32)  # avoid div-by-0
        self.usage = np.zeros((n, RES_DIMS), dtype=np.float32)
        self.ready = np.zeros(n, dtype=bool)
        self.class_ids = np.zeros(n, dtype=np.int32)
        self.dc_ids = np.full(n, -1, dtype=np.int32)

        self.row_of: Dict[str, int] = {}
        self.node_of: List[Optional[str]] = [None] * n
        # Lazily built object-dtype mirror of node_of for vectorized
        # row->node-ID gathers (the windowed collect maps a whole window's
        # chosen rows in one fancy index instead of a Python lookup per
        # placement). Invalidated whenever a row's identity changes.
        self._node_id_arr: Optional[np.ndarray] = None
        self._free: List[int] = list(range(n - 1, -1, -1))
        self._reserved_cache: Dict[str, np.ndarray] = {}
        # Bumped whenever a row's IDENTITY changes (node removed, row freed
        # for reuse, table grown): a device-side usage chain built against an
        # older epoch may carry a departed node's usage on a reused row and
        # must rebase (shape checks alone miss free-list reuse).
        self.row_epoch = 0
        # Bumped on ANY node-set change (upsert, readiness flip, removal):
        # the invalidation key for caches derived from the node population —
        # the shared sweep eligibility (TensorIndex.shared_elig) and the
        # system scheduler's memoized ready-node list. Coarser than
        # row_epoch, which only tracks identity changes.
        self.node_version = 0

        # Vocabularies
        self.class_vocab: Dict[str, int] = {}
        self.class_names: List[str] = []
        self.dc_vocab: Dict[str, int] = {}
        self.dc_names: List[str] = []

        # Device sync state. Two dirty tiers: rows whose capacity/readiness
        # changed (node upserts — must always refresh) vs rows where only
        # USAGE moved (alloc commits). A caller that overrides usage with a
        # device-side chain can skip the usage tier entirely, turning the
        # steady-state storm refresh (one blocking host->device RTT per
        # window) into zero transfers.
        self._dirty_rows: Set[int] = set()
        self._usage_dirty: Set[int] = set()
        self._resized = True
        self._device: Optional[dict] = None
        # Multi-chip: when set, device arrays shard their node axis over the
        # mesh (jax.sharding) and every consumer kernel runs SPMD with XLA
        # inserting the ICI collectives (SURVEY §7.1: the node axis IS the
        # sharded tensor axis). None = single-device arrays, byte-identical
        # to the pre-mesh path.
        self.mesh = None
        self._node_sharding = None

    # --------------------------------------------------------------- mesh
    def set_mesh(self, mesh) -> None:
        """Shard the node axis of the device arrays over `mesh` (a 1-D
        jax.sharding.Mesh). Must be a power-of-two device count: rows are
        padded to powers of two (>= 64), so divisibility is guaranteed for
        any pow2 mesh up to 64 devices and preserved across table growth.
        Call before serving traffic; existing device arrays are rebuilt."""
        if mesh is None:
            self.mesh = None
            self._node_sharding = None
            self._device = None
            self._resized = True
            return
        n_dev = mesh.devices.size
        if n_dev & (n_dev - 1):
            raise ValueError(
                f"scheduling mesh needs a power-of-two device count, got "
                f"{n_dev}")
        if self.n_rows % n_dev:
            raise ValueError(
                f"node axis ({self.n_rows}) not divisible by mesh ({n_dev})")
        from jax.sharding import NamedSharding, PartitionSpec

        axis = mesh.axis_names[0]
        self.mesh = mesh
        self._node_sharding = NamedSharding(mesh, PartitionSpec(axis))
        self._device = None  # rebuild sharded on next device_arrays()
        self._resized = True

    def _put(self, arr: np.ndarray):
        """Upload one full array, sharded over the mesh when set."""
        import jax
        import jax.numpy as jnp

        if self._node_sharding is not None:
            return jax.device_put(arr, self._node_sharding)
        return jnp.asarray(arr)

    # ------------------------------------------------------------- vocab
    def class_id(self, computed_class: str) -> int:
        cid = self.class_vocab.get(computed_class)
        if cid is None:
            cid = len(self.class_names)
            self.class_vocab[computed_class] = cid
            self.class_names.append(computed_class)
        return cid

    def dc_id(self, dc: str) -> int:
        did = self.dc_vocab.get(dc)
        if did is None:
            did = len(self.dc_names)
            self.dc_vocab[dc] = did
            self.dc_names.append(dc)
        return did

    # ------------------------------------------------------------ updates
    def upsert_node(self, node: Node) -> None:
        with self._lock:
            row = self.row_of.get(node.ID)
            if row is None:
                row = self._alloc_row()
                self.row_of[node.ID] = row
                self.node_of[row] = node.ID
                self._node_id_arr = None
                self.usage[row] = 0.0
            cap = resources_vec(node.Resources)
            reserved = resources_vec(node.Reserved)
            self.capacity[row] = cap
            # ScoreFit denominator: total minus reserved for cpu/mem. May be
            # zero; the kernel reproduces Go's Inf/NaN division semantics.
            self.score_cap[row] = cap[:2] - reserved[:2]
            # Reserved is baseline usage; preserve the alloc-usage component.
            self.usage[row] = self.usage[row] - self._reserved_of(node.ID) + reserved
            self._reserved_cache[node.ID] = reserved
            self.ready[row] = (node.Status == NodeStatusReady) and not node.Drain
            self.class_ids[row] = self.class_id(node.ComputedClass)
            self.dc_ids[row] = self.dc_id(node.Datacenter)
            self._dirty_rows.add(row)
            self.node_version += 1

    def _reserved_of(self, node_id: str) -> np.ndarray:
        return self._reserved_cache.get(node_id, np.zeros(RES_DIMS, dtype=np.float32))

    def set_node_readiness(self, node_id: str, ready: bool) -> None:
        with self._lock:
            row = self.row_of.get(node_id)
            if row is None:
                return
            self.ready[row] = ready
            self._dirty_rows.add(row)
            self.node_version += 1

    def remove_node(self, node_id: str) -> None:
        with self._lock:
            row = self.row_of.pop(node_id, None)
            if row is None:
                return
            self.node_of[row] = None
            self._node_id_arr = None
            self.capacity[row] = 0.0
            self.score_cap[row] = 1.0
            self.usage[row] = 0.0
            self.ready[row] = False
            self.dc_ids[row] = -1
            self._free.append(row)
            self._dirty_rows.add(row)
            self._reserved_cache.pop(node_id, None)
            self.row_epoch += 1
            self.node_version += 1

    def reset(self) -> None:
        """Drop every row in place (a snapshot restore replaced the world
        and the incremental feed never saw the staged writes). Mirrors are
        zeroed, all rows freed, and BOTH epochs bump so every derived
        consumer — usage chains, shared eligibility, cached row-id arrays
        — rebuilds against the restored population. Mesh/sharding and the
        vocabularies survive: ids are append-only and stay valid."""
        with self._lock:
            self.capacity[:] = 0.0
            self.score_cap[:] = 1.0
            self.usage[:] = 0.0
            self.ready[:] = False
            self.class_ids[:] = 0
            self.dc_ids[:] = -1
            self.row_of.clear()
            self.node_of = [None] * self.n_rows
            self._node_id_arr = None
            self._free = list(range(self.n_rows - 1, -1, -1))
            self._reserved_cache.clear()
            self._dirty_rows.clear()
            self._usage_dirty.clear()
            self._resized = True  # full re-upload on next device_arrays
            self.row_epoch += 1
            self.node_version += 1

    def add_alloc_usage(self, alloc: Allocation) -> None:
        self._apply_usage(alloc, +1.0)

    def remove_alloc_usage(self, alloc: Allocation) -> None:
        self._apply_usage(alloc, -1.0)

    def _apply_usage(self, alloc: Allocation, sign: float) -> None:
        with self._lock:
            row = self.row_of.get(alloc.NodeID)
            if row is None:
                return
            self.usage[row] += sign * alloc_vec(alloc)
            self._usage_dirty.add(row)

    def apply_row_usage_deltas(self, rows: np.ndarray, vecs: np.ndarray,
                               epoch: int) -> bool:
        """Row-addressed batch usage transition: a columnar sweep commit
        carries its node ROWS from emit time, so when no row changed
        identity since (`epoch` still current) the whole batch lands as
        one scatter-add with ZERO per-node dict lookups. Returns False —
        apply nothing — when the epoch moved or rows are out of bounds;
        the caller falls back to the id-addressed path."""
        with self._lock:
            if len(rows) == 0:
                return True
            if epoch != self.row_epoch:
                return False
            if int(rows[-1]) >= self.n_rows:  # rows are sorted ascending
                return False
            np.add.at(self.usage, rows, vecs)
            self._usage_dirty.update(rows.tolist())
            return True

    def apply_usage_deltas(self, node_ids: Sequence[str],
                           vecs: np.ndarray) -> None:
        """Batched usage transitions under ONE lock: a committed plan's 50
        allocs become one scatter-add instead of 50 lock/indexing rounds
        (the plan applier is on the scheduling critical path)."""
        with self._lock:
            rows = []
            keep = []
            for k, nid in enumerate(node_ids):
                row = self.row_of.get(nid)
                if row is not None:
                    rows.append(row)
                    keep.append(k)
            if not rows:
                return
            rows_arr = np.asarray(rows, dtype=np.int64)
            np.add.at(self.usage, rows_arr, vecs[keep])
            self._usage_dirty.update(rows)

    # ------------------------------------------------------------ row mgmt
    def _alloc_row(self) -> int:
        if not self._free:
            self._grow()
        return self._free.pop()

    def _grow(self) -> None:
        old = self.n_rows
        new = old * 2
        self.capacity = _grow2(self.capacity, new)
        self.score_cap = _grow2(self.score_cap, new, fill=1.0)
        self.usage = _grow2(self.usage, new)
        self.ready = _grow1(self.ready, new, fill=False)
        self.class_ids = _grow1(self.class_ids, new, fill=0)
        self.dc_ids = _grow1(self.dc_ids, new, fill=-1)
        self.node_of.extend([None] * (new - old))
        self._free.extend(range(new - 1, old - 1, -1))
        self.n_rows = new
        self._resized = True
        self.row_epoch += 1

    # --------------------------------------------------------- device sync
    def device_arrays(self, skip_usage: bool = False) -> dict:
        """Return jax device arrays, refreshing dirty rows via scatter.

        skip_usage=True refreshes only rows whose capacity/readiness changed
        and leaves usage-only dirty rows queued — valid ONLY for callers that
        override the usage input with their own device-side chain (the
        pipelined worker mid-storm). The queued rows are flushed by the next
        full call."""
        ensure_backend()

        with self._lock:
            pending = (set(self._dirty_rows) if skip_usage
                       else self._dirty_rows | self._usage_dirty)
            if self._device is None or self._resized:
                if (self._node_sharding is not None
                        and self.n_rows % self.mesh.devices.size):
                    raise ValueError(
                        f"node axis ({self.n_rows}) not divisible by mesh "
                        f"({self.mesh.devices.size})")
                self._device = {
                    "capacity": self._put(self.capacity),
                    "score_cap": self._put(self.score_cap),
                    "usage": self._put(self.usage),
                }
                self._resized = False
                self._dirty_rows.clear()
                self._usage_dirty.clear()
            elif pending:
                rows = np.fromiter(pending, dtype=np.int32)
                # Fixed-size scatter chunks (tail padded by repeating the
                # first row — sets are idempotent): ONE compiled refresh
                # program ever, instead of one per distinct dirty-row count.
                # A mid-serving XLA compile blocks the scheduling path for
                # hundreds of ms, which dwarfs any transfer saving.
                d = self._device
                # Smallest bucket that fits: compile count stays bounded
                # without shipping a storm-sized transfer when one heartbeat
                # dirtied one row.
                size = _REFRESH_CHUNKS[-1]
                for candidate in _REFRESH_CHUNKS:
                    if len(rows) <= candidate:
                        size = candidate
                        break
                for i in range(0, len(rows), size):
                    chunk = rows[i:i + size]
                    if len(chunk) < size:
                        chunk = np.concatenate(
                            [chunk, np.full(size - len(chunk),
                                            chunk[0], dtype=np.int32)])
                    # ONE host->device transfer per chunk: rows + all three
                    # column groups ride a single packed array and split
                    # device-side (transfers are blocking RTTs on
                    # remote-attached TPUs; dispatches are async).
                    packed = np.concatenate(
                        [chunk[:, None].astype(np.float32),
                         self.capacity[chunk], self.score_cap[chunk],
                         self.usage[chunk]], axis=1)
                    d["capacity"], d["score_cap"], d["usage"] = \
                        _scatter_refresh(d["capacity"], d["score_cap"],
                                         d["usage"], packed)
                # The scatter writes all three column groups, so refreshed
                # rows are current in BOTH tiers regardless of why they were
                # dirty.
                self._dirty_rows -= pending
                self._usage_dirty -= pending
            return dict(self._device)

    def warm_device(self) -> None:
        """Precompile every dirty-row refresh program for the current table
        size. Each _REFRESH_CHUNKS bucket is a distinct XLA program; the
        first dirty set that lands in a cold bucket otherwise pays its
        compile (hundreds of ms) in the middle of serving. The warm scatter
        rewrites row 0 with its own current values — a no-op — so this is
        safe to call at any time; servers call it once the node table has
        reached steady size (e.g. after initial cluster sync)."""
        with self._lock:
            self.device_arrays()
            d = self._device
            for size in _REFRESH_CHUNKS:
                chunk = np.zeros(size, dtype=np.int32)
                packed = np.concatenate(
                    [chunk[:, None].astype(np.float32),
                     self.capacity[chunk], self.score_cap[chunk],
                     self.usage[chunk]], axis=1)
                d["capacity"], d["score_cap"], d["usage"] = \
                    _scatter_refresh(d["capacity"], d["score_cap"],
                                     d["usage"], packed)

    # ------------------------------------------------------------- queries
    def node_id_array(self) -> np.ndarray:
        """Object-dtype [n_rows] mirror of node_of, rebuilt lazily when a
        row's identity changes. Callers get a SNAPSHOT: a node removed
        after the return may still appear — the same benign race as a live
        node_of read per placement; the plan applier's re-verification
        against committed state owns the outcome either way."""
        with self._lock:
            arr = self._node_id_arr
            if arr is None or len(arr) != self.n_rows:
                arr = np.empty(self.n_rows, dtype=object)
                arr[:] = self.node_of
                self._node_id_arr = arr
            return arr

    def rows_for(self, node_ids: Sequence[str]) -> np.ndarray:
        return np.array([self.row_of[i] for i in node_ids], dtype=np.int32)

    def snapshot_rows(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Consistent (usage, capacity) copies of the given rows, taken under
        the tensor lock. Alloc commits mutate usage rows IN PLACE
        (_apply_usage), so a lock-free reader could see a torn row — half a
        usage vector before an in-flight `+=`, half after. Fancy indexing
        copies, so the returned arrays are immune to later mutation."""
        with self._lock:
            return self.usage[rows], self.capacity[rows]

    def eligibility_mask(self, dc_ids: Sequence[int],
                        class_ok: Optional[np.ndarray]) -> np.ndarray:
        """ready & datacenter-membership & per-class eligibility, as [N] bool."""
        with self._lock:
            mask = self.ready.copy()
            if dc_ids is not None:
                mask &= np.isin(self.dc_ids, np.asarray(list(dc_ids), dtype=np.int32))
            if class_ok is not None:
                mask &= class_ok[self.class_ids]
            return mask


# Force a chain rebase after this many chained windows: the chain misses
# slow-path/fallback commits (undercount — the applier catches any
# oversubscription) and evictions (overcount — spurious blocked evals), so
# its drift is bounded even through a storm that never pauses.
REBASE_WINDOWS = 256


class ChainLease:
    """One window's exclusive hold on the shared device usage chain.

    Returned by :meth:`ChainArbiter.acquire`; carries the usage array the
    window's kernels must chain on (``None`` = committed usage from the
    table), the arbiter's taint sequence at acquire time (windows in
    flight compare it at finish to detect phantom usage raised under
    them), and the node-table row epoch observed at chain-validation time
    (a row changing identity mid-dispatch must still rebase the NEXT
    window). The holder ends the lease with exactly one of
    :meth:`ChainArbiter.publish` (fast evals dispatched — the window is
    now in flight) or :meth:`ChainArbiter.abort` (nothing dispatched)."""

    __slots__ = ("chain", "taint_seq", "epoch", "rebased", "released",
                 "seq")

    def __init__(self, chain, taint_seq: int, epoch: int, rebased: bool):
        self.chain = chain
        self.taint_seq = taint_seq
        self.epoch = epoch
        self.rebased = rebased
        self.released = False  # publish/abort happened (one-shot)
        self.seq = 0           # chain position, assigned at publish


class ChainArbiter:
    """Arbiter of the cross-worker device usage chain.

    N pipelined workers place optimistically against one node table; their
    windows chain each kernel on the previous window's ``usage_after`` so
    every placement sees every placement dispatched before it — regardless
    of which worker dispatched it. Without arbitration, two workers each
    keep a PRIVATE chain from committed usage: neither sees the other's
    in-flight placements, both argmax onto the same best rows, and the
    plan applier bounces half the plans as partial commits (the measured
    2-worker collapse). The arbiter serializes only the chain handoff:

      * ``acquire`` — block until no other window is mid-dispatch, decide
        whether the tail is still valid (taint/epoch/depth/drained checks,
        previously per-worker ``_usage_chain``), and hand the tail out as
        a :class:`ChainLease`.
      * ``publish`` — install the window's ``usage_after`` as the new
        tail and count the window in flight; the next ``acquire`` (any
        worker) chains on it.
      * ``taint`` / ``finish_window`` — a window that ends with stale or
        fallback records left phantom usage in the chain; the taint bumps
        the sequence (in-flight windows quarantine their squeezed evals
        at finish) and marks the tail dirty so the next ``acquire`` drains
        ALL lease holders — across every worker — and rebases onto
        committed state coherently.

    Dispatch serialization is not a scaling loss: the dispatch stage is
    GIL-bound Python, so two workers' dispatches could not run
    concurrently anyway — the win is that their drain fetches (GIL
    released) and build stages interleave on a chain that stays
    coherent.

    On a sharded mesh the tail is a :class:`kernels.MeshChain` — the
    node-sharded usage PLUS a lead-device pending winner ring — not a
    plain array. The arbiter treats it opaquely: ``shape`` drives the
    resize/epoch rebase checks, publish/acquire hand it through, and a
    rebase simply drops it (committed state lives in the node tensor;
    the ring's placements either committed through plans or are being
    redelivered). Consumers that need real rows (eviction overlays,
    the monolithic-scan fallback, numpy readers) call
    ``materialize()``, which folds the ring into the sharded usage."""

    _concurrency = guarded_by(
        "_cond", "_tail", "_tail_epoch", "_holder", "_pending",
        "_windows_since_rebase", "_dirty", "_taint_seq", "_published_seq",
        "_settled_seq")

    def __init__(self, nt: NodeTensor, rebase_windows: int = REBASE_WINDOWS):
        self.nt = nt
        self.rebase_windows = rebase_windows
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tail = None            # usage_after of the last dispatched window
        self._tail_epoch = -1        # nt.row_epoch the tail was validated at
        self._holder: Optional[str] = None  # window mid-dispatch (lease out)
        self._pending = 0            # published windows not yet finished
        self._windows_since_rebase = 0
        self._dirty = False          # tail carries phantom usage: rebase next
        self._taint_seq = 0
        # Chain-order finish barrier: windows SETTLE (make their phantom-
        # usage quarantine decision) in publish order, across workers.
        self._published_seq = 0      # windows published so far
        self._settled_seq = 0        # highest contiguously settled window
        self._drained = threading.Event()  # pending == 0 (across all workers)
        self._drained.set()

    # ------------------------------------------------------------- leasing
    def acquire(self, stop: Optional[threading.Event] = None,
                holder: str = "", drain_timeout: float = 60.0) -> ChainLease:
        """Take the window lease, waiting out any other worker's dispatch.

        Rebase decisions (all previously per-worker, now global): a dirty
        or depth-limited tail waits out EVERY in-flight window — any
        worker's — before restarting from committed state; an epoch/shape
        mismatch or a fully drained pipeline rebases immediately
        (committed state is strictly fresher once everything landed).
        The drain wait is bounded: a wedged window must not wedge every
        worker, and rebasing onto committed state early is always safe —
        the plan applier re-verifies every placement."""
        nt = self.nt
        with self._cond:
            while self._holder is not None:
                if stop is not None and stop.is_set():
                    raise RuntimeError("chain arbiter: worker stopping")
                self._cond.wait(0.1)
            self._holder = holder or "window"
            dirty = self._dirty
            self._dirty = False
            chain = self._tail
            if chain is not None and dirty:
                # Phantom usage baked into the tail: wait the in-flight
                # windows out (their commits land in the host mirror),
                # then restart from committed state.
                self._wait_drained_locked(stop, drain_timeout)
                chain = None
            if chain is not None and (chain.shape[0] != nt.n_rows
                                      or self._tail_epoch != nt.row_epoch):
                # Table resized OR a row changed identity (node removed /
                # freed row reused): the chain may carry a departed
                # node's usage on a row that now belongs to someone else.
                chain = None
            if chain is not None \
                    and self._windows_since_rebase >= self.rebase_windows:
                # Bound chain drift: drain the pipeline, then restart.
                self._wait_drained_locked(stop, drain_timeout)
                chain = None
            if chain is not None and self._pending == 0:
                # Pipeline is empty: everything this chain carries has
                # committed into the host mirror, so committed state is
                # strictly fresher (it also includes slow-path/fallback
                # commits the chain missed).
                chain = None
            rebased = self._tail is not None and chain is None
            if chain is None:
                self._tail = None
                self._windows_since_rebase = 0
            return ChainLease(chain=chain, taint_seq=self._taint_seq,
                              epoch=nt.row_epoch, rebased=rebased)

    def publish(self, lease: ChainLease, usage_after) -> None:
        """Install the dispatched window's usage tail and count it in
        flight; releases the dispatch lease."""
        with self._cond:
            if lease.released:
                return
            lease.released = True
            self._published_seq += 1
            lease.seq = self._published_seq
            self._tail = usage_after
            self._tail_epoch = lease.epoch
            self._windows_since_rebase += 1
            self._pending += 1
            self._drained.clear()
            self._holder = None
            self._cond.notify_all()

    def abort(self, lease: ChainLease) -> None:
        """Release the dispatch lease without publishing (the window had
        no fast evals, or dispatch failed before any kernel launched).
        One-shot like publish: a double release must not free a lease
        another worker has since acquired."""
        with self._cond:
            if lease.released:
                return
            lease.released = True
            self._holder = None
            self._cond.notify_all()

    # ------------------------------------------------------ window lifetime
    def finish_window(self) -> bool:
        """A published window fully finished (built, acked or nacked).
        Returns True when that drained the pipeline across ALL workers."""
        with self._cond:
            self._pending = max(0, self._pending - 1)
            drained = self._pending == 0
            if drained:
                self._drained.set()
                self._cond.notify_all()
            return drained

    def taint(self) -> None:
        """A window ended with stale/fallback records: its chained kernel
        placements never commit as dispatched. Windows in flight on the
        tainted tail detect this via the sequence bump; the next acquire
        sees the dirty flag and rebases."""
        with self._cond:
            self._taint_seq += 1
            self._dirty = True

    def taint_changed(self, seq: int) -> bool:
        with self._cond:
            return self._taint_seq != seq

    def wait_turn(self, seq: int, stop: Optional[threading.Event] = None,
                  timeout: float = 60.0) -> bool:
        """Block until every window published BEFORE chain position `seq`
        has SETTLED — made its phantom-usage quarantine decision and
        raised any taint. One build thread per worker settles its own
        windows in order, but with N workers a window chained on another
        worker's tail can otherwise finish first and consult the taint
        sequence before the tail owner raises it — parking squeezed evals
        as blocked on capacity that was never really taken. Bounded: a
        wedged predecessor must not wedge every worker, and proceeding
        early only risks the (rare, logged) missed-quarantine the barrier
        normally closes."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._settled_seq < seq - 1:
                if stop is not None and stop.is_set():
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
            return True

    def mark_settled(self, seq: int) -> None:
        """The window at chain position `seq` made its taint decision;
        successors may now make theirs. Idempotent (the build loop's
        finally re-marks windows _finish_fast already settled)."""
        with self._cond:
            if seq > self._settled_seq:
                self._settled_seq = seq
                self._cond.notify_all()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until no window is in flight (quiesce for tests/bench)."""
        return self._drained.wait(timeout)

    def wait_dispatch_idle(self, timeout: float) -> bool:
        """Park until no window is mid-dispatch (the lease is free),
        WITHOUT acquiring: a worker waits its turn BEFORE dequeuing evals
        it could not launch anyway. Dequeue-then-wait holds those evals
        hostage through the other worker's dispatch — their deadlines
        burn and the storm splinters into one-eval windows. The lease is
        only held during dispatch, so a worker parked here still wakes in
        time to dispatch while the previous window's drain/build (the
        device RTT and plan-applier wait) run lease-free."""
        with self._cond:
            deadline = time.monotonic() + timeout
            while self._holder is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.05))
            return True

    @property
    def pending(self) -> int:
        with self._cond:
            return self._pending

    @property
    def dirty(self) -> bool:
        with self._cond:
            return self._dirty

    def _wait_drained_locked(self, stop: Optional[threading.Event],
                             timeout: float) -> None:
        """Wait (bounded, stop-aware) for pending == 0 with _lock held.
        Proceeding before fully drained is safe — it only rebases onto
        committed state while windows are still landing, which the plan
        applier's re-verification already tolerates."""
        deadline = time.monotonic() + timeout
        while self._pending > 0:
            if stop is not None and stop.is_set():
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            self._cond.wait(min(remaining, 0.1))


_BACKEND_CHECKED = False
_SCATTER_REFRESH = None


def _scatter_refresh(capacity, score_cap, usage, packed):
    """Jitted split + 3-way row scatter of one packed refresh transfer.
    packed: [k, 1 + R + 2 + R] f32 = (row, capacity, score_cap, usage)."""
    global _SCATTER_REFRESH
    if _SCATTER_REFRESH is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def refresh(cap, sc, us, pk):
            rows = pk[:, 0].astype(jnp.int32)
            cap_v = pk[:, 1:1 + RES_DIMS]
            sc_v = pk[:, 1 + RES_DIMS:3 + RES_DIMS]
            us_v = pk[:, 3 + RES_DIMS:]
            return (cap.at[rows].set(cap_v), sc.at[rows].set(sc_v),
                    us.at[rows].set(us_v))

        _SCATTER_REFRESH = refresh

    # packed stays a host array (uncommitted): jit places it with the other
    # operands, which may be sharded over a mesh — an eager jnp.asarray here
    # would commit it to the default device and conflict.
    return _SCATTER_REFRESH(capacity, score_cap, usage, packed)


def ensure_backend() -> None:
    """Fail over to any available JAX backend if the configured one is gone.

    A scheduler must keep placing when an accelerator platform fails to
    initialize (e.g. a remote-TPU plugin configured in the environment but
    not registered); XLA:CPU runs the same programs.
    """
    global _BACKEND_CHECKED
    if _BACKEND_CHECKED:
        return
    import jax

    try:
        jax.devices()
    except RuntimeError:
        import logging

        logging.getLogger("nomad.tensor").warning(
            "configured JAX backend unavailable; falling back to auto-detect")
        jax.config.update("jax_platforms", "")
        jax.devices()
    _BACKEND_CHECKED = True


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _grow2(a: np.ndarray, n: int, fill: float = 0.0) -> np.ndarray:
    out = np.full((n, a.shape[1]), fill, dtype=a.dtype)
    out[: a.shape[0]] = a
    return out


def _grow1(a: np.ndarray, n: int, fill) -> np.ndarray:
    out = np.full(n, fill, dtype=a.dtype)
    out[: a.shape[0]] = a
    return out
