"""Node-table tensorization: the bridge from the state store to XLA.

This layer is the TPU-first core of the design. The reference walks per-node
Go iterators (reference: scheduler/feasible.go, scheduler/rank.go); here the
node table lives as device-resident arrays ([N, R] capacity/usage, [N] class
and datacenter ids) updated incrementally as the FSM applies writes, and
feasibility/scoring run as one vectorized XLA program over the whole node
axis (nomad_tpu/scheduler/kernels.py). String-typed constraint work (regex,
versions) happens host-side once per computed node class — classes are few —
and is gathered across the node axis (reference optimization:
scheduler/feasible.go:454-568 re-expressed as tensor compression).
"""

from .node_table import NodeTensor, RES_DIMS, alloc_vec, resources_vec  # noqa: F401
from .constraints import ClassEligibility, check_constraint, resolve_target  # noqa: F401
from .index import TensorIndex  # noqa: F401
