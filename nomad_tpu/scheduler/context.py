"""EvalContext: per-evaluation working state (reference: scheduler/context.go).

Carries the state snapshot, the plan under construction, the metrics
accumulator, and the proposed-allocation view: existing non-terminal
allocations minus planned evictions plus planned placements (reference:
context.go:109-140) — the invariant that placement k+1 must observe
placement k.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from nomad_tpu.structs import Allocation, AllocMetric, Plan, remove_allocs

from .scheduler import State


class EvalContext:
    def __init__(self, state: State, plan: Plan,
                 logger: Optional[logging.Logger] = None):
        self.state = state
        self.plan = plan
        self.logger = logger or logging.getLogger("sched")
        self.metrics = AllocMetric()

    def reset(self) -> None:
        self.metrics = AllocMetric()

    def proposed_allocs(self, node_id: str) -> List[Allocation]:
        """Proposed allocations on a node: existing non-terminal, minus plan
        evictions, plus plan placements (reference: context.go:109-140)."""
        existing = self.state.allocs_by_node_terminal(node_id, False)
        if node_id in self.plan.NodeUpdate:
            existing = remove_allocs(list(existing), self.plan.NodeUpdate[node_id])
        proposed = list(existing)
        proposed.extend(self.plan.NodeAllocation.get(node_id, ()))
        return proposed
