"""Schedulers: the TPU hot path (reference: scheduler/).

The reference's per-node iterator chain becomes batched XLA programs
(kernels.py); reconciliation (diffing required vs existing allocations) stays
host-side Python — it is O(allocations of one job), not hot.
"""

from .scheduler import (  # noqa: F401
    BUILTIN_SCHEDULERS, Planner, Scheduler, SetStatusError, State,
    new_scheduler,
)
from .generic_sched import GenericScheduler  # noqa: F401
from .system_sched import SystemScheduler  # noqa: F401
from .context import EvalContext  # noqa: F401
from .stack import GenericStack, SystemStack  # noqa: F401
from .testing import Harness  # noqa: F401
