"""CPU reference placement path: the reference's iterator-chain algorithm.

A faithful host-side implementation of the reference Stack semantics
(reference: scheduler/stack.go, feasible.go, rank.go, select.go): Fisher-
Yates node shuffle, computed-class-memoized feasibility with escape hatch,
BinPack scoring over proposed usage, job anti-affinity, and the
max(2, ceil(log2 n)) LimitIterator with MaxScore selection.

Used as (a) the baseline the TPU path must beat (bench.py) and (b) the
golden model for placement-quality parity tests.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nomad_tpu.structs import Job, Node, TaskGroup
from nomad_tpu.tensor.constraints import (
    node_has_drivers,
    node_meets_constraints,
)
from nomad_tpu.tensor.node_table import RES_DIMS, resources_vec

from .util import task_group_constraints

SERVICE_PENALTY = 10.0
BATCH_PENALTY = 5.0


def binpack_score(usage: np.ndarray, demand: np.ndarray,
                  score_cap: np.ndarray) -> float:
    """BestFit-v3 over proposed (cpu, mem) utilization: 20 - 10^freeCpuPct -
    10^freeMemPct clamped to [0, 18], with the reference's IEEE Inf/NaN
    division edges (reference: scheduler/rank.go:131-240, funcs.go:102-137).
    score_cap is capacity minus reserved for (cpu, mem)."""
    util2 = usage[:2] + demand[:2]
    with np.errstate(divide="ignore", invalid="ignore"):
        free = 1.0 - util2 / score_cap
        total = 10.0 ** free[0] + 10.0 ** free[1]
    score = float(np.clip(20.0 - total, 0.0, 18.0))
    return 0.0 if np.isnan(score) else score


class CPUReferenceStack:
    """Per-placement iterator walk over node dicts + numpy usage vectors."""

    def __init__(self, nodes: Sequence[Node], batch: bool = False,
                 rng: Optional[random.Random] = None):
        self.nodes = list(nodes)
        self.batch = batch
        self.rng = rng or random.Random()
        # Resource vectors per node.
        self.capacity = {n.ID: resources_vec(n.Resources) for n in self.nodes}
        self.score_cap = {
            n.ID: (resources_vec(n.Resources)[:2]
                   - resources_vec(n.Reserved)[:2])
            for n in self.nodes}
        self.usage: Dict[str, np.ndarray] = {
            n.ID: resources_vec(n.Reserved) for n in self.nodes}
        self.job_allocs: Dict[str, int] = {}
        self.job: Optional[Job] = None
        # Class-level feasibility memo (reference: feasible.go:454-568).
        self._class_memo: Dict[Tuple[str, str], bool] = {}

    def set_job(self, job: Job) -> None:
        self.job = job
        self.job_allocs = {}

    def _feasible(self, node: Node, tg: TaskGroup, constraints, drivers) -> bool:
        key = (node.ComputedClass, tg.Name)
        memo = self._class_memo.get(key)
        if memo is not None:
            return memo
        ok = (node_meets_constraints(node, self.job.Constraints)
              and node_meets_constraints(node, constraints)
              and node_has_drivers(node, drivers))
        self._class_memo[key] = ok
        return ok

    def select(self, tg: TaskGroup) -> Optional[Tuple[str, float]]:
        """One placement: returns (node_id, score) or None."""
        assert self.job is not None
        cons = task_group_constraints(tg)
        demand = resources_vec(cons.size)

        # Random source (Fisher-Yates shuffle, reference: util.go:281-287).
        order = list(range(len(self.nodes)))
        self.rng.shuffle(order)

        # LimitIterator: max(2, ceil(log2 n)) feasible candidates
        # (reference: stack.go:120-133).
        limit = 2
        n = len(self.nodes)
        if not self.batch and n > 0:
            limit = max(2, int(math.ceil(math.log2(n))))

        penalty = BATCH_PENALTY if self.batch else SERVICE_PENALTY
        best: Optional[Tuple[str, float]] = None
        seen = 0
        for i in order:
            node = self.nodes[i]
            if node.Status != "ready" or node.Drain:
                continue
            if not self._feasible(node, tg, cons.constraints, cons.drivers):
                continue
            # BinPack fit + score (reference: rank.go:131-240).
            usage = self.usage[node.ID]
            if np.any(self.capacity[node.ID] - usage < demand):
                continue
            score = binpack_score(usage, demand, self.score_cap[node.ID])
            score -= self.job_allocs.get(node.ID, 0) * penalty
            if best is None or score > best[1]:
                best = (node.ID, score)
            seen += 1
            if seen >= limit:
                break
        if best is None:
            return None
        node_id, score = best
        self.usage[node_id] = self.usage[node_id] + demand
        self.job_allocs[node_id] = self.job_allocs.get(node_id, 0) + 1
        return best

    def select_batch(self, tgs: Sequence[TaskGroup]) -> List[Optional[Tuple[str, float]]]:
        return [self.select(tg) for tg in tgs]


class CPUReferenceServedStack:
    """GenericScheduler-compatible stack running the reference's host-side
    iterator chain against LIVE cluster state — the honest denominator for
    the served benchmark: same broker, plan applier, raft, and status paths
    as the TPU stack, with only the placement engine swapped.

    Semantics mirror CPUReferenceStack (Fisher-Yates shuffle, class-memoized
    feasibility, BinPack scoring, max(2, ceil(log2 n)) candidate limit,
    reference: scheduler/stack.go:120-133, rank.go:131-240); usage derives
    lazily per candidate node from ctx.proposed_allocs, exactly the
    reference BinPackIterator's proposed-allocation walk."""

    elig = None  # no tensorized eligibility: escape/class reporting no-ops

    def __init__(self, ctx, batch: bool, rng: Optional[random.Random] = None):
        self.ctx = ctx
        self.batch = batch
        self.rng = rng or random.Random()
        self.job: Optional[Job] = None
        self.nodes: List[Node] = []
        self._class_memo: Dict[Tuple[str, str], bool] = {}

    def set_job(self, job: Job) -> None:
        self.job = job
        self._class_memo.clear()

    def set_nodes(self, nodes: Sequence[Node]) -> None:
        self.nodes = list(nodes)

    # ------------------------------------------------------------- internals
    def _feasible(self, node: Node, tg: TaskGroup, constraints, drivers) -> bool:
        key = (node.ComputedClass, tg.Name)
        memo = self._class_memo.get(key)
        if memo is not None:
            return memo
        ok = (node_meets_constraints(node, self.job.Constraints)
              and node_meets_constraints(node, constraints)
              and node_has_drivers(node, drivers))
        self._class_memo[key] = ok
        return ok

    def _usage(self, node: Node, cache: Dict[str, np.ndarray],
               counts: Dict[str, int]) -> np.ndarray:
        from nomad_tpu.tensor.node_table import alloc_vec

        vec = cache.get(node.ID)
        if vec is None:
            vec = resources_vec(node.Reserved)
            job_id = self.job.ID if self.job is not None else ""
            n_job = 0
            for a in self.ctx.proposed_allocs(node.ID):
                vec = vec + alloc_vec(a)
                if a.JobID == job_id:
                    n_job += 1
            cache[node.ID] = vec
            counts[node.ID] = n_job
        return vec

    def _option(self, node: Node, tg: TaskGroup, score: float):
        from nomad_tpu.structs import NetworkIndex, Resources

        from .stack import SelectedOption

        option = SelectedOption(node=node, score=score)
        needs_net = any(t.Resources is not None and t.Resources.Networks
                        for t in tg.Tasks)
        netidx = None
        if needs_net:
            netidx = NetworkIndex()
            netidx.set_node(node)
            netidx.add_allocs(self.ctx.proposed_allocs(node.ID))
        for task in tg.Tasks:
            resources = (task.Resources.copy() if task.Resources is not None
                         else Resources())
            if netidx is not None and task.Resources is not None \
                    and task.Resources.Networks:
                try:
                    offer = netidx.assign_network(
                        task.Resources.Networks[0], self.rng)
                except ValueError:
                    return None
                resources.Networks = [offer]
                netidx.add_reserved(offer)
            option.task_resources[task.Name] = resources
        return option

    # -------------------------------------------------------------- selection
    def select_batch(self, tgs: Sequence[TaskGroup]) -> List:
        usage_cache: Dict[str, np.ndarray] = {}
        counts: Dict[str, int] = {}
        return [self._select(tg, usage_cache, counts) for tg in tgs]

    def _select(self, tg: TaskGroup, usage_cache: Dict[str, np.ndarray],
                counts: Dict[str, int]):
        assert self.job is not None
        m = self.ctx.metrics
        cons = task_group_constraints(tg)
        demand = resources_vec(cons.size)

        order = list(range(len(self.nodes)))
        self.rng.shuffle(order)
        limit = 2
        n = len(self.nodes)
        if not self.batch and n > 0:
            limit = max(2, int(math.ceil(math.log2(n))))
        penalty = BATCH_PENALTY if self.batch else SERVICE_PENALTY

        best = None
        best_node = None
        seen = 0
        for i in order:
            node = self.nodes[i]
            m.NodesEvaluated += 1
            if not self._feasible(node, tg, cons.constraints, cons.drivers):
                m.NodesFiltered += 1
                continue
            usage = self._usage(node, usage_cache, counts)
            capacity = resources_vec(node.Resources)
            if np.any(capacity - usage < demand):
                m.NodesExhausted += 1
                continue
            score = binpack_score(
                usage, demand,
                capacity[:2] - resources_vec(node.Reserved)[:2])
            score -= counts.get(node.ID, 0) * penalty
            if best is None or score > best:
                best, best_node = score, node
            seen += 1
            if seen >= limit:
                break
        if best_node is None:
            return None
        option = self._option(best_node, tg, best)
        if option is None:
            return None
        usage_cache[best_node.ID] = usage_cache[best_node.ID] + demand
        counts[best_node.ID] = counts.get(best_node.ID, 0) + 1
        self.ctx.metrics.score_node(best_node, "binpack", best)
        return option

    def select_on_node(self, tg: TaskGroup, node: Node):
        """Feasibility + fit on one specific node (in-place update path)."""
        cons = task_group_constraints(tg)
        if node.Status != "ready" or node.Drain:
            return None
        if not self._feasible(node, tg, cons.constraints, cons.drivers):
            return None
        cache: Dict[str, np.ndarray] = {}
        counts: Dict[str, int] = {}
        usage = self._usage(node, cache, counts)
        capacity = resources_vec(node.Resources)
        demand = resources_vec(cons.size)
        if np.any(capacity - usage < demand):
            return None
        score = binpack_score(usage, demand,
                              capacity[:2] - resources_vec(node.Reserved)[:2])
        return self._option(node, tg, score)
