"""CPU reference placement path: the reference's iterator-chain algorithm.

A faithful host-side implementation of the reference Stack semantics
(reference: scheduler/stack.go, feasible.go, rank.go, select.go): Fisher-
Yates node shuffle, computed-class-memoized feasibility with escape hatch,
BinPack scoring over proposed usage, job anti-affinity, and the
max(2, ceil(log2 n)) LimitIterator with MaxScore selection.

Used as (a) the baseline the TPU path must beat (bench.py) and (b) the
golden model for placement-quality parity tests.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nomad_tpu.structs import Job, Node, TaskGroup
from nomad_tpu.tensor.constraints import (
    node_has_drivers,
    node_meets_constraints,
)
from nomad_tpu.tensor.node_table import RES_DIMS, resources_vec

from .util import task_group_constraints

SERVICE_PENALTY = 10.0
BATCH_PENALTY = 5.0


class CPUReferenceStack:
    """Per-placement iterator walk over node dicts + numpy usage vectors."""

    def __init__(self, nodes: Sequence[Node], batch: bool = False,
                 rng: Optional[random.Random] = None):
        self.nodes = list(nodes)
        self.batch = batch
        self.rng = rng or random.Random()
        # Resource vectors per node.
        self.capacity = {n.ID: resources_vec(n.Resources) for n in self.nodes}
        self.score_cap = {
            n.ID: (resources_vec(n.Resources)[:2]
                   - resources_vec(n.Reserved)[:2])
            for n in self.nodes}
        self.usage: Dict[str, np.ndarray] = {
            n.ID: resources_vec(n.Reserved) for n in self.nodes}
        self.job_allocs: Dict[str, int] = {}
        self.job: Optional[Job] = None
        # Class-level feasibility memo (reference: feasible.go:454-568).
        self._class_memo: Dict[Tuple[str, str], bool] = {}

    def set_job(self, job: Job) -> None:
        self.job = job
        self.job_allocs = {}

    def _feasible(self, node: Node, tg: TaskGroup, constraints, drivers) -> bool:
        key = (node.ComputedClass, tg.Name)
        memo = self._class_memo.get(key)
        if memo is not None:
            return memo
        ok = (node_meets_constraints(node, self.job.Constraints)
              and node_meets_constraints(node, constraints)
              and node_has_drivers(node, drivers))
        self._class_memo[key] = ok
        return ok

    def select(self, tg: TaskGroup) -> Optional[Tuple[str, float]]:
        """One placement: returns (node_id, score) or None."""
        assert self.job is not None
        cons = task_group_constraints(tg)
        demand = resources_vec(cons.size)

        # Random source (Fisher-Yates shuffle, reference: util.go:281-287).
        order = list(range(len(self.nodes)))
        self.rng.shuffle(order)

        # LimitIterator: max(2, ceil(log2 n)) feasible candidates
        # (reference: stack.go:120-133).
        limit = 2
        n = len(self.nodes)
        if not self.batch and n > 0:
            limit = max(2, int(math.ceil(math.log2(n))))

        penalty = BATCH_PENALTY if self.batch else SERVICE_PENALTY
        best: Optional[Tuple[str, float]] = None
        seen = 0
        for i in order:
            node = self.nodes[i]
            if node.Status != "ready" or node.Drain:
                continue
            if not self._feasible(node, tg, cons.constraints, cons.drivers):
                continue
            # BinPack fit + score (reference: rank.go:131-240).
            usage = self.usage[node.ID]
            if np.any(self.capacity[node.ID] - usage < demand):
                continue
            util2 = usage[:2] + demand[:2]
            with np.errstate(divide="ignore", invalid="ignore"):
                free = 1.0 - util2 / self.score_cap[node.ID]
                total = 10.0 ** free[0] + 10.0 ** free[1]
            score = float(np.clip(20.0 - total, 0.0, 18.0))
            if np.isnan(score):
                score = 0.0
            score -= self.job_allocs.get(node.ID, 0) * penalty
            if best is None or score > best[1]:
                best = (node.ID, score)
            seen += 1
            if seen >= limit:
                break
        if best is None:
            return None
        node_id, score = best
        self.usage[node_id] = self.usage[node_id] + demand
        self.job_allocs[node_id] = self.job_allocs.get(node_id, 0) + 1
        return best

    def select_batch(self, tgs: Sequence[TaskGroup]) -> List[Optional[Tuple[str, float]]]:
        return [self.select(tg) for tg in tgs]
