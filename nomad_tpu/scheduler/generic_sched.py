"""GenericScheduler: service + batch jobs (reference: scheduler/generic_sched.go).

Control flow matches the reference — trigger validation, bounded retry with
progress reset, reconcile, in-place vs destructive updates, rolling-update
limits, blocked-eval creation/reuse — but computePlacements hands the entire
missing-allocation list to the stack as ONE batched device program instead of
a per-allocation iterator walk.
"""

from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional

from nomad_tpu.structs import (
    Allocation,
    AllocMetric,
    Evaluation,
    Job,
    Plan,
    PlanAnnotations,
    PlanResult,
    generate_uuid,
)
from nomad_tpu.structs.structs import (
    AllocClientStatusFailed,
    AllocClientStatusPending,
    AllocDesiredStatusEvict,
    AllocDesiredStatusFailed,
    AllocDesiredStatusRun,
    AllocDesiredStatusStop,
    EvalStatusBlocked,
    EvalStatusComplete,
    EvalStatusFailed,
    EvalTriggerJobDeregister,
    EvalTriggerJobRegister,
    EvalTriggerMaxPlans,
    EvalTriggerNodeUpdate,
    EvalTriggerPeriodicJob,
    EvalTriggerRollingUpdate,
)
from nomad_tpu.tensor import TensorIndex

from .context import EvalContext
from .scheduler import Planner, SetStatusError, State
from .stack import GenericStack
from .util import (
    ALLOC_IN_PLACE,
    ALLOC_MIGRATING,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    BLOCKED_EVAL_FAILED_PLACEMENTS,
    BLOCKED_EVAL_MAX_PLAN,
    AllocTuple,
    desired_updates,
    attempt_inplace_updates,
    diff_allocs,
    evict_and_place,
    materialize_task_groups,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    tasks_updated,
)

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

_HANDLED_TRIGGERS = (
    EvalTriggerJobRegister, EvalTriggerNodeUpdate, EvalTriggerJobDeregister,
    EvalTriggerRollingUpdate, EvalTriggerPeriodicJob, EvalTriggerMaxPlans,
)


def has_escaped(stack: Optional[GenericStack], job: Optional[Job]) -> bool:
    """True when a constraint escaped computed-class evaluation (reference:
    the escaped flag threaded through feasible.go checkers). Filters the
    TG cache to THIS job: a shared window ClassEligibility also holds other
    jobs' entries."""
    if stack is None or stack.elig is None or job is None:
        return False
    cache = stack.elig._job_cache.get(job.ID)
    if cache is not None and cache[2]:
        return True
    return any(v[2] for k, v in stack.elig._tg_cache.items()
               if k[0] == job.ID)


def class_eligibility(stack: Optional[GenericStack], job: Optional[Job],
                      tindex: Optional[TensorIndex]) -> Dict[str, bool]:
    """Per-computed-class eligibility snapshot for blocked evals
    (reference: generic_sched.go blocked-eval ClassEligibility). Only THIS
    job's cache entries participate — the eligibility object may be shared
    across a scheduling window."""
    if stack is None or stack.elig is None or job is None:
        return {}
    elig = stack.elig
    nt = tindex.nt if tindex else None
    out: Dict[str, bool] = {}
    job_cache = elig._job_cache.get(job.ID)
    tables = []
    if job_cache is not None:
        tables.append(job_cache[1])
    tables.extend(v[1] for k, v in elig._tg_cache.items() if k[0] == job.ID)
    if not tables or nt is None:
        return out
    import numpy as np

    combined = np.logical_and.reduce(tables) if len(tables) > 1 else tables[0]
    for cid, name in enumerate(nt.class_names):
        if cid < len(combined):
            out[name] = bool(combined[cid])
    return out


def filter_complete_allocs(allocs: List[Allocation],
                           batch: bool) -> List[Allocation]:
    """(reference: generic_sched.go:267-303)"""

    def keep(a: Allocation) -> bool:
        if batch:
            if a.DesiredStatus in (AllocDesiredStatusStop,
                                   AllocDesiredStatusEvict,
                                   AllocDesiredStatusFailed):
                return a.ran_successfully()
            return a.ClientStatus != AllocClientStatusFailed
        return not a.terminal_status()

    return [a for a in allocs if keep(a)]


def build_placement_allocs(eval: Evaluation, job: Job, ctx: EvalContext,
                           place, options, plan: Plan,
                           failed_tg_allocs: Dict[str, AllocMetric]) -> None:
    """Turn stack selections into plan allocations; coalesce failures per TG
    (reference per-alloc loop: generic_sched.go:392-443)."""
    # Scoring finished before this runs, so the eval's metrics are final:
    # one immutable snapshot shared by every placed alloc (a copy per alloc
    # would walk the accumulated per-node Scores map P times — O(P^2)).
    shared_metric = None
    for tup, option in zip(place, options):
        if option is not None:
            if shared_metric is None:
                shared_metric = ctx.metrics.copy()
            alloc = Allocation(
                ID=generate_uuid(),
                EvalID=eval.ID,
                Name=tup.Name,
                JobID=job.ID,
                TaskGroup=tup.TaskGroup.Name,
                Metrics=shared_metric,
                NodeID=option.node.ID,
                TaskResources=option.task_resources,
                DesiredStatus=AllocDesiredStatusRun,
                ClientStatus=AllocClientStatusPending,
            )
            plan.append_alloc(alloc)
        else:
            metric = failed_tg_allocs.get(tup.TaskGroup.Name)
            if metric is not None:
                metric.CoalescedFailures += 1
            else:
                failed_tg_allocs[tup.TaskGroup.Name] = ctx.metrics.copy()


class GenericScheduler:
    def __init__(self, state: State, planner: Planner,
                 tindex: Optional[TensorIndex], logger: logging.Logger,
                 batch: bool, rng: Optional[random.Random] = None,
                 impl: str = "tpu"):
        self.state = state
        self.planner = planner
        self.tindex = tindex
        self.logger = logger
        self.batch = batch
        self.rng = rng or random.Random()
        # "tpu" (device placement kernels) or "cpu-reference" (the
        # reference's host-side iterator chain) — the benchmark denominator
        # runs through this seam so both engines share every other stage.
        self.impl = impl

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result: Optional[PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[GenericStack] = None
        self.limit_reached = False
        self.next_eval: Optional[Evaluation] = None
        self.blocked: Optional[Evaluation] = None
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}

    # ------------------------------------------------------------- process
    def process(self, eval: Evaluation) -> None:
        """(reference: generic_sched.go:100-152)"""
        self.eval = eval
        if eval.TriggeredBy not in _HANDLED_TRIGGERS:
            set_status(self.planner, eval, self.next_eval, self.blocked,
                       self.failed_tg_allocs, EvalStatusFailed,
                       f"scheduler cannot handle '{eval.TriggeredBy}' evaluation reason")
            return

        limit = MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch else MAX_SERVICE_SCHEDULE_ATTEMPTS
        try:
            retry_max(limit, self._process,
                      lambda: progress_made(self.plan_result))
        except SetStatusError as e:
            # No forward progress: leave a blocked eval to retry on capacity.
            self._create_blocked_eval(plan_failure=True)
            set_status(self.planner, eval, self.next_eval, self.blocked,
                       self.failed_tg_allocs, e.eval_status, str(e))
            return

        # A blocked eval that still couldn't place everything is re-blocked.
        if eval.Status == EvalStatusBlocked and self.failed_tg_allocs:
            new_eval = eval.copy()
            new_eval.EscapedComputedClass = self._has_escaped()
            new_eval.ClassEligibility = self._class_eligibility()
            self.planner.reblock_eval(new_eval)
            return

        set_status(self.planner, eval, self.next_eval, self.blocked,
                   self.failed_tg_allocs, EvalStatusComplete, "")

    def _has_escaped(self) -> bool:
        return has_escaped(self.stack, self.job)

    def _class_eligibility(self) -> Dict[str, bool]:
        return class_eligibility(self.stack, self.job, self.tindex)

    def _create_blocked_eval(self, plan_failure: bool) -> None:
        """(reference: generic_sched.go:156-177)"""
        escaped = self._has_escaped()
        class_elig = {} if escaped else self._class_eligibility()
        self.blocked = self.eval.create_blocked_eval(class_elig, escaped)
        if plan_failure:
            self.blocked.TriggeredBy = EvalTriggerMaxPlans
            self.blocked.StatusDescription = BLOCKED_EVAL_MAX_PLAN
        else:
            self.blocked.StatusDescription = BLOCKED_EVAL_FAILED_PLACEMENTS
        self.planner.create_eval(self.blocked)

    # --------------------------------------------------------- one attempt
    def _process(self) -> bool:
        """(reference: generic_sched.go:181-263) Returns True when done."""
        self.job = self.state.job_by_id(self.eval.JobID)
        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = {}
        self.ctx = EvalContext(self.state, self.plan, self.logger)
        if self.impl == "cpu-reference":
            from .cpu_reference import CPUReferenceServedStack

            self.stack = CPUReferenceServedStack(self.ctx, self.batch,
                                                 self.rng)
        else:
            if self.tindex is None:
                self.tindex = TensorIndex.from_state(self.state)
            self.stack = GenericStack(self.ctx, self.tindex, self.batch,
                                      self.rng)
        if self.job is not None:
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        if (self.eval.Status != EvalStatusBlocked and self.failed_tg_allocs
                and self.blocked is None):
            self._create_blocked_eval(plan_failure=False)

        if self.plan.is_no_op() and not self.eval.AnnotatePlan:
            return True

        if self.limit_reached and self.next_eval is None:
            self.next_eval = self.eval.next_rolling_eval(self.job.Update.Stagger)
            self.planner.create_eval(self.next_eval)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        if new_state is not None:
            # Stale data: refresh and retry. A store-attached index stays in
            # sync by itself; only a one-shot snapshot index must be rebuilt.
            self.state = new_state
            if self.tindex is not None and not self.tindex.attached:
                self.tindex = None  # rebuilt from the fresh state next attempt
            return False

        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            self.logger.debug("eval %s: attempted %d placements, %d placed",
                              self.eval.ID, expected, actual)
            return False
        return True

    # ----------------------------------------------------------- reconcile
    def _filter_complete_allocs(self, allocs: List[Allocation]) -> List[Allocation]:
        return filter_complete_allocs(allocs, self.batch)

    def _compute_job_allocs(self) -> None:
        """(reference: generic_sched.go:307-389)"""
        groups = materialize_task_groups(self.job)
        allocs = self.state.allocs_by_job(self.eval.JobID)
        allocs = self._filter_complete_allocs(list(allocs))
        tainted = tainted_nodes(self.state, allocs)
        diff = diff_allocs(self.job, tainted, groups, allocs)
        self.logger.debug("eval %s: place %d update %d migrate %d stop %d ignore %d",
                          self.eval.ID, len(diff.place), len(diff.update),
                          len(diff.migrate), len(diff.stop), len(diff.ignore))

        for tup in diff.stop:
            self.plan.append_update(tup.Alloc, AllocDesiredStatusStop,
                                    ALLOC_NOT_NEEDED)

        destructive, inplace = self._inplace_update(diff.update)
        diff.update = destructive

        if self.eval.AnnotatePlan:
            self.plan.Annotations = PlanAnnotations(
                DesiredTGUpdates=desired_updates(diff, inplace, destructive))

        limit = [len(diff.update) + len(diff.migrate)]
        if self.job is not None and self.job.Update.rolling():
            limit = [self.job.Update.MaxParallel]

        self.limit_reached = evict_and_place(self.ctx, diff, diff.migrate,
                                             ALLOC_MIGRATING, limit)
        self.limit_reached = (evict_and_place(self.ctx, diff, diff.update,
                                              ALLOC_UPDATING, limit)
                              or self.limit_reached)

        if not diff.place:
            return
        self._compute_placements(diff.place)

    def _inplace_update(self, updates: List[AllocTuple]
                        ) -> tuple[List[AllocTuple], List[AllocTuple]]:
        """In-place where the TG didn't materially change (reference:
        util.go:389-468). Returns (destructive, inplace)."""
        return attempt_inplace_updates(self.state, self.plan, self.stack,
                                       self.eval.ID, self.ctx, updates)

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        """Batched placement: ONE device program for the whole list
        (reference per-alloc loop: generic_sched.go:392-443)."""
        nodes, by_dc = ready_nodes_in_dcs(self.state, self.job.Datacenters)
        self.stack.set_nodes(nodes)

        options = self.stack.select_batch([t.TaskGroup for t in place])
        self.ctx.metrics.NodesAvailable = by_dc

        # QoS preemption (capability beyond reference v0.4): a HIGH-tier
        # placement that found no feasible capacity may evict lower-tier
        # allocs; the plan applier re-verifies evictions + placement
        # atomically per node. The planner (Worker) carries the config;
        # no-op when QoS is off or nothing failed.
        qos = getattr(self.planner, "qos", None)
        if (qos is not None and qos.enabled and qos.preemption
                and any(o is None for o in options)):
            from nomad_tpu.qos import attempt_preemption

            options = attempt_preemption(
                self.state, self.plan, self.eval.ID, self.job, place,
                options, nodes, qos,
                counters=getattr(self.planner, "qos_counters", None),
                log=self.logger)

        build_placement_allocs(self.eval, self.job, self.ctx, place, options,
                               self.plan, self.failed_tg_allocs)
