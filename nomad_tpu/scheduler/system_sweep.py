"""Vectorized system-job sweep: fused feasibility, tensor diff, bulk emit.

A system evaluation places one allocation per feasible node — the most
TPU-shaped workload in the repo (one fused mask over the whole node axis,
no scan chain: each placement is pinned to its node, so no placement's
decision depends on another's winner). The exact path walks Python per
node: `diff_system_allocs` builds an AllocTuple + `Allocation(NodeID=...)`
per node, `_compute_placements` runs a per-pair select and materializes a
SelectedOption per node. At 10k nodes that is tens of thousands of object
constructions per evaluation before a single allocation exists.

This module computes the same decision as row math on the node tensor:

  existing  [name -> rows]   bitmap of rows already carrying the instance
  place     = eligible & feasible & ~existing      (per task-group instance)
  stop      = existing & (tainted | ~required)     (classified per alloc)
  update    = existing & version-changed           (exact in-place attempt)

and emits the placements as a columnar batch — shared per-task-group
task-resource templates, one shared metric snapshot, one shared resource
vector — plus a :class:`SweepBatch` descriptor (node-row indices + per-row
demand) that the plan applier verifies as ONE vectorized capacity check
per chunk instead of a per-node Python walk.

The exact per-node path survives in system_sched.py for network-ask
groups (port bitmaps are host state) and as the oracle for the
fixed-seed equivalence gate (tests/test_system_sweep_equivalence.py).

Semantics contract: bug-for-bug parity with the exact path on a quiesced
state — same stops (with the same descriptions), same placements, same
in-place updates, same FailedTGAllocs metrics. The node set derives from
the live tensor mirror rather than the snapshot's node walk; the mirror
is updated synchronously at state commit, so it is at least as fresh as
any snapshot and the plan applier's re-verification owns the outcome of
any in-flight divergence (the same contract the windowed service path
documents).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List

import numpy as np

from nomad_tpu.resilience import failpoints
from nomad_tpu.structs import Allocation, Resources
from nomad_tpu.structs.structs import (
    AllocClientStatusPending,
    AllocDesiredStatusRun,
    AllocDesiredStatusStop,
    JobTypeBatch,
    generate_uuid,
)
from nomad_tpu.telemetry import metrics
from nomad_tpu.tensor import alloc_vec, resources_vec
from nomad_tpu.tensor.node_table import DIM_NAMES, RES_DIMS

from .util import (
    ALLOC_NODE_TAINTED,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    AllocTuple,
    attempt_inplace_updates,
    materialize_task_groups,
    tainted_nodes,
    task_group_constraints,
)


@dataclass
class SweepBatch:
    """Columnar descriptor of a system sweep's placements, attached to the
    plan as ``plan._sweep`` (an underscore attribute, like
    ``alloc._resvec_cache``, so RPC serialization never sees it — a remote
    applier simply falls back to the per-node verify).

    One entry per UNIQUE placed node row, in row order; ``delta`` is the
    summed demand of every alloc placed on that row (multi-instance task
    groups fold together). Only rows whose node has NO eviction in the
    same plan are included — eviction credit depends on verify-time
    snapshot state, which the per-node path owns. ``epoch``/``n_rows``
    pin the tensor generation: a row that changed identity between emit
    and verify invalidates the whole descriptor (the applier falls back,
    it never mis-verifies).

    The per-ALLOC columns (``alloc_ids``/``alloc_names``/``alloc_tg``,
    row-sorted, with ``starts`` giving each unique row's alloc range)
    carry the batch the rest of the way: the plan applier encodes an
    admitted sweep chunk as ONE ``ApplySweepBatch`` raft entry straight
    from these columns — ids + instance names + a frozen per-TG template
    — and the state store scatter-applies it without ever walking the
    plan's per-alloc objects."""

    rows: np.ndarray        # [U] int64, sorted unique node rows
    node_ids: List[str]     # [U] aligned node IDs
    delta: np.ndarray       # [U, RES_DIMS] float32 summed placed demand
    epoch: int              # nt.row_epoch at emit
    n_rows: int             # nt.n_rows at emit
    counts: np.ndarray = None       # [U] allocs folded into each row
    starts: np.ndarray = None       # [U+1] per-row alloc offsets
    alloc_ids: List[str] = None     # [K] row-sorted alloc UUIDs
    alloc_names: List[str] = None   # [K] instance names (job.tg[i])
    alloc_tg: List[int] = None      # [K] index into templates
    templates: List = None          # per-TG frozen template Allocations
    # Which emit path built the batch: "system" (tensor sweep) or
    # "service" (pipelined service window, stack._collect_build_all_placed).
    # Carried through the raft entry into the SweepSegment so operators
    # can see which commit path a storm took (sched-stats `Store` block).
    kind: str = "system"

    def slice(self, lo: int, hi: int) -> "SweepBatch":
        """Chunk view for _submit_chunked: shares the backing arrays."""
        if self.starts is None:
            return SweepBatch(rows=self.rows[lo:hi],
                              node_ids=self.node_ids[lo:hi],
                              delta=self.delta[lo:hi],
                              epoch=self.epoch, n_rows=self.n_rows,
                              kind=self.kind)
        s, e = int(self.starts[lo]), int(self.starts[hi])
        return SweepBatch(rows=self.rows[lo:hi],
                          node_ids=self.node_ids[lo:hi],
                          delta=self.delta[lo:hi],
                          epoch=self.epoch, n_rows=self.n_rows,
                          counts=self.counts[lo:hi],
                          starts=self.starts[lo:hi + 1] - s,
                          alloc_ids=self.alloc_ids[s:e],
                          alloc_names=self.alloc_names[s:e],
                          alloc_tg=self.alloc_tg[s:e],
                          templates=self.templates, kind=self.kind)

    def wire(self) -> dict:
        """msgpack-safe encoding for the ApplySweepBatch raft entry (numpy
        arrays become lists; templates stay Allocation objects — to_dict
        flattens them at the consensus boundary). Per-alloc node ids are
        NOT shipped: they re-expand from (node_ids, counts) at apply."""
        return {
            "Kind": self.kind,
            "Templates": self.templates,
            "TGIdx": list(self.alloc_tg),
            "AllocIDs": list(self.alloc_ids),
            "Names": list(self.alloc_names),
            "RowNodeIDs": list(self.node_ids),
            "Counts": [int(c) for c in self.counts],
            "Rows": [int(r) for r in self.rows],
            "Delta": self.delta.tolist(),
            "Epoch": self.epoch,
            "NRows": self.n_rows,
        }


# Escape hatch for A/B benchmarks and oracle runs: True routes every
# system eval onto the exact per-node path regardless of applicability.
FORCE_EXACT = False


def sweep_applicable(job, tindex) -> bool:
    """The tensor-sweep path serves every system eval except: no job (a
    deregister's stop-all walk is O(allocs), not hot) and network asks
    anywhere in the job (port bitmaps are sequential host state — the
    exact per-node path is kept for those, reference: rank.go:150-240's
    network-check-the-winners-only shape)."""
    if FORCE_EXACT or job is None or tindex is None:
        return False
    for tg in job.TaskGroups:
        for task in tg.Tasks:
            if task.Resources is not None and task.Resources.Networks:
                return False
    return True


def compute_job_allocs(sched) -> None:
    """Vectorized body of SystemScheduler._compute_job_allocs for a
    sweep-applicable eval. Mutates sched.plan / sched.failed_tg_allocs
    exactly like the exact path; attaches the plan's SweepBatch. The
    caller guarantees sweep_applicable() and a stack wired via
    adopt_shared (in-place updates run through stack.select_on_node)."""
    t0 = time.monotonic()
    job = sched.job
    state = sched.state
    plan = sched.plan
    ctx = sched.ctx
    nt = sched.tindex.nt
    elig = sched.stack.inner.elig
    m = ctx.metrics

    allocs = [a for a in state.allocs_by_job(sched.eval.JobID)
              if not a.terminal_status()]
    tainted = tainted_nodes(state, allocs)
    required = materialize_task_groups(job)

    # ---- tensor diff: classify existing allocs (O(allocations of this
    # job), the one loop that inherently needs the alloc objects — stops
    # and updates carry them into the plan).
    row_of = nt.row_of
    has_name_rows: Dict[str, List[int]] = {name: [] for name in required}
    updates: List[AllocTuple] = []
    job_mod = job.JobModifyIndex
    for a in allocs:
        name = a.Name
        tg = required.get(name)
        if tg is not None:
            row = row_of.get(a.NodeID)
            if row is not None:
                # Every alloc of a required name marks the row existing —
                # including stopped/updated ones (a tainted stop is not
                # replaced on the same node; an update replaces in place
                # or via the destructive stop+place below).
                has_name_rows[name].append(row)
        if tg is None:
            desc = ALLOC_NODE_TAINTED if tainted.get(a.NodeID) \
                else ALLOC_NOT_NEEDED
            plan.append_update(a, AllocDesiredStatusStop, desc)
            continue
        if tainted.get(a.NodeID, False):
            # Finished batch work stays finished even on a tainted node;
            # system migrations are stops (diff_system_allocs).
            if (a.Job is not None and a.Job.Type == JobTypeBatch
                    and a.ran_successfully()):
                continue
            plan.append_update(a, AllocDesiredStatusStop, ALLOC_NODE_TAINTED)
            continue
        if a.Job is not None and job_mod != a.Job.JobModifyIndex:
            updates.append(AllocTuple(name, tg, a))
        # else: ignore

    # In-place first (non-destructive changes keep the running alloc);
    # the rest stop + replace on the same node (exact per-alloc path —
    # updates are O(existing allocs) and need object-level TG diffs).
    destructive: List[AllocTuple] = []
    if updates:
        destructive, _ = attempt_inplace_updates(
            state, plan, sched.stack.inner, sched.eval.ID, ctx, updates)
        for tup in destructive:
            plan.append_update(tup.Alloc, AllocDesiredStatusStop,
                               ALLOC_UPDATING)

    # ---- fused eligibility: ready & DC membership as one row mask (the
    # sweep's replacement for the ready_nodes_in_dcs state walk).
    dcs = job.Datacenters
    dc_ids = [nt.dc_vocab[d] for d in dcs if d in nt.dc_vocab]
    elig_mask = nt.eligibility_mask(dc_ids, None)

    # One consistent usage snapshot for the whole sweep (alloc commits
    # mutate rows in place; same torn-row hazard snapshot_rows documents).
    with nt._lock:
        usage0 = nt.usage.astype(np.float64, copy=True)
        capacity = nt.capacity.astype(np.float64, copy=True)
    n_snap = usage0.shape[0]
    job_mask, _, _ = elig.job_mask(job.ID, job.Constraints)
    # The table can GROW mid-eval (a node registering crosses a
    # power-of-two boundary), so arrays snapshotted at different moments
    # may disagree on length. Row indices are stable across growth, so
    # clamping to the shortest view just defers newly-grown rows to the
    # next eval (which sees a fresh node_version) — stale-but-safe, never
    # an out-of-bounds gather.
    n0 = min(len(elig_mask), n_snap, len(job_mask))
    if len(elig_mask) > n0:
        elig_mask = elig_mask[:n0]

    # Destructive replacements re-place on their own node even though the
    # name is still "existing" there; dropped when the node is no longer
    # eligible (the exact path's node_by_id miss).
    destructive_rows: Dict[str, List[int]] = {}
    for tup in destructive:
        row = row_of.get(tup.Alloc.NodeID)
        if row is not None and row < n0 and elig_mask[row]:
            destructive_rows.setdefault(tup.Name, []).append(row)

    metrics.measure_since(("nomad", "sched", "system", "diff"), t0)

    # ---- per-TG fused feasibility + bulk emit.
    # No drop semantics at the emit seam: a triggered failpoint always
    # surfaces as a failed sweep (the worker nacks; the broker redelivers
    # the eval exactly once — nothing was submitted).
    if failpoints.fire("sched.system.emit") == "drop":
        raise failpoints.FailpointError("sched.system.emit")
    t1 = time.monotonic()

    # In-plan deltas, whole-table: stops subtract, placements (in-place
    # updates so far, then each TG's winners) add — the batched mirror of
    # select_on_node's per-node plan walk.
    eff_delta = np.zeros((n_snap, RES_DIMS), dtype=np.float64)
    for nid, ups in plan.NodeUpdate.items():
        row = row_of.get(nid)
        if row is None or row >= n_snap:
            continue
        for u in ups:
            full = state.alloc_by_id(u.ID) or u
            eff_delta[row] -= alloc_vec(full)
    for nid, placed in plan.NodeAllocation.items():
        row = row_of.get(nid)
        if row is None or row >= n_snap:
            continue
        for a in placed:
            eff_delta[row] += alloc_vec(a)

    # Group instance names by task group, preserving job.TaskGroups order
    # (the exact path's by_tg first-appearance order).
    by_tg: Dict[str, List[str]] = {}
    tg_obj: Dict[str, object] = {}
    for name, tg in required.items():
        by_tg.setdefault(tg.Name, []).append(name)
        tg_obj[tg.Name] = tg

    any_candidates = bool(destructive_rows) or elig_mask.any()
    if any_candidates and required:
        # NodesAvailable: ready-node count per asked datacenter (the
        # ready_nodes_in_dcs dc_map, computed as one reduction per DC).
        node_by_dc = {dc: 0 for dc in dcs}
        for dc in dcs:
            did = nt.dc_vocab.get(dc)
            if did is not None:
                node_by_dc[dc] = int((nt.ready & (nt.dc_ids == did)).sum())
        m.NodesAvailable = node_by_dc

    node_id_arr = nt.node_id_array()
    nodes_by_row = elig.nodes_by_row
    sweep_rows: List[np.ndarray] = []
    sweep_vecs: List[np.ndarray] = []
    # Per-alloc descriptor columns, appended in lockstep with sweep_rows:
    # the columnar commit path replicates (id, name, template-index) per
    # alloc instead of the alloc objects.
    alloc_ids_l: List[str] = []
    alloc_names_l: List[str] = []
    alloc_tg_l: List[int] = []
    sweep_templates: List[Allocation] = []
    n_emitted = 0

    for tg_name, names in by_tg.items():
        tg = tg_obj[tg_name]
        cons = task_group_constraints(tg)
        tg_mask, _, _ = elig.tg_mask(job.ID, tg.Name, cons.constraints,
                                     cons.drivers)
        # A cached TG mask may predate a table grow; clamp this group's
        # candidate space to the shortest consistent view (see n0 above).
        n_eff = min(n0, len(tg_mask))
        em = elig_mask if n_eff == n0 else elig_mask[:n_eff]
        demand = resources_vec(cons.size).astype(np.float64)
        # Per-dimension exhaustion over the whole axis, float64 like the
        # exact path's fit_lacking; instances of one TG check the same
        # usage (the exact path computes all of a TG's options before
        # appending its allocs), while the NEXT TG sees this one's.
        lacking = (capacity - (usage0 + eff_delta)) < demand[None, :]
        fits = ~lacking.any(axis=1)

        placed_per_name: List[tuple] = []  # (name, ok_rows ndarray)
        n_failed = 0
        for name in names:
            extra = [r for r in destructive_rows.get(name, ()) if r < n_eff]
            named = has_name_rows[name]
            if named or extra:
                cand_mask = em.copy()
                if named:
                    named_arr = np.asarray(named, dtype=np.int64)
                    cand_mask[named_arr[named_arr < n_eff]] = False
                rows = np.flatnonzero(cand_mask)
                if extra:
                    rows = np.concatenate(
                        [rows, np.asarray(extra, dtype=np.int64)])
            else:
                rows = np.flatnonzero(em)
            if not len(rows):
                continue
            # Metrics: the exact counters select_batch_on_nodes
            # accumulates over this instance's candidate pairs.
            m.NodesEvaluated += len(rows)
            job_ok = job_mask[rows]
            tg_ok = tg_mask[rows]
            for sel, label in ((~job_ok, "job constraints"),
                               ((job_ok & ~tg_ok), "group constraints")):
                if sel.any():
                    for r in rows[sel].tolist():
                        m.filter_node(nodes_by_row.get(r), label)
            eligible = job_ok & tg_ok
            ok = eligible & fits[rows]
            exhausted = eligible & ~fits[rows]
            n_ex = int(exhausted.sum())
            if n_ex:
                m.NodesExhausted += n_ex
                per_dim = (lacking[rows] & exhausted[:, None]).sum(axis=0)
                for d, count in enumerate(per_dim.tolist()):
                    if count:
                        dim = DIM_NAMES[d]
                        m.DimensionExhausted[dim] = (
                            m.DimensionExhausted.get(dim, 0) + count)
            ok_rows = rows[ok]
            n_failed += len(rows) - len(ok_rows)
            if len(ok_rows):
                placed_per_name.append((name, ok_rows))

        if n_failed:
            metric = sched.failed_tg_allocs.get(tg.Name)
            if metric is None:
                metric = sched.failed_tg_allocs[tg.Name] = m.copy()
                n_failed -= 1
            metric.CoalescedFailures += n_failed
        if not placed_per_name:
            continue

        # Bulk emit: one frozen task-resources template + one metric
        # snapshot + one resource vector shared by every alloc of the TG
        # (the shared_vec/shared_metric trick extended to the whole
        # sweep; the value-frozen contract is alloc._resvec_cache's).
        tr_template: Dict[str, Resources] = {}
        shared_vec = np.zeros(RES_DIMS, dtype=np.float32)
        for task in tg.Tasks:
            r = (task.Resources.copy() if task.Resources is not None
                 else Resources())
            tr_template[task.Name] = r
            shared_vec += resources_vec(r)
        shared_metric = m.copy()
        node_alloc = plan.NodeAllocation
        # Template stamping: the dataclass constructor runs ~20 field
        # assignments + default factories per call, which at 10k
        # placements is a visible slice of the sweep. One fully-formed
        # template per TG is cloned by __dict__ copy; only the per-alloc
        # identity fields (ID, Name, NodeID) and the mutable per-alloc
        # containers (Services/TaskStates — the client writes into
        # those) are re-set per clone.
        template = Allocation(
            EvalID=sched.eval.ID,
            JobID=job.ID,
            TaskGroup=tg.Name,
            Metrics=shared_metric,
            TaskResources=tr_template,
            DesiredStatus=AllocDesiredStatusRun,
            ClientStatus=AllocClientStatusPending,
        )
        template._resvec_cache = shared_vec
        tmpl_dict = template.__dict__
        tpl_idx = len(sweep_templates)
        sweep_templates.append(template)
        new = object.__new__
        cls = Allocation
        for name, ok_rows in placed_per_name:
            ids = node_id_arr[ok_rows]
            kept: List[int] = []
            for k, nid in enumerate(ids.tolist()):
                if nid is None:
                    continue  # row freed mid-sweep: exact path skips too
                alloc = new(cls)
                alloc.__dict__ = dict(tmpl_dict)
                alloc.ID = generate_uuid()
                alloc.Name = name
                alloc.NodeID = nid
                alloc.Services = {}
                alloc.TaskStates = {}
                bucket = node_alloc.get(nid)
                if bucket is None:
                    node_alloc[nid] = [alloc]
                else:
                    bucket.append(alloc)
                kept.append(k)
                alloc_ids_l.append(alloc.ID)
                alloc_names_l.append(name)
                alloc_tg_l.append(tpl_idx)
            rows_kept = (ok_rows if len(kept) == len(ids)
                         else ok_rows[kept])
            if len(rows_kept):
                n_emitted += len(rows_kept)
                sweep_rows.append(rows_kept.astype(np.int64, copy=False))
                sweep_vecs.append(
                    np.broadcast_to(shared_vec,
                                    (len(rows_kept), RES_DIMS)))
                # The next TG's fit sees this one's placements.
                np.add.at(eff_delta, rows_kept,
                          shared_vec.astype(np.float64))

    if n_emitted:
        rows_all = np.concatenate(sweep_rows)
        vecs_all = np.concatenate(sweep_vecs)
        ur, inv = np.unique(rows_all, return_inverse=True)
        delta = np.zeros((len(ur), RES_DIMS), dtype=np.float32)
        np.add.at(delta, inv, vecs_all)
        ids = node_id_arr[ur]
        ids_list = ids.tolist()
        emitted_per_row = np.bincount(inv, minlength=len(ur))
        # Descriptor coverage: only rows whose plan state the delta FULLY
        # describes. Rows with stops stay on the per-node verify path
        # (eviction credit is verify-time snapshot state), as do rows
        # whose NodeAllocation carries allocs the sweep didn't emit —
        # in-place updates on a node that also received a fresh instance
        # need the exact remove-then-add accounting.
        keep = np.asarray(
            [nid not in plan.NodeUpdate
             and len(plan.NodeAllocation[nid]) == emitted_per_row[k]
             for k, nid in enumerate(ids_list)], dtype=bool)
        # Per-alloc columns, sorted into unique-row order so a node-range
        # chunk slice maps to a contiguous alloc range (starts).
        order = np.argsort(rows_all, kind="stable")
        keep_alloc = keep[inv][order]
        aid_sorted = np.asarray(alloc_ids_l, dtype=object)[order]
        name_sorted = np.asarray(alloc_names_l, dtype=object)[order]
        tg_sorted = np.asarray(alloc_tg_l, dtype=np.int64)[order]
        counts = emitted_per_row
        if not keep.all():
            ur, delta = ur[keep], delta[keep]
            ids_list = [nid for nid, k in zip(ids_list, keep.tolist()) if k]
            counts = emitted_per_row[keep]
            aid_sorted = aid_sorted[keep_alloc]
            name_sorted = name_sorted[keep_alloc]
            tg_sorted = tg_sorted[keep_alloc]
        starts = np.concatenate(
            [np.zeros(1, dtype=np.int64),
             np.cumsum(counts, dtype=np.int64)])
        plan._sweep = SweepBatch(rows=ur, node_ids=ids_list,
                                 delta=delta, epoch=nt.row_epoch,
                                 n_rows=nt.n_rows,
                                 counts=counts, starts=starts,
                                 alloc_ids=aid_sorted.tolist(),
                                 alloc_names=name_sorted.tolist(),
                                 alloc_tg=tg_sorted.tolist(),
                                 templates=sweep_templates)
        metrics.incr_counter(("nomad", "sched", "system", "placed"),
                             n_emitted)
    metrics.measure_since(("nomad", "sched", "system", "emit"), t1)
