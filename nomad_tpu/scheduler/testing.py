"""Scheduler test harness (reference: scheduler/testing.go).

Runs real schedulers against a real StateStore with a fake Planner that
applies plans directly and records everything — the backbone of the scenario
test suite (reference: generic_sched_test.go, system_sched_test.go).
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Tuple

from nomad_tpu.state.state_store import StateStore
from nomad_tpu.structs import Allocation, Evaluation, Plan, PlanResult
from nomad_tpu.tensor import TensorIndex

from .scheduler import new_scheduler

logger = logging.getLogger("sched.harness")


class Harness:
    """In-process State + Planner capture (reference: testing.go:36-207)."""

    def __init__(self, state: Optional[StateStore] = None):
        self.state = state or StateStore()
        self.tindex = TensorIndex.attach(self.state)
        self._lock = threading.Lock()
        self.next_index = 1

        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.creates: List[Evaluation] = []
        self.reblocks: List[Evaluation] = []
        self.reject_plan = False

    # ----------------------------------------------------------- planner API
    def submit_plan(self, plan: Plan) -> Tuple[Optional[PlanResult], Optional[object]]:
        """Apply the plan directly to the store (reference: testing.go:68-125)."""
        with self._lock:
            self.plans.append(plan)
            if self.reject_plan:
                # Refresh requested: hand back the current state snapshot.
                return PlanResult(RefreshIndex=self.state.latest_index()), self.state.snapshot()

            index = self._next_index()
            result = PlanResult(
                NodeUpdate=plan.NodeUpdate,
                NodeAllocation=plan.NodeAllocation,
                AllocIndex=index,
            )

            # Flatten updates + placements into one alloc upsert, attaching
            # the plan's job to placements (reference: testing.go:96-118).
            allocs: List[Allocation] = []
            for updates in plan.NodeUpdate.values():
                allocs.extend(updates)
            for placed in plan.NodeAllocation.values():
                for alloc in placed:
                    if alloc.Job is None:
                        alloc.Job = plan.Job
                    allocs.append(alloc)
            self.state.upsert_allocs(index, allocs)
            return result, None

    def update_eval(self, eval: Evaluation) -> None:
        with self._lock:
            self.evals.append(eval)

    def create_eval(self, eval: Evaluation) -> None:
        with self._lock:
            self.creates.append(eval)

    def reblock_eval(self, eval: Evaluation) -> None:
        with self._lock:
            self.reblocks.append(eval)

    # -------------------------------------------------------------- helpers
    def _next_index(self) -> int:
        idx = max(self.next_index, self.state.latest_index() + 1)
        self.next_index = idx + 1
        return idx

    def upsert(self, obj_kind: str, obj) -> int:
        """Convenience store writer with auto index."""
        idx = self._next_index()
        if obj_kind == "node":
            self.state.upsert_node(idx, obj)
        elif obj_kind == "job":
            self.state.upsert_job(idx, obj)
        elif obj_kind == "evals":
            self.state.upsert_evals(idx, obj)
        elif obj_kind == "allocs":
            self.state.upsert_allocs(idx, obj)
        else:
            raise ValueError(obj_kind)
        return idx

    def process(self, scheduler_name: str, eval: Evaluation) -> None:
        """Run a scheduler end to end against a state snapshot
        (reference: testing.go:183-196)."""
        snap = self.state.snapshot()
        sched = new_scheduler(scheduler_name, snap, self, self.tindex, logger)
        sched.process(eval)
