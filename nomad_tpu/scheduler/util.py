"""Reconciler utilities (reference: scheduler/util.go).

Pure host-side logic: O(allocations of one job), not the hot path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from nomad_tpu.structs import (
    Allocation,
    Constraint,
    DesiredUpdates,
    Job,
    Node,
    Resources,
    TaskGroup,
)
from nomad_tpu.structs.structs import (
    AllocDesiredStatusStop,
    EvalStatusFailed,
    JobTypeBatch,
    NodeStatusReady,
    should_drain_node,
)

from .scheduler import SetStatusError, State

# Descriptions used on plan updates (reference: generic_sched.go:20-39)
ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_UPDATING = "alloc is being updated due to job update"
ALLOC_IN_PLACE = "alloc updating in-place"
ALLOC_NODE_TAINTED = "alloc not needed as node is tainted"
BLOCKED_EVAL_MAX_PLAN = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"


@dataclass
class AllocTuple:
    """(name, task group, existing alloc) (reference: util.go:12-17)."""

    Name: str
    TaskGroup: Optional[TaskGroup]
    Alloc: Optional[Allocation] = None


@dataclass
class DiffResult:
    place: List[AllocTuple] = field(default_factory=list)
    update: List[AllocTuple] = field(default_factory=list)
    migrate: List[AllocTuple] = field(default_factory=list)
    stop: List[AllocTuple] = field(default_factory=list)
    ignore: List[AllocTuple] = field(default_factory=list)

    def append(self, other: "DiffResult") -> None:
        self.place.extend(other.place)
        self.update.extend(other.update)
        self.migrate.extend(other.migrate)
        self.stop.extend(other.stop)
        self.ignore.extend(other.ignore)


def materialize_task_groups(job: Optional[Job]) -> Dict[str, TaskGroup]:
    """Count expansion: name -> TG, names `job.tg[i]` (reference: util.go:21-34)."""
    out: Dict[str, TaskGroup] = {}
    if job is None:
        return out
    for tg in job.TaskGroups:
        for i in range(tg.Count):
            out[f"{job.Name}.{tg.Name}[{i}]"] = tg
    return out


def diff_allocs(job: Optional[Job], tainted: Dict[str, bool],
                required: Dict[str, TaskGroup],
                allocs: List[Allocation]) -> DiffResult:
    """Set difference of required vs existing (reference: util.go:60-138)."""
    result = DiffResult()
    existing = set()
    for exist in allocs:
        name = exist.Name
        existing.add(name)
        tg = required.get(name)
        if tg is None:
            result.stop.append(AllocTuple(name, tg, exist))
            continue
        if tainted.get(exist.NodeID, False):
            # Finished batch work stays finished even on a tainted node.
            if (exist.Job is not None and exist.Job.Type == JobTypeBatch
                    and exist.ran_successfully()):
                result.ignore.append(AllocTuple(name, tg, exist))
            else:
                result.migrate.append(AllocTuple(name, tg, exist))
            continue
        if (job is not None and exist.Job is not None
                and job.JobModifyIndex != exist.Job.JobModifyIndex):
            result.update.append(AllocTuple(name, tg, exist))
            continue
        result.ignore.append(AllocTuple(name, tg, exist))
    for name, tg in required.items():
        if name not in existing:
            result.place.append(AllocTuple(name, tg))
    return result


def diff_system_allocs(job: Job, nodes: List[Node], tainted: Dict[str, bool],
                       allocs: List[Allocation]) -> DiffResult:
    """Per-node diff for system jobs; placements carry their target node
    (reference: util.go:142-181).

    Nodes with NO existing allocs — the whole fleet on a fresh job
    register, most of it on any re-evaluation — short-circuit straight to
    placements: running the full diff machinery (DiffResult + nested
    loops) per node costs ~10x the AllocTuple emission itself at 10k-node
    system sweeps."""
    node_allocs: Dict[str, List[Allocation]] = {}
    for alloc in allocs:
        node_allocs.setdefault(alloc.NodeID, []).append(alloc)

    required = materialize_task_groups(job)
    req_items = list(required.items())
    result = DiffResult()
    place = result.place
    emitted: set = set()  # a duplicated Node entry must not double-place
    for node in nodes:
        if node.ID not in node_allocs and node.ID not in emitted:
            emitted.add(node.ID)
            for name, tg in req_items:
                place.append(AllocTuple(name, tg, Allocation(NodeID=node.ID)))
    for node_id, nallocs in node_allocs.items():
        diff = diff_allocs(job, tainted, required, nallocs)
        for tup in diff.place:
            tup.Alloc = Allocation(NodeID=node_id)
        # Migrations don't apply to system jobs: tainted node => stop.
        diff.stop.extend(diff.migrate)
        diff.migrate = []
        result.append(diff)
    return result


def ready_nodes_in_dcs(state: State, dcs: List[str]) -> Tuple[List[Node], Dict[str, int]]:
    """(reference: util.go:184-221)"""
    dc_map = {dc: 0 for dc in dcs}
    out = []
    for node in state.nodes():
        if node.Status != NodeStatusReady or node.Drain:
            continue
        if node.Datacenter not in dc_map:
            continue
        out.append(node)
        dc_map[node.Datacenter] += 1
    return out, dc_map


def retry_max(max_attempts: int, cb: Callable[[], bool],
              reset: Optional[Callable[[], bool]] = None) -> None:
    """Retry until success with optional progress-based reset
    (reference: util.go:224-248)."""
    attempts = 0
    while attempts < max_attempts:
        if cb():
            return
        if reset is not None and reset():
            attempts = 0
        else:
            attempts += 1
    raise SetStatusError(f"maximum attempts reached ({max_attempts})",
                         EvalStatusFailed)


def progress_made(result) -> bool:
    """(reference: util.go:252-255)"""
    return result is not None and (bool(result.NodeUpdate)
                                   or bool(result.NodeAllocation))


def tainted_nodes(state: State, allocs: List[Allocation]) -> Dict[str, bool]:
    """Nodes whose allocs must migrate (reference: util.go:259-278)."""
    out: Dict[str, bool] = {}
    for alloc in allocs:
        if alloc.NodeID in out:
            continue
        node = state.node_by_id(alloc.NodeID)
        if node is None:
            out[alloc.NodeID] = True
            continue
        out[alloc.NodeID] = should_drain_node(node.Status) or node.Drain
    return out


def tasks_updated(a: TaskGroup, b: TaskGroup) -> bool:
    """Field-sensitive update classifier: does the TG change require a
    destructive update? (reference: util.go:291-352)"""
    if len(a.Tasks) != len(b.Tasks):
        return True
    for at in a.Tasks:
        bt = b.lookup_task(at.Name)
        if bt is None:
            return True
        if (at.Driver != bt.Driver or at.User != bt.User
                or at.Config != bt.Config or at.Env != bt.Env
                or at.Meta != bt.Meta or at.Artifacts != bt.Artifacts):
            return True
        ar, br = at.Resources, bt.Resources
        if ar is None or br is None:
            if ar is not br:
                return True
            continue
        if len(ar.Networks) != len(br.Networks):
            return True
        for an, bn in zip(ar.Networks, br.Networks):
            if an.MBits != bn.MBits:
                return True
            if _network_port_map(an) != _network_port_map(bn):
                return True
        if (ar.CPU != br.CPU or ar.MemoryMB != br.MemoryMB
                or ar.DiskMB != br.DiskMB or ar.IOPS != br.IOPS):
            return True
    return False


def _network_port_map(n) -> Dict[str, int]:
    """Dynamic port values are ignored for comparison (reference: util.go:356-366)."""
    out = {p.Label: p.Value for p in n.ReservedPorts}
    out.update({p.Label: -1 for p in n.DynamicPorts})
    return out


def evict_and_place(ctx, diff: DiffResult, allocs: List[AllocTuple],
                    desc: str, limit: List[int]) -> bool:
    """Evict up to limit[0] and queue replacements; True if limit reached
    (reference: util.go:471-485). limit is a 1-element mutable cell."""
    n = len(allocs)
    for i in range(min(n, limit[0])):
        a = allocs[i]
        ctx.plan.append_update(a.Alloc, AllocDesiredStatusStop, desc)
        diff.place.append(a)
    if n <= limit[0]:
        limit[0] -= n
        return False
    limit[0] = 0
    return True


@dataclass
class TGConstraints:
    """Aggregated TG constraints/drivers/size (reference: util.go:488-510)."""

    constraints: List[Constraint]
    drivers: List[str]
    size: Resources


def task_group_constraints(tg: TaskGroup) -> TGConstraints:
    constraints = list(tg.Constraints)
    drivers = []
    size = Resources()
    for task in tg.Tasks:
        if task.Driver not in drivers:
            drivers.append(task.Driver)
        constraints.extend(task.Constraints)
        size.add(task.Resources)
    return TGConstraints(constraints, drivers, size)


def desired_updates(diff: DiffResult, inplace: List[AllocTuple],
                    destructive: List[AllocTuple]) -> Dict[str, DesiredUpdates]:
    """Per-TG desired-change counts for plan annotations
    (reference: util.go:513-595)."""
    out: Dict[str, DesiredUpdates] = {}

    def get(name: str) -> DesiredUpdates:
        if name not in out:
            out[name] = DesiredUpdates()
        return out[name]

    for tup in diff.place:
        get(tup.TaskGroup.Name).Place += 1
    for tup in diff.stop:
        get(tup.Alloc.TaskGroup).Stop += 1
    for tup in diff.ignore:
        get(tup.TaskGroup.Name).Ignore += 1
    for tup in diff.migrate:
        get(tup.TaskGroup.Name).Migrate += 1
    for tup in inplace:
        get(tup.TaskGroup.Name).InPlaceUpdate += 1
    for tup in destructive:
        get(tup.TaskGroup.Name).DestructiveUpdate += 1
    return out


def set_status(planner, eval, next_eval, spawned_blocked, tg_metrics,
               status: str, desc: str) -> None:
    """Write the eval's terminal status through the planner
    (reference: util.go:369-386)."""
    new_eval = eval.copy()
    new_eval.Status = status
    new_eval.StatusDescription = desc
    new_eval.FailedTGAllocs = tg_metrics or {}
    if next_eval is not None:
        new_eval.NextEval = next_eval.ID
    if spawned_blocked is not None:
        new_eval.BlockedEval = spawned_blocked.ID
    planner.update_eval(new_eval)


def attempt_inplace_updates(state, plan, stack, eval_id, ctx, updates):
    """Split updated allocs into (destructive, inplace); in-place winners
    are appended to the plan with refreshed resources (reference:
    inplaceUpdate, util.go:389-468). `stack` must expose select_on_node.
    Shared by the generic and system schedulers."""
    from nomad_tpu.structs.structs import (
        AllocClientStatusPending,
        AllocDesiredStatusRun,
        AllocDesiredStatusStop,
    )

    destructive = []
    inplace = []
    for tup in updates:
        existing_tg = (tup.Alloc.Job.lookup_task_group(tup.TaskGroup.Name)
                       if tup.Alloc.Job is not None else None)
        if existing_tg is None or tasks_updated(tup.TaskGroup, existing_tg):
            destructive.append(tup)
            continue
        node = state.node_by_id(tup.Alloc.NodeID)
        if node is None:
            destructive.append(tup)
            continue
        # Stage an eviction so the current alloc is discounted in the fit.
        plan.append_update(tup.Alloc, AllocDesiredStatusStop, ALLOC_IN_PLACE)
        option = stack.select_on_node(tup.TaskGroup, node)
        plan.pop_update(tup.Alloc)
        if option is None:
            destructive.append(tup)
            continue
        # Networks are not updatable in place; restore existing offers.
        for task_name, resources in option.task_resources.items():
            existing_res = tup.Alloc.TaskResources.get(task_name)
            if existing_res is not None:
                resources.Networks = existing_res.Networks
        new_alloc = tup.Alloc.copy()
        new_alloc.EvalID = eval_id
        new_alloc.Job = None  # the plan carries the job
        new_alloc.Resources = None  # computed at plan apply
        new_alloc.TaskResources = option.task_resources
        new_alloc.Metrics = ctx.metrics.copy()
        new_alloc.DesiredStatus = AllocDesiredStatusRun
        new_alloc.ClientStatus = AllocClientStatusPending
        plan.append_alloc(new_alloc)
        inplace.append(tup)
    return destructive, inplace
