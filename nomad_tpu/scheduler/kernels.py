"""XLA placement kernels: the scheduling hot path as tensor programs.

This replaces the reference's per-node iterator chain (reference:
scheduler/stack.go Select -> select.go MaxScoreIterator -> rank.go
BinPackIterator -> feasible.go checkers) with batched device programs:

  place_batch   lax.scan over the placements of one evaluation; each step is
                a fused feasibility-mask + BestFit-v3 score + argmax over the
                whole node axis, with in-register usage/anti-affinity updates
                so placement k+1 sees placement k's proposed allocation
                (reference semantics: scheduler/context.go:109-140).

Scoring matches reference funcs.go:102-137 (including its Inf/NaN division
edges) with the job anti-affinity penalty applied after clamping (reference:
rank.go:242-304). Selection is a global argmax rather than the reference's
max-over-log2(n)-random-candidates (reference: stack.go:120-133), which can
only improve placement quality; host-supplied per-node noise reproduces the
load-spreading effect of the reference's node shuffle on ties.

All shapes are static per (N_pad, P_pad) bucket: the node axis is padded to a
power of two by NodeTensor and the placement axis by the stack, so jit caches
stay warm. The node axis is the sharding axis for multi-chip meshes
(nomad_tpu/parallel/).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_LOG2_10 = float(np.log2(10.0))


class PlacementResult(NamedTuple):
    packed: jax.Array       # [P, 3] f32: (chosen row or -1, score, n_feasible)
    usage_after: jax.Array  # [N, R] usage including the new placements

    # The packed layout exists because a device->host readback has a fixed
    # RTT cost on remote-attached TPUs: one transfer per eval, not three.
    @property
    def chosen(self):
        return self.packed[:, 0].astype(jnp.int32)

    @property
    def scores(self):
        return self.packed[:, 1]

    @property
    def n_feasible(self):
        return self.packed[:, 2].astype(jnp.int32)


def _score(usage2: jax.Array, score_cap: jax.Array) -> jax.Array:
    """BestFit-v3: 20 - 10^freeCpuPct - 10^freeMemPct, clamped to [0, 18].

    usage2 [..., 2] is proposed (cpu, mem) utilization including reserved;
    score_cap [..., 2] is capacity minus reserved (broadcastable). Division
    by zero follows IEEE (Inf/NaN) exactly like the Go reference; NaN
    sanitizes to 0. THE one definition of the formula — the monolithic
    scan, the keyed kernel's three passes, and the host mirror must all
    agree bit-for-bit.
    """
    free_pct = 1.0 - usage2 / score_cap
    # 10^x on the MXU-friendly path: exp2(x * log2 10).
    total = (jnp.exp2(free_pct[..., 0] * _LOG2_10)
             + jnp.exp2(free_pct[..., 1] * _LOG2_10))
    score = jnp.clip(20.0 - total, 0.0, 18.0)
    return jnp.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)


def _make_step(capacity, score_cap, tg_masks, noise, penalty,
               distinct_hosts, job_counts0=None, banned0=None):
    """The ONE definition of the per-placement scan step (fused
    feasibility mask + BestFit-v3 score + argmax + in-register state
    updates). place_batch uses the plain (demand, tg_id, valid) input
    tuple; place_batch_multi adds a reset flag that reloads the per-JOB
    state (anti-affinity counts, distinct-hosts bans) at eval boundaries.
    Sharing the body keeps single/multi/chained parity by construction."""

    def step(carry, inputs):
        usage, job_counts, banned = carry
        if len(inputs) == 4:
            demand, tg_id, is_valid, is_reset = inputs
            job_counts = jnp.where(is_reset, job_counts0, job_counts)
            banned = jnp.where(is_reset, banned0, banned)
        else:
            demand, tg_id, is_valid = inputs
        eligible = tg_masks[tg_id]

        fits = jnp.all(capacity - usage >= demand[None, :], axis=1)
        ok = fits & eligible & ~(distinct_hosts & banned)

        util2 = usage[:, :2] + demand[None, :2]
        score = _score(util2, score_cap)
        score = score - job_counts.astype(jnp.float32) * penalty + noise
        masked = jnp.where(ok, score, -jnp.inf)

        idx = jnp.argmax(masked)
        found = ok[idx] & is_valid

        one = found.astype(usage.dtype)
        usage = usage.at[idx].add(demand * one)
        job_counts = job_counts.at[idx].add(found.astype(job_counts.dtype))
        banned = banned.at[idx].set(banned[idx] | found)

        out = jnp.stack([
            jnp.where(found, idx, -1).astype(jnp.float32),
            jnp.where(found, masked[idx], -jnp.inf),
            jnp.sum(ok).astype(jnp.float32),
        ])
        return (usage, job_counts, banned), out

    return step


@functools.partial(jax.jit, donate_argnums=())
def place_batch(
    capacity: jax.Array,    # [N, R] total resources (fit bound)
    score_cap: jax.Array,   # [N, 2] cpu/mem minus reserved (score denominator)
    usage: jax.Array,       # [N, R] reserved + committed allocs (+/- plan deltas)
    tg_masks: jax.Array,    # [T, N] bool per task group: ready & dc & class & escaped
    job_counts: jax.Array,  # [N] int32 proposed allocs of this job per node
    demands: jax.Array,     # [P, R] per-placement resource ask
    tg_ids: jax.Array,      # [P] int32 task-group index into tg_masks
    valid: jax.Array,       # [P] bool: real placement vs padding
    noise: jax.Array,       # [N] f32 tie-break jitter in [0, 1e-3)
    penalty: jax.Array,     # f32 job anti-affinity penalty (10 service / 5 batch)
    distinct_hosts: jax.Array,  # bool: job has a distinct_hosts constraint
    banned0: jax.Array,     # [N] bool: nodes already holding this job's allocs
) -> PlacementResult:
    step = _make_step(capacity, score_cap, tg_masks, noise, penalty,
                      distinct_hosts)
    (usage, _, _), packed = jax.lax.scan(
        step, (usage, job_counts, banned0), (demands, tg_ids, valid))
    return PlacementResult(packed, usage)


@functools.partial(jax.jit, donate_argnums=())
def place_batch_multi(
    capacity: jax.Array,    # [N, R]
    score_cap: jax.Array,   # [N, 2]
    usage: jax.Array,       # [N, R] chain input (window-sequential)
    tg_masks: jax.Array,    # [T, N] shared across the window's evals
    job_counts0: jax.Array,  # [N] per-eval anti-affinity base (shared)
    demands: jax.Array,     # [E*P, R] all evals' placements, concatenated
    tg_ids: jax.Array,      # [E*P]
    valid: jax.Array,       # [E*P]
    noise: jax.Array,       # [N]
    penalty: jax.Array,     # f32
    distinct_hosts: jax.Array,  # bool (shared job shape)
    banned0: jax.Array,     # [N] per-eval distinct-hosts base (shared)
    reset: jax.Array,       # [E*P] bool: True at each eval's first step
) -> PlacementResult:
    """One scan over a WHOLE WINDOW of same-shaped evaluations.

    A registration storm's window is N near-identical evals whose prepared
    inputs dedupe to one PreparedBatch; dispatching place_batch per eval
    pays a host->device launch per eval plus an eager jnp.stack over the
    window at drain (both scale with window size and dominate on a
    remote-attached TPU). This kernel concatenates the placements and
    resets the per-JOB state (anti-affinity counts, distinct-hosts bans)
    at each eval boundary, so the whole window is ONE dispatch and ONE
    readback while usage chains exactly as the per-eval kernels did
    (reference sequencing semantics: scheduler/context.go:109-140 within
    an eval; optimistic worker chaining across evals)."""
    step = _make_step(capacity, score_cap, tg_masks, noise, penalty,
                      distinct_hosts, job_counts0=job_counts0,
                      banned0=banned0)
    (usage, _, _), packed = jax.lax.scan(
        step, (usage, job_counts0, banned0),
        (demands, tg_ids, valid, reset))
    return PlacementResult(packed, usage)


class CompactResult(NamedTuple):
    """Host-side per-eval view of a compacted window result: exactly the
    arrays the plan build consumes, in the dtypes it consumes them
    (packed's f32 triple forces a cast + tolist per column per eval on
    the host otherwise)."""

    chosen: np.ndarray   # [P_pad] int32 chosen row per placement (-1 = none)
    scores: np.ndarray   # [P_pad] f32 winning score per placement
    nf_last: int         # n_feasible of the eval's LAST valid placement
    ok: bool             # every valid placement found a row


@jax.jit
def compact_window(packed3, valid, last_idx):
    """On-device reduction of a window's packed kernel outputs to the
    minimal arrays the host build actually needs, BEFORE the device->host
    copy: chosen rows as int32, winner scores, the per-eval n_feasible of
    the final valid placement (the only one metrics keep — earlier fills
    are overwritten before anything snapshots them), and a per-eval
    success mask so the host can branch straight into the vectorized
    all-placed build without scanning. Cuts the transfer by ~1/3 against
    the raw [*, 3] f32 layout and moves every cast off the host.

    packed3 [E, P, 3]; valid [E, P] bool; last_idx [E] int32 (index of
    each eval's last valid placement). Returns (chosen [E, P] int32,
    scores [E, P] f32, nf_last [E] int32, ok [E] bool)."""
    chosen = packed3[..., 0].astype(jnp.int32)
    scores = packed3[..., 1]
    nf_last = jnp.take_along_axis(
        packed3[..., 2], last_idx[:, None].astype(jnp.int32), axis=1
    )[:, 0].astype(jnp.int32)
    ok = jnp.all((chosen >= 0) | ~valid, axis=1)
    return chosen, scores, nf_last, ok


def compact_host(packed: np.ndarray, n_valid: int) -> CompactResult:
    """Numpy mirror of compact_window for one already-host-side result
    (host-placed evals and non-jax test arrays skip the device entirely)."""
    packed = np.asarray(packed)
    chosen = packed[:, 0].astype(np.int32)
    return CompactResult(
        chosen=chosen,
        scores=packed[:, 1].astype(np.float32, copy=False),
        nf_last=int(packed[n_valid - 1, 2]),
        ok=bool((chosen[:n_valid] >= 0).all()))


_LOG2_10_F32 = np.float32(_LOG2_10)


def place_batch_host(capacity, score_cap, usage, tg_masks, job_counts,
                     demands, tg_ids, valid, noise, penalty,
                     distinct_hosts, banned0) -> PlacementResult:
    """Numpy mirror of place_batch for SHALLOW windows.

    On a remote-attached TPU every host sync costs a fixed ~100ms round
    trip (and the first device->host transfer pins the whole process into
    that mode), so a lone eval's 50 placements are orders of magnitude
    faster as host vector ops than as a device dispatch + readback. The
    pipelined worker routes small idle-broker windows here and storms to
    the device chain; semantics are identical — same f32 BestFit-v3
    formula with its Inf/NaN edges (reference funcs.go:102-137), same
    anti-affinity penalty and noise tie-break, same in-loop usage updates
    so placement k+1 sees placement k (reference context semantics,
    scheduler/context.go:109-140). tests/test_tensor_and_kernels.py
    asserts parity against the device kernel."""
    capacity = np.asarray(capacity, np.float32)
    score_cap = np.asarray(score_cap, np.float32)
    usage = np.array(usage, np.float32, copy=True)
    job_counts = np.array(job_counts, np.int32, copy=True)
    banned = np.array(banned0, bool, copy=True)
    demands = np.asarray(demands, np.float32)
    tg_ids = np.asarray(tg_ids, np.int32)
    valid = np.asarray(valid, bool)
    noise = np.asarray(noise, np.float32)
    penalty = np.float32(penalty)
    distinct_hosts = bool(distinct_hosts)
    tg_masks = np.asarray(tg_masks, bool)

    p = len(tg_ids)
    packed = np.empty((p, 3), np.float32)
    neg_inf = np.float32(-np.inf)

    def full_scores(demand):
        """Whole-table masked-score pass — the same f32 formula as the
        device kernel's step."""
        util2 = usage[:, :2] + demand[:2]
        free_pct = np.float32(1.0) - util2 / score_cap
        total = (np.exp2(free_pct[:, 0] * _LOG2_10_F32)
                 + np.exp2(free_pct[:, 1] * _LOG2_10_F32))
        score = np.clip(np.float32(20.0) - total,
                        np.float32(0.0), np.float32(18.0))
        score = np.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)
        return score - job_counts.astype(np.float32) * penalty + noise

    def row_score(idx, demand):
        """One row of full_scores, recomputed after the row's usage or
        count changed — bit-identical to the full pass for that row."""
        util2 = usage[idx, :2] + demand[:2]
        free_pct = np.float32(1.0) - util2 / score_cap[idx]
        total = (np.exp2(free_pct[0] * _LOG2_10_F32)
                 + np.exp2(free_pct[1] * _LOG2_10_F32))
        score = np.float32(np.clip(np.float32(20.0) - total,
                                   np.float32(0.0), np.float32(18.0)))
        score = np.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)
        return (score - np.float32(job_counts[idx]) * penalty
                + noise[idx])

    with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
        # A storm places many copies of the same task group: between two
        # placements of one (tg, demand) key only ONE node row changes, so
        # the masked-score vector is computed once per key and patched at
        # the placed row afterwards — O(rows) once + O(keys) per step
        # instead of O(rows) per step. Exactly the same f32 values as the
        # naive loop (each row's score is a pure function of that row).
        cache: dict = {}  # key -> [masked, ok, n_feasible, demand, tg]
        for k in range(p):
            demand = demands[k]
            tg = int(tg_ids[k])
            key = (tg, demand.tobytes())
            ent = cache.get(key)
            if ent is None:
                eligible = tg_masks[tg]
                fits = np.all(capacity - usage >= demand[None, :], axis=1)
                ok = fits & eligible
                if distinct_hosts:
                    ok &= ~banned
                masked = np.where(ok, full_scores(demand), neg_inf)
                ent = cache[key] = [masked, ok,
                                    np.float32(np.count_nonzero(ok)),
                                    demand, tg]
            masked, ok, n_feasible = ent[0], ent[1], ent[2]
            idx = int(np.argmax(masked))
            found = bool(ok[idx]) and bool(valid[k])
            packed[k, 0] = np.float32(idx) if found else np.float32(-1)
            packed[k, 1] = masked[idx] if found else neg_inf
            packed[k, 2] = n_feasible
            if found:
                usage[idx] += demand
                job_counts[idx] += 1
                banned[idx] = True
                # Patch the changed row into every cached key: a row's
                # score/feasibility is a pure function of that row, so the
                # patched vectors stay identical to a full recompute.
                cap_row = capacity[idx]
                usage_row = usage[idx]
                for cent in cache.values():
                    cmask, cok, cn, cdemand, ctg = cent
                    old_ok = bool(cok[idx])
                    new_ok = (bool(np.all(cap_row - usage_row >= cdemand))
                              and bool(tg_masks[ctg, idx]))
                    if distinct_hosts:
                        new_ok = new_ok and not banned[idx]
                    cok[idx] = new_ok
                    cmask[idx] = (row_score(idx, cdemand) if new_ok
                                  else neg_inf)
                    if new_ok != old_ok:
                        cent[2] = np.float32(
                            cn + (1.0 if new_ok else -1.0))
    # Same result type as the device kernel; both arrays are
    # host-side numpy here — the pipelined drain dispatches on
    # isinstance(packed, np.ndarray) and skips the readback.
    return PlacementResult(packed, usage)


# ----------------------------------------------------- keyed candidates
# Candidate-set placement: the storm kernel for meshes AND single chips.
#
# Every PreparedBatch satisfies demands[p] == tg_demands[tg_ids[p]]
# (stack.prepare), so a window of P placements draws from at most T
# distinct (task-group, demand) KEYS — and the monolithic scan's full
# score pass per placement is P/T-fold redundant. Worse, under SPMD
# sharding that scan issues a global argmax plus a global sum PER
# PLACEMENT over the sharded node axis — two latency-bound ICI
# collectives serialized by the scan (measured 0.65x at 8 devices in
# round 4). This kernel restructures the whole window around candidate
# sets:
#
#   1. ONE vectorized score pass per KEY over local rows at window start
#      (masked BestFit-v3, [T, n_loc]) — no collective.
#   2. Each shard takes its local top-K candidate rows per key
#      (lax.top_k; ties break to the lowest index, same as argmax),
#      where K = the window's valid placement count.
#   3. ONE all_gather ships the candidate packets (row data + per-key
#      eligibility, (2R + 6 + T) f32 each).
#   4. Candidates sort by global row id (argmax tie parity), dedup, and
#      trim to the GLOBAL top-K per key, so the replay size is
#      independent of the device count.
#   5. Every device replays the exact P-step sequential chain — resets,
#      bans, anti-affinity, the same f32 score ops — over the replicated
#      candidate table; each shard then applies the winners' usage
#      updates to rows it owns. One psum publishes the packed result.
#
# Exactness: at step j, every modified row is a prior winner (in the
# candidate set by induction, and within its key's global top-K by this
# same argument). The winner is either such a row, or the best
# UNMODIFIED row — every row ranked above it at window start for its key
# is modified (else it would win now), so its window-start rank is
# <= j <= K and it survives both the local top-K and the global trim.
# Feasibility is monotonic within a window (usage only grows, bans only
# appear mid-eval, eligibility is static) and eval-boundary resets
# restore unmodified rows to exactly their window-start scores, so the
# window-start ranking remains valid across resets. The replay
# recomputes scores from shipped row data with the exact same f32 ops as
# the monolithic step, so results are bit-identical for valid
# placements (tests assert this against place_batch/place_batch_multi).
# For padding placements (valid=False) chosen=-1 and score=-inf as
# always, but the n_feasible column is unspecified (the monolithic
# kernels compute it with the padding's zeroed demand; no consumer reads
# it).
#
# Collectives per window: 2 (one all_gather, one psum) — versus 2P for
# the naive SPMD scan. Total work per window: one score pass per key
# plus an O(K * T)-row replay — versus P full-table passes.


@functools.lru_cache(maxsize=64)
def _keyed_program(mesh, k_cand: int):
    """Build the jitted keyed-candidate program. mesh=None compiles the
    single-device variant (no collectives, same candidate semantics)."""
    if mesh is not None:
        axis = mesh.axis_names[0]
        n_shards = int(mesh.devices.size)
    else:
        axis = None
        n_shards = 1

    def local_fn(capacity, score_cap, usage, tg_masks, job_counts0,
                 key_demands, tg_ids, valid, noise, penalty, distinct,
                 banned0, reset):
        n_loc, r_dims = capacity.shape
        n_keys = key_demands.shape[0]
        if axis is not None:
            my = jax.lax.axis_index(axis)
            row_base = (my * n_loc).astype(jnp.int32)
        else:
            my = jnp.int32(0)
            row_base = jnp.int32(0)

        # ---- window-start score pass: one per key over local rows.
        fits0 = jnp.all(capacity[None] - usage[None]
                        >= key_demands[:, None, :], axis=-1)
        ok0 = fits0 & tg_masks & ~(distinct & banned0)[None, :]
        util2 = usage[None, :, :2] + key_demands[:, None, :2]
        score = _score(util2, score_cap[None])
        score = (score - job_counts0.astype(jnp.float32)[None, :] * penalty
                 + noise[None, :])
        masked0 = jnp.where(ok0, score, -jnp.inf)        # [T, n_loc]
        nf0_loc = jnp.sum(ok0, axis=1).astype(jnp.int32)  # [T]

        # ---- local top-K candidates per key -> gathered packets.
        kc = min(k_cand, n_loc)
        _, loc_idx = jax.lax.top_k(masked0, kc)          # [T, kc]
        cand = loc_idx.reshape(-1)                       # [T*kc]
        pkt = jnp.concatenate([
            (cand + row_base)[:, None].astype(jnp.float32),
            capacity[cand],
            score_cap[cand],
            usage[cand],
            job_counts0[cand][:, None].astype(jnp.float32),
            banned0[cand][:, None].astype(jnp.float32),
            noise[cand][:, None],
            tg_masks[:, cand].T.astype(jnp.float32),     # [T*kc, T]
        ], axis=1)
        if axis is not None:
            pkt_all, nf_all = jax.lax.all_gather((pkt, nf0_loc), axis)
            pkt_all = pkt_all.reshape(n_shards * n_keys * kc, -1)
            nf0 = jnp.sum(nf_all, axis=0)                # [T]
        else:
            pkt_all = pkt
            nf0 = nf0_loc
        n_cand = pkt_all.shape[0]

        # Ascending global-row order makes every later argmax break ties
        # toward the lowest row — the monolithic kernel's behavior.
        rows_g = pkt_all[:, 0].astype(jnp.int32)
        order = jnp.argsort(rows_g)
        pkt_s = pkt_all[order]
        rows_s = pkt_s[:, 0].astype(jnp.int32)
        keep = jnp.concatenate(
            [jnp.ones((1,), bool), rows_s[1:] != rows_s[:-1]])

        c_cap = pkt_s[:, 1:1 + r_dims]
        c_sc = pkt_s[:, 1 + r_dims:3 + r_dims]
        c_use0 = pkt_s[:, 3 + r_dims:3 + 2 * r_dims]
        c_cnt0 = pkt_s[:, 3 + 2 * r_dims].astype(jnp.int32)
        c_ban0 = pkt_s[:, 4 + 2 * r_dims] > 0.5
        c_noise = pkt_s[:, 5 + 2 * r_dims]
        c_elig = pkt_s[:, 6 + 2 * r_dims:] > 0.5         # [C, T]

        # Window-start ok/score per candidate per key — the n_feasible
        # delta baseline, and the ranking for the global trim. Rows
        # outside the candidate set cannot change feasibility within a
        # window, so deltas over candidates are exact. ok0c_raw is
        # keep-independent: every copy of a row carries identical data,
        # so after compaction re-picks which copy survives, the raw
        # values stay valid for whichever copy that is.
        fits0c = jnp.all(c_cap[:, None, :] - c_use0[:, None, :]
                         >= key_demands[None, :, :], axis=-1)  # [C, T]
        ok0c_raw = fits0c & c_elig & ~(distinct & c_ban0)[:, None]
        util2c = c_use0[:, None, :2] + key_demands[None, :, :2]
        sc0c = _score(util2c, c_sc[:, None, :])
        sc0c = (sc0c - c_cnt0.astype(jnp.float32)[:, None] * penalty
                + c_noise[:, None])
        # Duplicate copies score -inf here so one row cannot occupy two
        # trim slots of the same key.
        masked0c = jnp.where(ok0c_raw & keep[:, None], sc0c, -jnp.inf)

        # Global trim + COMPACT: keep only each key's global top-K
        # candidates and shrink the arrays to that static size, so the
        # replay cost is independent of the device count. Winners
        # provably rank <= K for their key, so the trim is lossless.
        k_trim = min(k_cand, n_cand)
        if n_keys * k_trim < n_cand:
            _, tidx = jax.lax.top_k(masked0c.T, k_trim)  # [T, k_trim]
            sel = tidx.reshape(-1)                       # [T*k_trim]
            # Re-sort the compacted set by global row (argmax tie parity)
            # and rebuild the dedup mask FROM SCRATCH: a key short of
            # feasible candidates pads its trim slots with -inf entries
            # that can be a row's keep=False duplicate, and if that copy
            # sorts first, carrying the old keep forward would AND it
            # with first-occurrence and drop the row entirely. Copies are
            # identical, so first-occurrence alone is the right mask.
            sel = sel[jnp.argsort(rows_s[sel])]
            rows_s = rows_s[sel]
            keep = jnp.concatenate(
                [jnp.ones((1,), bool), rows_s[1:] != rows_s[:-1]])
            c_cap = c_cap[sel]
            c_sc = c_sc[sel]
            c_use0 = c_use0[sel]
            c_cnt0 = c_cnt0[sel]
            c_ban0 = c_ban0[sel]
            c_noise = c_noise[sel]
            c_elig = c_elig[sel]
            ok0c_raw = ok0c_raw[sel]
        ok0c = ok0c_raw & keep[:, None]

        # Per-placement demand, zeroed for padding steps exactly like the
        # monolithic kernels' zero-padded demand rows.
        kd_p = key_demands[tg_ids] * valid[:, None].astype(jnp.float32)

        def replay(carry, xs):
            c_use, c_cnt, c_ban = carry
            t_j, v_j, r_j, d_j = xs
            c_cnt = jnp.where(r_j, c_cnt0, c_cnt)
            c_ban = jnp.where(r_j, c_ban0, c_ban)
            elig_j = jax.lax.dynamic_index_in_dim(
                c_elig, t_j, axis=1, keepdims=False)
            fits_c = jnp.all(c_cap - c_use >= d_j[None, :], axis=1)
            ok_c = fits_c & elig_j & ~(distinct & c_ban) & keep
            sc = _score(c_use[:, :2] + d_j[None, :2], c_sc)
            sc = sc - c_cnt.astype(jnp.float32) * penalty + c_noise
            m = jnp.where(ok_c, sc, -jnp.inf)
            i = jnp.argmax(m)
            found = ok_c[i] & v_j
            one = found.astype(c_use.dtype)
            c_use = c_use.at[i].add(d_j * one)
            c_cnt = c_cnt.at[i].add(found.astype(jnp.int32))
            c_ban = c_ban.at[i].set(c_ban[i] | found)
            ok0_j = jax.lax.dynamic_index_in_dim(
                ok0c, t_j, axis=1, keepdims=False)
            nf0_j = jax.lax.dynamic_index_in_dim(
                nf0, t_j, keepdims=False)
            nf = nf0_j + jnp.sum(ok_c) - jnp.sum(ok0_j)
            out = jnp.stack([
                jnp.where(found, rows_s[i], -1).astype(jnp.float32),
                jnp.where(found, m[i], -jnp.inf),
                nf.astype(jnp.float32),
            ])
            return (c_use, c_cnt, c_ban), out

        (c_use_f, _, _), packed = jax.lax.scan(
            replay, (c_use0, c_cnt0, c_ban0),
            (tg_ids, valid, reset, kd_p))                # [P, 3]

        # Publish the replay's FINAL candidate usage into the owning
        # shard's rows by scatter-SET: c_use_f accumulated each row's won
        # demands sequentially in placement order, bit-identical to the
        # monolithic scan's in-register adds — a scatter-ADD of per-
        # placement demands would apply duplicate indices in XLA-defined
        # order and could drift by an ulp when one row wins repeatedly.
        # Untouched candidate rows set their unchanged value (a no-op),
        # and kept rows are unique so the set order is immaterial.
        lr = rows_s - row_base
        mine = keep & (lr >= 0) & (lr < n_loc)
        # Foreign/duplicate entries get an out-of-range index and drop —
        # a clipped index could collide with a real winner row and race
        # its write with a stale gathered value.
        usage = usage.at[jnp.where(mine, lr, n_loc)].set(
            c_use_f, mode="drop")

        if axis is not None:
            # Every device computed the identical replay; one psum makes
            # that replication visible to the type system (and is the
            # only other collective — per WINDOW, not per placement).
            packed = jax.lax.psum(
                jnp.where(my == 0, packed, 0.0), axis)
        return packed, usage

    if mesh is None:
        return jax.jit(local_fn)

    import jax.sharding as jsh

    node = jsh.PartitionSpec(axis)
    mask2 = jsh.PartitionSpec(None, axis)
    rep = jsh.PartitionSpec()
    smapped = jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(node, node, node, mask2, node, rep, rep, rep, node,
                  rep, rep, node, rep),
        out_specs=(rep, node))
    return jax.jit(smapped)


def keyed_cand_count(n_valid: int) -> int:
    """Candidate budget for a window with n_valid real placements, padded
    to a power of two so jit compiles one program per bucket."""
    k = 8
    while k < n_valid:
        k *= 2
    return k


def place_batch_keyed(mesh, capacity, score_cap, usage, tg_masks,
                      job_counts0, key_demands, tg_ids, valid, noise,
                      penalty, distinct_hosts, banned0, reset,
                      n_valid: int) -> PlacementResult:
    """place_batch / place_batch_multi semantics via the keyed candidate
    kernel. key_demands is [T, R] with demands[p] == key_demands[tg_ids[p]]
    for every valid placement (stack.prepare's tg_demands). n_valid is the
    window's real placement count (host-known), which bounds the candidate
    sets. mesh=None runs single-device."""
    fn = _keyed_program(mesh, keyed_cand_count(n_valid))
    packed, usage = fn(capacity, score_cap, usage, tg_masks, job_counts0,
                      key_demands, tg_ids, valid, noise, penalty,
                      distinct_hosts, banned0, reset)
    return PlacementResult(packed, usage)


# Note: the system scheduler's per-node sweep and the plan applier's
# re-verification run host-side (numpy / structs.allocs_fit) — they are
# O(nodes-in-one-plan), tiny next to the placement scan, and need exact
# port-level network checks that don't tensorize. Only place_batch is hot.
