"""XLA placement kernels: the scheduling hot path as tensor programs.

This replaces the reference's per-node iterator chain (reference:
scheduler/stack.go Select -> select.go MaxScoreIterator -> rank.go
BinPackIterator -> feasible.go checkers) with batched device programs:

  place_batch   lax.scan over the placements of one evaluation; each step is
                a fused feasibility-mask + BestFit-v3 score + argmax over the
                whole node axis, with in-register usage/anti-affinity updates
                so placement k+1 sees placement k's proposed allocation
                (reference semantics: scheduler/context.go:109-140).

Scoring matches reference funcs.go:102-137 (including its Inf/NaN division
edges) with the job anti-affinity penalty applied after clamping (reference:
rank.go:242-304). Selection is a global argmax rather than the reference's
max-over-log2(n)-random-candidates (reference: stack.go:120-133), which can
only improve placement quality; host-supplied per-node noise reproduces the
load-spreading effect of the reference's node shuffle on ties.

All shapes are static per (N_pad, P_pad) bucket: the node axis is padded to a
power of two by NodeTensor and the placement axis by the stack, so jit caches
stay warm. The node axis is the sharding axis for multi-chip meshes
(nomad_tpu/parallel/).
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


def _shard_map(*args, **kwargs):
    """jax.shard_map moved out of jax.experimental across the jax versions
    this repo must serve on (TPU images run newer jax than the pinned CPU
    toolchain); resolve whichever spelling exists at first use."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:  # jax <= 0.4.x
        from jax.experimental.shard_map import shard_map as fn
    return fn(*args, **kwargs)


_LOG2_10 = float(np.log2(10.0))


class PlacementResult(NamedTuple):
    packed: jax.Array       # [P, 3] f32: (chosen row or -1, score, n_feasible)
    usage_after: jax.Array  # [N, R] usage including the new placements

    # The packed layout exists because a device->host readback has a fixed
    # RTT cost on remote-attached TPUs: one transfer per eval, not three.
    @property
    def chosen(self):
        return self.packed[:, 0].astype(jnp.int32)

    @property
    def scores(self):
        return self.packed[:, 1]

    @property
    def n_feasible(self):
        return self.packed[:, 2].astype(jnp.int32)


def _score(usage2: jax.Array, score_cap: jax.Array) -> jax.Array:
    """BestFit-v3: 20 - 10^freeCpuPct - 10^freeMemPct, clamped to [0, 18].

    usage2 [..., 2] is proposed (cpu, mem) utilization including reserved;
    score_cap [..., 2] is capacity minus reserved (broadcastable). Division
    by zero follows IEEE (Inf/NaN) exactly like the Go reference; NaN
    sanitizes to 0. THE one definition of the formula — the monolithic
    scan, the keyed kernel's three passes, and the host mirror must all
    agree bit-for-bit.
    """
    free_pct = 1.0 - usage2 / score_cap
    # 10^x on the MXU-friendly path: exp2(x * log2 10).
    total = (jnp.exp2(free_pct[..., 0] * _LOG2_10)
             + jnp.exp2(free_pct[..., 1] * _LOG2_10))
    score = jnp.clip(20.0 - total, 0.0, 18.0)
    return jnp.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)


def _make_step(capacity, score_cap, tg_masks, noise, penalty,
               distinct_hosts, job_counts0=None, banned0=None):
    """The ONE definition of the per-placement scan step (fused
    feasibility mask + BestFit-v3 score + argmax + in-register state
    updates). place_batch uses the plain (demand, tg_id, valid) input
    tuple; place_batch_multi adds a reset flag that reloads the per-JOB
    state (anti-affinity counts, distinct-hosts bans) at eval boundaries.
    Sharing the body keeps single/multi/chained parity by construction."""

    def step(carry, inputs):
        usage, job_counts, banned = carry
        if len(inputs) == 4:
            demand, tg_id, is_valid, is_reset = inputs
            job_counts = jnp.where(is_reset, job_counts0, job_counts)
            banned = jnp.where(is_reset, banned0, banned)
        else:
            demand, tg_id, is_valid = inputs
        eligible = tg_masks[tg_id]

        fits = jnp.all(capacity - usage >= demand[None, :], axis=1)
        ok = fits & eligible & ~(distinct_hosts & banned)

        util2 = usage[:, :2] + demand[None, :2]
        score = _score(util2, score_cap)
        score = score - job_counts.astype(jnp.float32) * penalty + noise
        masked = jnp.where(ok, score, -jnp.inf)

        idx = jnp.argmax(masked)
        found = ok[idx] & is_valid

        one = found.astype(usage.dtype)
        usage = usage.at[idx].add(demand * one)
        job_counts = job_counts.at[idx].add(found.astype(job_counts.dtype))
        banned = banned.at[idx].set(banned[idx] | found)

        out = jnp.stack([
            jnp.where(found, idx, -1).astype(jnp.float32),
            jnp.where(found, masked[idx], -jnp.inf),
            jnp.sum(ok).astype(jnp.float32),
        ])
        return (usage, job_counts, banned), out

    return step


@functools.partial(jax.jit, donate_argnums=())
def place_batch(
    capacity: jax.Array,    # [N, R] total resources (fit bound)
    score_cap: jax.Array,   # [N, 2] cpu/mem minus reserved (score denominator)
    usage: jax.Array,       # [N, R] reserved + committed allocs (+/- plan deltas)
    tg_masks: jax.Array,    # [T, N] bool per task group: ready & dc & class & escaped
    job_counts: jax.Array,  # [N] int32 proposed allocs of this job per node
    demands: jax.Array,     # [P, R] per-placement resource ask
    tg_ids: jax.Array,      # [P] int32 task-group index into tg_masks
    valid: jax.Array,       # [P] bool: real placement vs padding
    noise: jax.Array,       # [N] f32 tie-break jitter in [0, 1e-3)
    penalty: jax.Array,     # f32 job anti-affinity penalty (10 service / 5 batch)
    distinct_hosts: jax.Array,  # bool: job has a distinct_hosts constraint
    banned0: jax.Array,     # [N] bool: nodes already holding this job's allocs
) -> PlacementResult:
    step = _make_step(capacity, score_cap, tg_masks, noise, penalty,
                      distinct_hosts)
    (usage, _, _), packed = jax.lax.scan(
        step, (usage, job_counts, banned0), (demands, tg_ids, valid))
    return PlacementResult(packed, usage)


@functools.partial(jax.jit, donate_argnums=())
def place_batch_multi(
    capacity: jax.Array,    # [N, R]
    score_cap: jax.Array,   # [N, 2]
    usage: jax.Array,       # [N, R] chain input (window-sequential)
    tg_masks: jax.Array,    # [T, N] shared across the window's evals
    job_counts0: jax.Array,  # [N] per-eval anti-affinity base (shared)
    demands: jax.Array,     # [E*P, R] all evals' placements, concatenated
    tg_ids: jax.Array,      # [E*P]
    valid: jax.Array,       # [E*P]
    noise: jax.Array,       # [N]
    penalty: jax.Array,     # f32
    distinct_hosts: jax.Array,  # bool (shared job shape)
    banned0: jax.Array,     # [N] per-eval distinct-hosts base (shared)
    reset: jax.Array,       # [E*P] bool: True at each eval's first step
) -> PlacementResult:
    """One scan over a WHOLE WINDOW of same-shaped evaluations.

    A registration storm's window is N near-identical evals whose prepared
    inputs dedupe to one PreparedBatch; dispatching place_batch per eval
    pays a host->device launch per eval plus an eager jnp.stack over the
    window at drain (both scale with window size and dominate on a
    remote-attached TPU). This kernel concatenates the placements and
    resets the per-JOB state (anti-affinity counts, distinct-hosts bans)
    at each eval boundary, so the whole window is ONE dispatch and ONE
    readback while usage chains exactly as the per-eval kernels did
    (reference sequencing semantics: scheduler/context.go:109-140 within
    an eval; optimistic worker chaining across evals)."""
    step = _make_step(capacity, score_cap, tg_masks, noise, penalty,
                      distinct_hosts, job_counts0=job_counts0,
                      banned0=banned0)
    (usage, _, _), packed = jax.lax.scan(
        step, (usage, job_counts0, banned0),
        (demands, tg_ids, valid, reset))
    return PlacementResult(packed, usage)


class CompactResult(NamedTuple):
    """Host-side per-eval view of a compacted window result: exactly the
    arrays the plan build consumes, in the dtypes it consumes them
    (packed's f32 triple forces a cast + tolist per column per eval on
    the host otherwise)."""

    chosen: np.ndarray   # [P_pad] int32 chosen row per placement (-1 = none)
    scores: np.ndarray   # [P_pad] f32 winning score per placement
    nf_last: int         # n_feasible of the eval's LAST valid placement
    ok: bool             # every valid placement found a row


@jax.jit
def compact_window(packed3, valid, last_idx):
    """On-device reduction of a window's packed kernel outputs to the
    minimal arrays the host build actually needs, BEFORE the device->host
    copy: chosen rows as int32, winner scores, the per-eval n_feasible of
    the final valid placement (the only one metrics keep — earlier fills
    are overwritten before anything snapshots them), and a per-eval
    success mask so the host can branch straight into the vectorized
    all-placed build without scanning. Cuts the transfer by ~1/3 against
    the raw [*, 3] f32 layout and moves every cast off the host.

    packed3 [E, P, 3]; valid [E, P] bool; last_idx [E] int32 (index of
    each eval's last valid placement). Returns (chosen [E, P] int32,
    scores [E, P] f32, nf_last [E] int32, ok [E] bool)."""
    chosen = packed3[..., 0].astype(jnp.int32)
    scores = packed3[..., 1]
    nf_last = jnp.take_along_axis(
        packed3[..., 2], last_idx[:, None].astype(jnp.int32), axis=1
    )[:, 0].astype(jnp.int32)
    ok = jnp.all((chosen >= 0) | ~valid, axis=1)
    return chosen, scores, nf_last, ok


def compact_host(packed: np.ndarray, n_valid: int) -> CompactResult:
    """Numpy mirror of compact_window for one already-host-side result
    (host-placed evals and non-jax test arrays skip the device entirely)."""
    packed = np.asarray(packed)
    chosen = packed[:, 0].astype(np.int32)
    return CompactResult(
        chosen=chosen,
        scores=packed[:, 1].astype(np.float32, copy=False),
        nf_last=int(packed[n_valid - 1, 2]),
        ok=bool((chosen[:n_valid] >= 0).all()))


_LOG2_10_F32 = np.float32(_LOG2_10)


def place_batch_host(capacity, score_cap, usage, tg_masks, job_counts,
                     demands, tg_ids, valid, noise, penalty,
                     distinct_hosts, banned0) -> PlacementResult:
    """Numpy mirror of place_batch for SHALLOW windows.

    On a remote-attached TPU every host sync costs a fixed ~100ms round
    trip (and the first device->host transfer pins the whole process into
    that mode), so a lone eval's 50 placements are orders of magnitude
    faster as host vector ops than as a device dispatch + readback. The
    pipelined worker routes small idle-broker windows here and storms to
    the device chain; semantics are identical — same f32 BestFit-v3
    formula with its Inf/NaN edges (reference funcs.go:102-137), same
    anti-affinity penalty and noise tie-break, same in-loop usage updates
    so placement k+1 sees placement k (reference context semantics,
    scheduler/context.go:109-140). tests/test_tensor_and_kernels.py
    asserts parity against the device kernel."""
    capacity = np.asarray(capacity, np.float32)
    score_cap = np.asarray(score_cap, np.float32)
    usage = np.array(usage, np.float32, copy=True)
    job_counts = np.array(job_counts, np.int32, copy=True)
    banned = np.array(banned0, bool, copy=True)
    demands = np.asarray(demands, np.float32)
    tg_ids = np.asarray(tg_ids, np.int32)
    valid = np.asarray(valid, bool)
    noise = np.asarray(noise, np.float32)
    penalty = np.float32(penalty)
    distinct_hosts = bool(distinct_hosts)
    tg_masks = np.asarray(tg_masks, bool)

    p = len(tg_ids)
    packed = np.empty((p, 3), np.float32)
    neg_inf = np.float32(-np.inf)

    def full_scores(demand):
        """Whole-table masked-score pass — the same f32 formula as the
        device kernel's step."""
        util2 = usage[:, :2] + demand[:2]
        free_pct = np.float32(1.0) - util2 / score_cap
        total = (np.exp2(free_pct[:, 0] * _LOG2_10_F32)
                 + np.exp2(free_pct[:, 1] * _LOG2_10_F32))
        score = np.clip(np.float32(20.0) - total,
                        np.float32(0.0), np.float32(18.0))
        score = np.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)
        return score - job_counts.astype(np.float32) * penalty + noise

    def row_score(idx, demand):
        """One row of full_scores, recomputed after the row's usage or
        count changed — bit-identical to the full pass for that row."""
        util2 = usage[idx, :2] + demand[:2]
        free_pct = np.float32(1.0) - util2 / score_cap[idx]
        total = (np.exp2(free_pct[0] * _LOG2_10_F32)
                 + np.exp2(free_pct[1] * _LOG2_10_F32))
        score = np.float32(np.clip(np.float32(20.0) - total,
                                   np.float32(0.0), np.float32(18.0)))
        score = np.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)
        return (score - np.float32(job_counts[idx]) * penalty
                + noise[idx])

    with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
        # A storm places many copies of the same task group: between two
        # placements of one (tg, demand) key only ONE node row changes, so
        # the masked-score vector is computed once per key and patched at
        # the placed row afterwards — O(rows) once + O(keys) per step
        # instead of O(rows) per step. Exactly the same f32 values as the
        # naive loop (each row's score is a pure function of that row).
        cache: dict = {}  # key -> [masked, ok, n_feasible, demand, tg]
        for k in range(p):
            demand = demands[k]
            tg = int(tg_ids[k])
            key = (tg, demand.tobytes())
            ent = cache.get(key)
            if ent is None:
                eligible = tg_masks[tg]
                fits = np.all(capacity - usage >= demand[None, :], axis=1)
                ok = fits & eligible
                if distinct_hosts:
                    ok &= ~banned
                masked = np.where(ok, full_scores(demand), neg_inf)
                ent = cache[key] = [masked, ok,
                                    np.float32(np.count_nonzero(ok)),
                                    demand, tg]
            masked, ok, n_feasible = ent[0], ent[1], ent[2]
            idx = int(np.argmax(masked))
            found = bool(ok[idx]) and bool(valid[k])
            packed[k, 0] = np.float32(idx) if found else np.float32(-1)
            packed[k, 1] = masked[idx] if found else neg_inf
            packed[k, 2] = n_feasible
            if found:
                usage[idx] += demand
                job_counts[idx] += 1
                banned[idx] = True
                # Patch the changed row into every cached key: a row's
                # score/feasibility is a pure function of that row, so the
                # patched vectors stay identical to a full recompute.
                cap_row = capacity[idx]
                usage_row = usage[idx]
                for cent in cache.values():
                    cmask, cok, cn, cdemand, ctg = cent
                    old_ok = bool(cok[idx])
                    new_ok = (bool(np.all(cap_row - usage_row >= cdemand))
                              and bool(tg_masks[ctg, idx]))
                    if distinct_hosts:
                        new_ok = new_ok and not banned[idx]
                    cok[idx] = new_ok
                    cmask[idx] = (row_score(idx, cdemand) if new_ok
                                  else neg_inf)
                    if new_ok != old_ok:
                        cent[2] = np.float32(
                            cn + (1.0 if new_ok else -1.0))
    # Same result type as the device kernel; both arrays are
    # host-side numpy here — the pipelined drain dispatches on
    # isinstance(packed, np.ndarray) and skips the readback.
    return PlacementResult(packed, usage)


# ----------------------------------------------------- keyed candidates
# Candidate-set placement: the storm kernel for meshes AND single chips.
#
# Every PreparedBatch satisfies demands[p] == tg_demands[tg_ids[p]]
# (stack.prepare), so a window of P placements draws from at most T
# distinct (task-group, demand) KEYS — and the monolithic scan's full
# score pass per placement is P/T-fold redundant. This kernel
# restructures the whole window around candidate sets:
#
#   1. ONE vectorized score pass per KEY over the node rows at window
#      start (masked BestFit-v3, [T, N]) — then top-K candidate rows per
#      key (lax.top_k; ties break to the lowest index, same as argmax),
#      where K = the window's valid placement count.
#   2. Candidates sort by row id (argmax tie parity), dedup, and trim to
#      the top-K per key, bounding the replay size.
#   3. The exact P-step sequential chain — resets, bans, anti-affinity,
#      the same f32 score ops — replays over the candidate table only.
#
# Exactness: at step j, every modified row is a prior winner (in the
# candidate set by induction, and within its key's top-K by this same
# argument). The winner is either such a row, or the best UNMODIFIED
# row — every row ranked above it at window start for its key is
# modified (else it would win now), so its window-start rank is
# <= j <= K and it survives the top-K selection and the trim.
# Feasibility is monotonic within a window (usage only grows, bans only
# appear mid-eval, eligibility is static) and eval-boundary resets
# restore unmodified rows to exactly their window-start scores, so the
# window-start ranking remains valid across resets. The replay
# recomputes scores from shipped row data with the exact same f32 ops as
# the monolithic step, so results are bit-identical for valid
# placements (tests assert this against place_batch/place_batch_multi).
# For padding placements (valid=False) chosen=-1 and score=-inf as
# always, but the n_feasible column is unspecified (the monolithic
# kernels compute it with the padding's zeroed demand; no consumer reads
# it).
#
# On a MESH the window runs as an explicitly shard-local pipeline
# (`_mesh_keyed_program` below): each shard scores and top-Ks only its
# own rows, one small winner-row exchange crosses the interconnect, and
# the merge+replay runs once on the lead device — ZERO collectives in
# any compiled program. The single-device variant here is the parity
# oracle the mesh pipeline is gated against bit-for-bit.


@functools.lru_cache(maxsize=64)
def _keyed_program(mesh, k_cand: int):
    """Build the jitted single-device keyed-candidate program (mesh is
    accepted for cache-key compatibility but must be None; mesh execution
    goes through `_mesh_keyed_program`)."""
    assert mesh is None, "mesh windows run the shard-local pipeline"

    def local_fn(capacity, score_cap, usage, tg_masks, job_counts0,
                 key_demands, tg_ids, valid, noise, penalty, distinct,
                 banned0, reset):
        n_loc, r_dims = capacity.shape
        n_keys = key_demands.shape[0]
        row_base = jnp.int32(0)

        # ---- window-start score pass: one per key over local rows.
        fits0 = jnp.all(capacity[None] - usage[None]
                        >= key_demands[:, None, :], axis=-1)
        ok0 = fits0 & tg_masks & ~(distinct & banned0)[None, :]
        util2 = usage[None, :, :2] + key_demands[:, None, :2]
        score = _score(util2, score_cap[None])
        score = (score - job_counts0.astype(jnp.float32)[None, :] * penalty
                 + noise[None, :])
        masked0 = jnp.where(ok0, score, -jnp.inf)        # [T, n_loc]
        nf0_loc = jnp.sum(ok0, axis=1).astype(jnp.int32)  # [T]

        # ---- local top-K candidates per key -> gathered packets.
        kc = min(k_cand, n_loc)
        _, loc_idx = jax.lax.top_k(masked0, kc)          # [T, kc]
        cand = loc_idx.reshape(-1)                       # [T*kc]
        pkt = jnp.concatenate([
            (cand + row_base)[:, None].astype(jnp.float32),
            capacity[cand],
            score_cap[cand],
            usage[cand],
            job_counts0[cand][:, None].astype(jnp.float32),
            banned0[cand][:, None].astype(jnp.float32),
            noise[cand][:, None],
            tg_masks[:, cand].T.astype(jnp.float32),     # [T*kc, T]
        ], axis=1)
        pkt_all = pkt
        nf0 = nf0_loc
        n_cand = pkt_all.shape[0]

        # Ascending global-row order makes every later argmax break ties
        # toward the lowest row — the monolithic kernel's behavior.
        rows_g = pkt_all[:, 0].astype(jnp.int32)
        order = jnp.argsort(rows_g)
        pkt_s = pkt_all[order]
        rows_s = pkt_s[:, 0].astype(jnp.int32)
        keep = jnp.concatenate(
            [jnp.ones((1,), bool), rows_s[1:] != rows_s[:-1]])

        c_cap = pkt_s[:, 1:1 + r_dims]
        c_sc = pkt_s[:, 1 + r_dims:3 + r_dims]
        c_use0 = pkt_s[:, 3 + r_dims:3 + 2 * r_dims]
        c_cnt0 = pkt_s[:, 3 + 2 * r_dims].astype(jnp.int32)
        c_ban0 = pkt_s[:, 4 + 2 * r_dims] > 0.5
        c_noise = pkt_s[:, 5 + 2 * r_dims]
        c_elig = pkt_s[:, 6 + 2 * r_dims:] > 0.5         # [C, T]

        # Window-start ok/score per candidate per key — the n_feasible
        # delta baseline, and the ranking for the global trim. Rows
        # outside the candidate set cannot change feasibility within a
        # window, so deltas over candidates are exact. ok0c_raw is
        # keep-independent: every copy of a row carries identical data,
        # so after compaction re-picks which copy survives, the raw
        # values stay valid for whichever copy that is.
        fits0c = jnp.all(c_cap[:, None, :] - c_use0[:, None, :]
                         >= key_demands[None, :, :], axis=-1)  # [C, T]
        ok0c_raw = fits0c & c_elig & ~(distinct & c_ban0)[:, None]
        util2c = c_use0[:, None, :2] + key_demands[None, :, :2]
        sc0c = _score(util2c, c_sc[:, None, :])
        sc0c = (sc0c - c_cnt0.astype(jnp.float32)[:, None] * penalty
                + c_noise[:, None])
        # Duplicate copies score -inf here so one row cannot occupy two
        # trim slots of the same key.
        masked0c = jnp.where(ok0c_raw & keep[:, None], sc0c, -jnp.inf)

        # Global trim + COMPACT: keep only each key's global top-K
        # candidates and shrink the arrays to that static size, so the
        # replay cost is independent of the device count. Winners
        # provably rank <= K for their key, so the trim is lossless.
        k_trim = min(k_cand, n_cand)
        if n_keys * k_trim < n_cand:
            _, tidx = jax.lax.top_k(masked0c.T, k_trim)  # [T, k_trim]
            sel = tidx.reshape(-1)                       # [T*k_trim]
            # Re-sort the compacted set by global row (argmax tie parity)
            # and rebuild the dedup mask FROM SCRATCH: a key short of
            # feasible candidates pads its trim slots with -inf entries
            # that can be a row's keep=False duplicate, and if that copy
            # sorts first, carrying the old keep forward would AND it
            # with first-occurrence and drop the row entirely. Copies are
            # identical, so first-occurrence alone is the right mask.
            sel = sel[jnp.argsort(rows_s[sel])]
            rows_s = rows_s[sel]
            keep = jnp.concatenate(
                [jnp.ones((1,), bool), rows_s[1:] != rows_s[:-1]])
            c_cap = c_cap[sel]
            c_sc = c_sc[sel]
            c_use0 = c_use0[sel]
            c_cnt0 = c_cnt0[sel]
            c_ban0 = c_ban0[sel]
            c_noise = c_noise[sel]
            c_elig = c_elig[sel]
            ok0c_raw = ok0c_raw[sel]
        ok0c = ok0c_raw & keep[:, None]

        # Per-placement demand, zeroed for padding steps exactly like the
        # monolithic kernels' zero-padded demand rows.
        kd_p = key_demands[tg_ids] * valid[:, None].astype(jnp.float32)

        def replay(carry, xs):
            c_use, c_cnt, c_ban = carry
            t_j, v_j, r_j, d_j = xs
            c_cnt = jnp.where(r_j, c_cnt0, c_cnt)
            c_ban = jnp.where(r_j, c_ban0, c_ban)
            elig_j = jax.lax.dynamic_index_in_dim(
                c_elig, t_j, axis=1, keepdims=False)
            fits_c = jnp.all(c_cap - c_use >= d_j[None, :], axis=1)
            ok_c = fits_c & elig_j & ~(distinct & c_ban) & keep
            sc = _score(c_use[:, :2] + d_j[None, :2], c_sc)
            sc = sc - c_cnt.astype(jnp.float32) * penalty + c_noise
            m = jnp.where(ok_c, sc, -jnp.inf)
            i = jnp.argmax(m)
            found = ok_c[i] & v_j
            one = found.astype(c_use.dtype)
            c_use = c_use.at[i].add(d_j * one)
            c_cnt = c_cnt.at[i].add(found.astype(jnp.int32))
            c_ban = c_ban.at[i].set(c_ban[i] | found)
            ok0_j = jax.lax.dynamic_index_in_dim(
                ok0c, t_j, axis=1, keepdims=False)
            nf0_j = jax.lax.dynamic_index_in_dim(
                nf0, t_j, keepdims=False)
            nf = nf0_j + jnp.sum(ok_c) - jnp.sum(ok0_j)
            out = jnp.stack([
                jnp.where(found, rows_s[i], -1).astype(jnp.float32),
                jnp.where(found, m[i], -jnp.inf),
                nf.astype(jnp.float32),
            ])
            return (c_use, c_cnt, c_ban), out

        (c_use_f, _, _), packed = jax.lax.scan(
            replay, (c_use0, c_cnt0, c_ban0),
            (tg_ids, valid, reset, kd_p))                # [P, 3]

        # Publish the replay's FINAL candidate usage into the owning
        # shard's rows by scatter-SET: c_use_f accumulated each row's won
        # demands sequentially in placement order, bit-identical to the
        # monolithic scan's in-register adds — a scatter-ADD of per-
        # placement demands would apply duplicate indices in XLA-defined
        # order and could drift by an ulp when one row wins repeatedly.
        # Untouched candidate rows set their unchanged value (a no-op),
        # and kept rows are unique so the set order is immaterial.
        lr = rows_s - row_base
        mine = keep & (lr >= 0) & (lr < n_loc)
        # Duplicate entries get an out-of-range index and drop — a
        # clipped index could collide with a real winner row and race
        # its write with a stale gathered value.
        usage = usage.at[jnp.where(mine, lr, n_loc)].set(
            c_use_f, mode="drop")
        return packed, usage

    return jax.jit(local_fn)


def keyed_cand_count(n_valid: int) -> int:
    """Candidate budget for a window with n_valid real placements, padded
    to a power of two so jit compiles one program per bucket."""
    k = 8
    while k < n_valid:
        k *= 2
    return k


def place_batch_keyed(mesh, capacity, score_cap, usage, tg_masks,
                      job_counts0, key_demands, tg_ids, valid, noise,
                      penalty, distinct_hosts, banned0, reset,
                      n_valid: int) -> PlacementResult:
    """place_batch / place_batch_multi semantics via the keyed candidate
    kernel. key_demands is [T, R] with demands[p] == key_demands[tg_ids[p]]
    for every valid placement (stack.prepare's tg_demands). n_valid is the
    window's real placement count (host-known), which bounds the candidate
    sets. mesh=None runs single-device; a multi-device mesh runs the
    shard-local pipeline (`usage` may be the previous window's MeshChain
    to keep the usage chain shard-resident)."""
    if mesh is not None and int(mesh.devices.size) > 1:
        return _place_batch_keyed_mesh(
            mesh, capacity, score_cap, usage, tg_masks, job_counts0,
            key_demands, tg_ids, valid, noise, penalty, distinct_hosts,
            banned0, reset, n_valid)
    if isinstance(usage, MeshChain):
        usage = usage.materialize()
    fn = _keyed_program(None, keyed_cand_count(n_valid))
    packed, usage = fn(capacity, score_cap, usage, tg_masks, job_counts0,
                      key_demands, tg_ids, valid, noise, penalty,
                      distinct_hosts, banned0, reset)
    return PlacementResult(packed, usage)


# ------------------------------------------------ shard-local mesh pipeline
# The mesh window is an explicitly shard-local pipeline rather than one
# SPMD program (ROADMAP item 2: "per-shard local argmax + top-k merge
# instead of full-axis gathers, usage chain kept shard-local with only
# winner rows exchanged"):
#
#   COLD window (chain start / rebuild):
#     stage A (jax.shard_map, NO collectives): each shard applies the
#       pending winner-row ring to its own usage rows, runs ONE full
#       masked BestFit-v3 score pass over only its own rows, and takes
#       its LOCAL top-B candidate rows per key (B = 2k) with the B-th
#       score as its threshold tau_s.
#     exchange: the per-shard candidate packets hop to the lead device
#       as ONE small transfer ([devices, T*B + 2, W] rows — scores via
#       raw row data, global row ids, usage snapshots, per-key nf0/tau
#       tails — independent of the node count). No compiled program
#       contains a collective, so there is no per-window rendezvous
#       barrier: on ICI the packets ride point-to-point DMAs, on the
#       CPU mesh plain buffer copies.
#     pool build (lead device): merge-sort the candidates by ascending
#       global row (argmax tie parity), first-occurrence dedup, and
#       keep the WHOLE merged set as a resident candidate POOL with
#       tau = max_s tau_s and nf0 = sum_s nf0_s per key.
#
#   WARM window (every storm window after the first): runs ENTIRELY on
#     the lead device against the resident pool — zero shard dispatches,
#     zero cross-device transfers. One jitted step rescopes the pool
#     (window-start ok/score over O(devices * T * k) rows), checks the
#     exactness certificate, selects the top-k candidate set per key,
#     replays the exact P-step sequential chain (resets, bans,
#     anti-affinity, the same f32 score ops), scatters the winners'
#     final usage back into the pool, and appends the winner-row delta
#     (O(P) rows: global row + final usage vector) to a pending RING.
#
#   The sharded usage tail is only touched when it must be: the ring
#   applies to the owning shards inside the NEXT cold window's stage A
#   (or on materialize, for rebase paths and tests) as one scatter —
#   last-write-wins per row, deterministic. Winners only ever come from
#   the pool, so the pool's usage view and the sharded tail + ring are
#   always consistent.
#
# Exactness: winners only modify pool rows, so any row OUTSIDE the pool
# is untouched since the rebuild and still scores <= tau (its shard's
# top-B threshold <= the global max). The certificate per key
#     count(pool scores > tau) >= k   OR   pool ⊇ all feasible rows
# therefore proves the true global top-k lives in the pool; selection,
# dedup, and replay then match the single-device keyed kernel
# bit-for-bit (same f32 ops, same ascending-global-row tie parity).
# A failed certificate raises the chain's exactness FLAG and the
# pipelined worker treats the window like a failed drain: nack + chain
# taint + cold redispatch — the exactly-once machinery that already
# covers killed windows. Cold windows are exact unconditionally (the
# pool contains every shard's top-k at window start), so the flag is
# only ever consulted for warm windows.

_MESH_BUF_MULT = 2      # per-shard candidate buffer per key = mult * k
_MESH_RING_MULT = 16    # pending winner-row ring = mult * k_cand rows


class MeshChain:
    """Opaque sharded usage-chain tail for the keyed mesh pipeline.

    `usage` is the node-sharded usage EXCLUDING every window since the
    last rebuild; those winners live in `ring` (on the lead device)
    until the next cold window scatters them into their owning shards.
    `pool`/`pool_use`/`keep`/`tau`/`nf0` are the lead-device resident
    candidate state warm windows run against; `sig` pins the static
    inputs the warm path may assume unchanged (compared by object
    identity — `refs` keeps them alive). `flag` is the warm window's
    exactness certificate (None for cold windows, which are exact by
    construction). Everything is async device state: building a
    MeshChain never blocks the dispatching thread."""

    __slots__ = ("prog", "usage", "ring", "ring_n", "pool", "pool_use",
                 "keep", "tau", "nf0", "flag", "sig", "refs",
                 "exchange_bytes")

    def __init__(self, prog, usage, ring, ring_n, pool, pool_use, keep,
                 tau, nf0, flag, sig, refs, exchange_bytes):
        self.prog = prog
        self.usage = usage
        self.ring = ring
        self.ring_n = ring_n
        self.pool = pool
        self.pool_use = pool_use
        self.keep = keep
        self.tau = tau
        self.nf0 = nf0
        self.flag = flag
        self.sig = sig
        self.refs = refs
        self.exchange_bytes = exchange_bytes

    @property
    def shape(self):
        # ChainArbiter's shape/epoch validation sees the chain like a
        # plain usage array.
        return self.usage.shape

    def materialize(self):
        """Full usage including the pending winner ring, as a sharded
        device array (one scatter dispatch + one small transfer)."""
        import jax

        ring_rep = jax.device_put(self.ring, self.prog.rep_sharding)
        return self.prog.apply_fn(self.usage, ring_rep)

    def __array__(self, dtype=None):
        arr = np.asarray(self.materialize())
        return arr.astype(dtype) if dtype is not None else arr


class _MeshKeyedProgram:
    """Compiled stages + shardings for one (mesh, k_cand) bucket.

    The node-static columns (capacity, score_cap, job_counts, noise,
    banned) ride ONE packed table array so the cold stage's candidate
    gather is two reads (table + usage), not seven. Candidate packet
    rows use the single-device program's column layout:
    [row, capacity(R), score_cap(2), usage(R), counts, banned, noise,
    eligibility(T)]."""

    def __init__(self, mesh, k_cand):
        import jax.sharding as jsh

        self.mesh = mesh
        self.k_cand = k_cand
        self.ring_cap = _MESH_RING_MULT * k_cand
        axis = mesh.axis_names[0]
        self.axis = axis
        self.n_shards = int(mesh.devices.size)
        dev0 = mesh.devices.reshape(-1)[0]
        self.dev0 = dev0
        self.dev0_sharding = jsh.SingleDeviceSharding(dev0)
        self.node_sharding = jsh.NamedSharding(mesh, jsh.PartitionSpec(axis))
        self.mask_sharding = jsh.NamedSharding(
            mesh, jsh.PartitionSpec(None, axis))
        self.rep_sharding = jsh.NamedSharding(mesh, jsh.PartitionSpec())
        node = jsh.PartitionSpec(axis)
        mask2 = jsh.PartitionSpec(None, axis)
        rep = jsh.PartitionSpec()
        self._puts: "OrderedDict[tuple, tuple]" = OrderedDict()

        k = k_cand

        def pack_table(capacity, score_cap, job_counts0, noise, banned0):
            return jnp.concatenate([
                capacity,
                score_cap,
                job_counts0[:, None].astype(jnp.float32),
                noise[:, None],
                banned0[:, None].astype(jnp.float32),
            ], axis=1)

        # Sharded in, sharded out, no cross-shard ops: plain jit, XLA
        # propagates the sharding without collectives.
        self.pack_fn = jax.jit(pack_table)

        def _apply_ring(usage, ring, row_base):
            """Scatter the pending winner ring into this shard's rows.
            Ring entries carry each window's FINAL usage for the row, so
            the LAST entry per row wins; a scatter-max of ring positions
            picks it deterministically (XLA leaves duplicate-index
            scatter-set order undefined)."""
            n_loc = usage.shape[0]
            rc = ring.shape[0]
            drow = ring[:, 0].astype(jnp.int32) - row_base
            own = (ring[:, 0] >= 0) & (drow >= 0) & (drow < n_loc)
            iota = jnp.arange(rc, dtype=jnp.int32)
            pos = jnp.full((n_loc + 1,), -1, jnp.int32).at[
                jnp.where(own, drow, n_loc)].max(iota, mode="drop")
            last = own & (pos[jnp.clip(drow, 0, n_loc - 1)] == iota)
            # Foreign/padding/superseded rows get an out-of-range index
            # and drop.
            return usage.at[jnp.where(last, drow, n_loc)].set(
                ring[:, 1:], mode="drop")

        def a_cold(table, usage, tg_masks, key_demands, penalty, distinct,
                   ring):
            """Cold shard-local stage: apply the pending ring, one full
            score pass over only this shard's rows, emit the top-B
            candidate packet plus per-key nf0/top-score sidecars. No
            collectives — everything leaves via the explicit exchange.
            The top_k VALUES ship whole: slicing them in here breaks
            XLA:CPU's TopK custom-call rewrite and the lowering falls
            back to a full-row sort (measured 310ms vs 5ms per window at
            262k nodes) — pool_build extracts tau on the lead device."""
            n_loc, r_dims = usage.shape
            my = jax.lax.axis_index(axis)
            row_base = (my * n_loc).astype(jnp.int32)
            usage = _apply_ring(usage, ring, row_base)
            capacity = table[:, :r_dims]
            score_cap = table[:, r_dims:r_dims + 2]
            counts = table[:, r_dims + 2]
            noise = table[:, r_dims + 3]
            banned0 = table[:, r_dims + 4] > 0.5
            fits0 = jnp.all(capacity[None] - usage[None]
                            >= key_demands[:, None, :], axis=-1)
            ok0 = fits0 & tg_masks & ~(distinct & banned0)[None, :]
            util2 = usage[None, :, :2] + key_demands[:, None, :2]
            score = _score(util2, score_cap[None])
            score = score - counts[None, :] * penalty + noise[None, :]
            masked0 = jnp.where(ok0, score, -jnp.inf)
            nf0_loc = jnp.sum(ok0, axis=1).astype(jnp.int32)
            b_buf = min(_MESH_BUF_MULT * k, n_loc)
            vals, idx = jax.lax.top_k(masked0, b_buf)    # [T, B]
            flat = idx.reshape(-1)                       # [T*B] local rows
            pkt = jnp.concatenate([
                (flat + row_base)[:, None].astype(jnp.float32),
                capacity[flat],
                score_cap[flat],
                usage[flat],
                counts[flat][:, None],
                banned0[flat][:, None].astype(jnp.float32),
                noise[flat][:, None],
                tg_masks[:, flat].T.astype(jnp.float32),  # [T*B, T]
            ], axis=1)
            return pkt, usage, nf0_loc[None], vals[None]

        def pool_build(cand, nf_all, vals_all, key_demands):
            """Merge the per-shard packets into the resident candidate
            pool, once per rebuild, on the lead device: sort by
            ascending global row (argmax tie parity), first-occurrence
            dedup (copies of a row are identical), tau = max_s tau_s
            (each shard's B-th best window-start score), nf0 =
            sum_s nf0_s."""
            r_dims = key_demands.shape[1]
            nf0 = jnp.sum(nf_all, axis=0)
            tau = jnp.max(vals_all[:, :, vals_all.shape[2] - 1], axis=0)
            rows_g = cand[:, 0].astype(jnp.int32)
            order = jnp.argsort(rows_g)
            pool = cand[order]
            rows_s = rows_g[order]
            keep = jnp.concatenate(
                [jnp.ones((1,), bool), rows_s[1:] != rows_s[:-1]])
            pool_use = pool[:, 3 + r_dims:3 + 2 * r_dims]
            return pool, pool_use, keep, tau, nf0

        def warm_step(pool, pool_use, keep, tau, nf0, ring, ring_n,
                      key_demands, tg_ids, valid, reset, penalty,
                      distinct):
            """One window against the resident pool, entirely on the
            lead device: window-start ok/score pass, exactness
            certificate, top-k candidate selection, the exact P-step
            replay, winner scatter-back, ring append."""
            n_cand, w = pool.shape
            n_keys = key_demands.shape[0]
            r_dims = key_demands.shape[1]
            rows_s = pool[:, 0].astype(jnp.int32)
            c_cap = pool[:, 1:1 + r_dims]
            c_sc = pool[:, 1 + r_dims:3 + r_dims]
            c_cnt0 = pool[:, 3 + 2 * r_dims].astype(jnp.int32)
            c_ban0 = pool[:, 4 + 2 * r_dims] > 0.5
            c_noise = pool[:, 5 + 2 * r_dims]
            c_elig = pool[:, 6 + 2 * r_dims:] > 0.5     # [C, T]

            # Window-start ok/score per pool row per key — the
            # n_feasible delta baseline, the certificate's evidence, and
            # the selection ranking (identical f32 ops to the
            # single-device program's window-start pass).
            fits0 = jnp.all(c_cap[:, None, :] - pool_use[:, None, :]
                            >= key_demands[None, :, :], axis=-1)
            ok0 = (fits0 & c_elig & ~(distinct & c_ban0)[:, None]
                   & keep[:, None])                      # [C, T]
            util2 = pool_use[:, None, :2] + key_demands[None, :, :2]
            sc0 = _score(util2, c_sc[:, None, :])
            sc0 = (sc0 - c_cnt0.astype(jnp.float32)[:, None] * penalty
                   + c_noise[:, None])
            m0 = jnp.where(ok0, sc0, -jnp.inf)           # [C, T]

            # Exactness certificate: rows outside the pool are untouched
            # since rebuild, hence still <= tau; the true top-k is in
            # the pool iff >= k pool rows score strictly above tau, or
            # the pool covers every feasible row of the table.
            k_sel = min(k, n_cand)
            n_fin = jnp.sum(ok0, axis=0)                 # [T]
            exact = ((jnp.sum(m0 > tau[None, :], axis=0) >= k_sel)
                     | (n_fin >= nf0))
            flag = jnp.any(~exact).astype(jnp.float32)

            # Top-k candidate set per key; ascending pool index IS
            # ascending global row (the pool is sorted), so a plain sort
            # of the selected indices restores argmax tie parity, and
            # first-occurrence dedup masks rows two keys both selected.
            _, tidx = jax.lax.top_k(m0.T, k_sel)         # [T, k_sel]
            sel = jnp.sort(tidx.reshape(-1))             # [T*k_sel]
            keep2 = jnp.concatenate(
                [jnp.ones((1,), bool), sel[1:] != sel[:-1]]) & keep[sel]
            rows_sel = rows_s[sel]
            s_cap = c_cap[sel]
            s_sc = c_sc[sel]
            s_use0 = pool_use[sel]
            s_cnt0 = c_cnt0[sel]
            s_ban0 = c_ban0[sel]
            s_noise = c_noise[sel]
            s_elig = c_elig[sel]                         # [S, T]
            ok0c = ok0[sel] & keep2[:, None]
            kd_p = key_demands[tg_ids] * valid[:, None].astype(jnp.float32)

            def replay(carry, xs):
                c_use, c_cnt, c_ban = carry
                t_j, v_j, r_j, d_j = xs
                c_cnt = jnp.where(r_j, s_cnt0, c_cnt)
                c_ban = jnp.where(r_j, s_ban0, c_ban)
                elig_j = jax.lax.dynamic_index_in_dim(
                    s_elig, t_j, axis=1, keepdims=False)
                fits_c = jnp.all(s_cap - c_use >= d_j[None, :], axis=1)
                ok_c = fits_c & elig_j & ~(distinct & c_ban) & keep2
                sc = _score(c_use[:, :2] + d_j[None, :2], s_sc)
                sc = sc - c_cnt.astype(jnp.float32) * penalty + s_noise
                m = jnp.where(ok_c, sc, -jnp.inf)
                i = jnp.argmax(m)
                found = ok_c[i] & v_j
                one = found.astype(c_use.dtype)
                c_use = c_use.at[i].add(d_j * one)
                c_cnt = c_cnt.at[i].add(found.astype(jnp.int32))
                c_ban = c_ban.at[i].set(c_ban[i] | found)
                ok0_j = jax.lax.dynamic_index_in_dim(
                    ok0c, t_j, axis=1, keepdims=False)
                nf0_j = jax.lax.dynamic_index_in_dim(
                    nf0, t_j, keepdims=False)
                nf = nf0_j + jnp.sum(ok_c) - jnp.sum(ok0_j)
                out = jnp.stack([
                    jnp.where(found, rows_sel[i], -1).astype(jnp.float32),
                    jnp.where(found, m[i], -jnp.inf),
                    nf.astype(jnp.float32),
                    i.astype(jnp.float32),
                ])
                return (c_use, c_cnt, c_ban), out

            (c_use_f, _, _), outs = jax.lax.scan(
                replay, (s_use0, s_cnt0, s_ban0),
                (tg_ids, valid, reset, kd_p))            # [P, 4]
            packed = outs[:, :3]

            # Winners' FINAL usage back into the pool by scatter-SET:
            # c_use_f accumulated each row's won demands sequentially in
            # placement order, bit-identical to the monolithic scan's
            # in-register adds. Masked duplicate entries carry a STALE
            # initial value (the replay never touches them), so they get
            # an out-of-range index and drop rather than racing the kept
            # copy's write.
            pool_use2 = pool_use.at[
                jnp.where(keep2, sel, n_cand)].set(c_use_f, mode="drop")

            # Next window's n_feasible baseline: only pool rows changed.
            fits_f = jnp.all(c_cap[:, None, :] - pool_use2[:, None, :]
                             >= key_demands[None, :, :], axis=-1)
            ok_f = (fits_f & c_elig & ~(distinct & c_ban0)[:, None]
                    & keep[:, None])
            nf0_2 = nf0 + (jnp.sum(ok_f, axis=0)
                           - jnp.sum(ok0, axis=0)).astype(jnp.int32)

            # Winner-row delta appended to the pending ring: O(P) rows
            # of (global row or -1, final usage). Duplicate winners
            # carry identical values; cross-window ordering is resolved
            # by the ring-apply's last-write-wins scatter.
            widx = outs[:, 3].astype(jnp.int32)
            delta = jnp.concatenate(
                [outs[:, 0:1], c_use_f[widx]], axis=1)   # [P, 1+R]
            ring2 = jax.lax.dynamic_update_slice(
                ring, delta, (ring_n, jnp.int32(0)))
            return packed, pool_use2, nf0_2, ring2, flag

        def apply_ring_full(usage, ring):
            my = jax.lax.axis_index(axis)
            row_base = (my * usage.shape[0]).astype(jnp.int32)
            return _apply_ring(usage, ring, row_base)

        self.a_cold = jax.jit(_shard_map(
            a_cold, mesh=mesh,
            in_specs=(node, node, mask2, rep, rep, rep, rep),
            out_specs=(node, node, node, node), check_rep=False))
        self.pool_build = jax.jit(pool_build)
        self.warm_step = jax.jit(warm_step)
        self.apply_fn = jax.jit(_shard_map(
            apply_ring_full, mesh=mesh,
            in_specs=(node, rep), out_specs=node, check_rep=False))

    def table(self, d_cap, d_sc, d_counts, d_noise, d_banned) -> object:
        """Packed node-static table for these committed inputs, memoized
        by identity (one device-side concat per signature change)."""
        key = ("table", id(d_cap), id(d_sc), id(d_counts), id(d_noise),
               id(d_banned))
        hit = self._puts.get(key)
        if hit is not None:
            self._puts.move_to_end(key)
            return hit[1]
        dev = self.pack_fn(d_cap, d_sc, d_counts, d_noise, d_banned)
        self._puts[key] = ((d_cap, d_sc, d_counts, d_noise, d_banned), dev)
        while len(self._puts) > 64:
            self._puts.popitem(last=False)
        return dev

    # --------------------------------------------------------- input plumbing
    def put(self, name: str, arr, sharding) -> object:
        """Commit one input to the mesh, memoized by object identity: a
        chained storm passes the same host arrays every window and must
        not pay a broadcast per window."""
        import jax

        if _is_committed(arr):
            return arr
        key = (name, id(arr))
        hit = self._puts.get(key)
        if hit is not None:
            self._puts.move_to_end(key)
            return hit[1]
        dev = jax.device_put(np.asarray(arr), sharding)
        self._puts[key] = (arr, dev)
        while len(self._puts) > 64:
            self._puts.popitem(last=False)
        return dev

    def dev0_view(self, arr) -> object:
        """Lead-device view of a replicated/committed array (zero-copy
        when the array already has an addressable shard on dev0)."""
        import jax

        if isinstance(arr, np.ndarray) or np.isscalar(arr):
            return arr  # uncommitted: jit places it with the pool on dev0
        try:
            for s in arr.addressable_shards:
                if s.device == self.dev0:
                    return s.data
        except AttributeError:
            pass
        return jax.device_put(arr, self.dev0_sharding)


def _is_committed(arr) -> bool:
    return hasattr(arr, "sharding") and not isinstance(arr, np.ndarray)


@functools.lru_cache(maxsize=16)
def _mesh_keyed_program(mesh, k_cand: int) -> _MeshKeyedProgram:
    return _MeshKeyedProgram(mesh, k_cand)


def _place_batch_keyed_mesh(mesh, capacity, score_cap, usage, tg_masks,
                            job_counts0, key_demands, tg_ids, valid, noise,
                            penalty, distinct_hosts, banned0, reset,
                            n_valid: int) -> PlacementResult:
    """One window of the shard-local mesh pipeline. `usage` may be a
    plain array (cold window) or the previous window's MeshChain (warm
    when the chain's static inputs are identical by object identity and
    the pending ring has room; anything else rebuilds cold, reusing the
    chained usage + ring when the k bucket still matches)."""
    import jax
    import time as _time

    from nomad_tpu.resilience import failpoints

    k_cand = keyed_cand_count(n_valid)
    prog = _mesh_keyed_program(mesh, k_cand)
    p_pad = len(tg_ids)
    ring_cap = prog.ring_cap
    r_dims = key_demands.shape[1]

    refs = (capacity, score_cap, tg_masks, job_counts0, noise, banned0,
            key_demands, penalty, distinct_hosts)
    sig = tuple(map(id, refs)) + (k_cand, id(mesh))

    d_cap = prog.put("capacity", capacity, prog.node_sharding)
    d_sc = prog.put("score_cap", score_cap, prog.node_sharding)
    d_masks = prog.put("tg_masks", tg_masks, prog.mask_sharding)
    d_counts = prog.put("job_counts", job_counts0, prog.node_sharding)
    d_noise = prog.put("noise", noise, prog.node_sharding)
    d_banned = prog.put("banned0", banned0, prog.node_sharding)
    d_kd = prog.put("key_demands", key_demands, prog.rep_sharding)
    d_pen = prog.put("penalty", penalty, prog.rep_sharding)
    d_dist = prog.put("distinct", distinct_hosts, prog.rep_sharding)
    d_table = prog.table(d_cap, d_sc, d_counts, d_noise, d_banned)

    chain = usage if isinstance(usage, MeshChain) else None
    # The warm path may assume nothing changed but usage-via-winners:
    # the signature pins every static input by identity and the pending
    # ring must have room for this window's delta.
    warm = (chain is not None and chain.prog is prog
            and chain.sig == sig
            and chain.ring_n + p_pad <= ring_cap)

    t0 = _time.perf_counter()
    exchange_ms = 0.0
    exchange_bytes = 0
    poisoned = False
    if warm:
        pool, pool_use, keep = chain.pool, chain.pool_use, chain.keep
        tau, nf0 = chain.tau, chain.nf0
        ring, ring_n = chain.ring, chain.ring_n
        usage_tail = chain.usage
    else:
        if chain is not None and chain.prog is prog:
            # Same bucket: the cold stage applies the chain's pending
            # ring while rebuilding, no materialize round trip.
            base, ring0 = chain.usage, jax.device_put(
                chain.ring, prog.rep_sharding)
        elif chain is not None:
            base = chain.materialize()
            ring0 = prog.put("ring0", _ring_zero(ring_cap, r_dims),
                             prog.rep_sharding)
        else:
            base = usage if _is_committed(usage) else \
                prog.put("usage", usage, prog.node_sharding)
            ring0 = prog.put("ring0", _ring_zero(ring_cap, r_dims),
                             prog.rep_sharding)
        pkt, usage_tail, nf_sh, vals_sh = prog.a_cold(
            d_table, base, d_masks, d_kd, d_pen, d_dist, ring0)
        # The winner-row exchange seam: per-shard candidate packets hop
        # to the lead device as one small async device-to-device
        # transfer — never a collective, never a host sync. Warm windows
        # don't cross the interconnect at all. Chaos coverage:
        # tensor.mesh.exchange — `error`/`delay` surface at this dispatch
        # seam (the worker routes the run to the exact path); `drop`
        # simulates a SILENTLY lost/corrupt exchange by poisoning the
        # chain's exactness certificate, so the failure surfaces where a
        # real ICI loss would — at the drain-stage certificate check,
        # which nacks the window, taints the chain, and redelivers
        # exactly once through the ChainArbiter rebase.
        poisoned = failpoints.fire("tensor.mesh.exchange") == "drop"
        pkt0 = jax.device_put(pkt, prog.dev0_sharding)
        nf0_0 = jax.device_put(nf_sh, prog.dev0_sharding)
        vals0 = jax.device_put(vals_sh, prog.dev0_sharding)
        exchange_bytes = int(pkt.nbytes + nf_sh.nbytes + vals_sh.nbytes)
        pool, pool_use, keep, tau, nf0 = prog.pool_build(
            pkt0, nf0_0, vals0, prog.dev0_view(d_kd))
        ring = prog.dev0_view(prog.put(
            "ring0", _ring_zero(ring_cap, r_dims), prog.rep_sharding))
        ring_n = 0
        # Timer stops HERE: exchange_ms is cold rebuild + winner-row
        # exchange dispatch only — warm windows perform no exchange, so
        # their (lead-device) dispatch must not inflate the metric.
        exchange_ms = (_time.perf_counter() - t0) * 1e3

    packed, pool_use2, nf0_2, ring2, flag = prog.warm_step(
        pool, pool_use, keep, tau, nf0, ring, np.int32(ring_n),
        prog.dev0_view(d_kd), prog.dev0_view(tg_ids),
        prog.dev0_view(valid), prog.dev0_view(reset),
        prog.dev0_view(d_pen), prog.dev0_view(d_dist))

    with _MESH_STATS_LOCK:
        _MESH_STATS["exchange_ms"] += exchange_ms
        _MESH_STATS["candidate_bytes"] += exchange_bytes
        _MESH_STATS["windows"] += 1
        _MESH_STATS["warm_windows"] += 1 if warm else 0

    chain_flag = flag if warm else None
    if poisoned:
        chain_flag = np.float32(1.0)
    new_chain = MeshChain(
        prog, usage_tail, ring2, ring_n + p_pad, pool, pool_use2, keep,
        tau, nf0_2, chain_flag, sig, refs, exchange_bytes)
    return PlacementResult(packed, new_chain)


_COLLECTIVE_RE = r"(all-gather|all-reduce|reduce-scatter|collective-permute)"


def mesh_collective_audit(mesh, k_cand: int, n_rows: int = 512,
                          n_keys: int = 4, p_pad: int = 64,
                          r_dims: int = 5) -> dict:
    """Compile every stage of the shard-local mesh pipeline on synthetic
    inputs and count the collectives in each program's HLO — the
    structural claim behind the pipeline (zero: the cold stage is
    shard-local, the exchange is an explicit point-to-point device_put,
    warm windows live on the lead device). Returns per-stage counts plus
    the per-window exchanged bytes at this shape. Shared by the tier-1
    collective-count gate (tests/test_tensor_and_kernels.py) and the
    multi-chip dry-run report, which FAILS (exit 2) on a regression."""
    import re

    import jax

    prog = _mesh_keyed_program(mesh, k_cand)
    rng = np.random.default_rng(0)
    put = jax.device_put
    d_cap = put(rng.uniform(1e3, 4e3, (n_rows, r_dims)).astype(np.float32),
                prog.node_sharding)
    d_sc = put(rng.uniform(8e2, 4e3, (n_rows, 2)).astype(np.float32),
               prog.node_sharding)
    usage = put(np.zeros((n_rows, r_dims), np.float32), prog.node_sharding)
    d_counts = put(np.zeros(n_rows, np.int32), prog.node_sharding)
    d_noise = put((rng.random(n_rows) * 1e-3).astype(np.float32),
                  prog.node_sharding)
    d_banned = put(np.zeros(n_rows, bool), prog.node_sharding)
    d_masks = put(np.ones((n_keys, n_rows), bool), prog.mask_sharding)
    d_kd = put(np.full((n_keys, r_dims), 20, np.float32), prog.rep_sharding)
    d_pen = put(np.float32(10.0), prog.rep_sharding)
    d_dist = put(np.asarray(False), prog.rep_sharding)
    ring0 = put(_ring_zero(prog.ring_cap, r_dims), prog.rep_sharding)
    table = prog.pack_fn(d_cap, d_sc, d_counts, d_noise, d_banned)

    def count(jitted, *args):
        hlo = jitted.lower(*args).compile().as_text()
        return len(re.findall(_COLLECTIVE_RE, hlo))

    out = {"cold": count(prog.a_cold, table, usage, d_masks, d_kd, d_pen,
                         d_dist, ring0)}
    pkt, usage_tail, nf_sh, vals_sh = prog.a_cold(
        table, usage, d_masks, d_kd, d_pen, d_dist, ring0)
    out["exchange_bytes"] = int(pkt.nbytes + nf_sh.nbytes + vals_sh.nbytes)
    pkt0 = put(pkt, prog.dev0_sharding)
    nf0_0 = put(nf_sh, prog.dev0_sharding)
    vals0 = put(vals_sh, prog.dev0_sharding)
    out["pool_build"] = count(prog.pool_build, pkt0, nf0_0, vals0,
                              prog.dev0_view(d_kd))
    pool, pool_use, keep, tau, nf0 = prog.pool_build(
        pkt0, nf0_0, vals0, prog.dev0_view(d_kd))
    tg_ids = np.zeros(p_pad, np.int32)
    valid = np.ones(p_pad, bool)
    reset = np.zeros(p_pad, bool)
    out["warm"] = count(
        prog.warm_step, pool, pool_use, keep, tau, nf0,
        prog.dev0_view(ring0), np.int32(0), prog.dev0_view(d_kd), tg_ids,
        valid, reset, prog.dev0_view(d_pen), prog.dev0_view(d_dist))
    out["apply"] = count(prog.apply_fn, usage_tail, ring0)
    return out


# Module-level mesh pipeline counters, drained by the pipelined worker
# into its declared stats schema (nomad.mesh.* keys). The lock covers
# the += read-modify-writes against a concurrent drain's copy+reset:
# with N workers, one worker's roll-up runs lease-free while another's
# dispatch is mid-increment.
_MESH_STATS = {"exchange_ms": 0.0, "candidate_bytes": 0, "windows": 0,
               "warm_windows": 0}
_MESH_STATS_LOCK = threading.Lock()


def mesh_stats_drain() -> dict:
    """Return-and-reset the pipeline counters (any worker's stats
    roll-up may drain; totals are preserved across drains)."""
    with _MESH_STATS_LOCK:
        out = dict(_MESH_STATS)
        _MESH_STATS.update(exchange_ms=0.0, candidate_bytes=0, windows=0,
                           warm_windows=0)
    return out


@functools.lru_cache(maxsize=8)
def _ring_zero(cap: int, r_dims: int) -> np.ndarray:
    return np.full((cap, 1 + r_dims), -1.0, dtype=np.float32)


# Note: the system scheduler's per-node sweep and the plan applier's
# re-verification run host-side (numpy / structs.allocs_fit) — they are
# O(nodes-in-one-plan), tiny next to the placement scan, and need exact
# port-level network checks that don't tensorize. Only place_batch is hot.
