"""XLA placement kernels: the scheduling hot path as tensor programs.

This replaces the reference's per-node iterator chain (reference:
scheduler/stack.go Select -> select.go MaxScoreIterator -> rank.go
BinPackIterator -> feasible.go checkers) with batched device programs:

  place_batch   lax.scan over the placements of one evaluation; each step is
                a fused feasibility-mask + BestFit-v3 score + argmax over the
                whole node axis, with in-register usage/anti-affinity updates
                so placement k+1 sees placement k's proposed allocation
                (reference semantics: scheduler/context.go:109-140).

Scoring matches reference funcs.go:102-137 (including its Inf/NaN division
edges) with the job anti-affinity penalty applied after clamping (reference:
rank.go:242-304). Selection is a global argmax rather than the reference's
max-over-log2(n)-random-candidates (reference: stack.go:120-133), which can
only improve placement quality; host-supplied per-node noise reproduces the
load-spreading effect of the reference's node shuffle on ties.

All shapes are static per (N_pad, P_pad) bucket: the node axis is padded to a
power of two by NodeTensor and the placement axis by the stack, so jit caches
stay warm. The node axis is the sharding axis for multi-chip meshes
(nomad_tpu/parallel/).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_LOG2_10 = float(np.log2(10.0))


class PlacementResult(NamedTuple):
    packed: jax.Array       # [P, 3] f32: (chosen row or -1, score, n_feasible)
    usage_after: jax.Array  # [N, R] usage including the new placements

    # The packed layout exists because a device->host readback has a fixed
    # RTT cost on remote-attached TPUs: one transfer per eval, not three.
    @property
    def chosen(self):
        return self.packed[:, 0].astype(jnp.int32)

    @property
    def scores(self):
        return self.packed[:, 1]

    @property
    def n_feasible(self):
        return self.packed[:, 2].astype(jnp.int32)


def _score(usage2: jax.Array, score_cap: jax.Array) -> jax.Array:
    """BestFit-v3: 20 - 10^freeCpuPct - 10^freeMemPct, clamped to [0, 18].

    usage2 [N, 2] is proposed (cpu, mem) utilization including reserved;
    score_cap [N, 2] is capacity minus reserved. Division by zero follows
    IEEE (Inf/NaN) exactly like the Go reference; NaN sanitizes to 0.
    """
    free_pct = 1.0 - usage2 / score_cap
    # 10^x on the MXU-friendly path: exp2(x * log2 10).
    total = jnp.exp2(free_pct[:, 0] * _LOG2_10) + jnp.exp2(free_pct[:, 1] * _LOG2_10)
    score = jnp.clip(20.0 - total, 0.0, 18.0)
    return jnp.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)


def _make_step(capacity, score_cap, tg_masks, noise, penalty,
               distinct_hosts, job_counts0=None, banned0=None):
    """The ONE definition of the per-placement scan step (fused
    feasibility mask + BestFit-v3 score + argmax + in-register state
    updates). place_batch uses the plain (demand, tg_id, valid) input
    tuple; place_batch_multi adds a reset flag that reloads the per-JOB
    state (anti-affinity counts, distinct-hosts bans) at eval boundaries.
    Sharing the body keeps single/multi/chained parity by construction."""

    def step(carry, inputs):
        usage, job_counts, banned = carry
        if len(inputs) == 4:
            demand, tg_id, is_valid, is_reset = inputs
            job_counts = jnp.where(is_reset, job_counts0, job_counts)
            banned = jnp.where(is_reset, banned0, banned)
        else:
            demand, tg_id, is_valid = inputs
        eligible = tg_masks[tg_id]

        fits = jnp.all(capacity - usage >= demand[None, :], axis=1)
        ok = fits & eligible & ~(distinct_hosts & banned)

        util2 = usage[:, :2] + demand[None, :2]
        score = _score(util2, score_cap)
        score = score - job_counts.astype(jnp.float32) * penalty + noise
        masked = jnp.where(ok, score, -jnp.inf)

        idx = jnp.argmax(masked)
        found = ok[idx] & is_valid

        one = found.astype(usage.dtype)
        usage = usage.at[idx].add(demand * one)
        job_counts = job_counts.at[idx].add(found.astype(job_counts.dtype))
        banned = banned.at[idx].set(banned[idx] | found)

        out = jnp.stack([
            jnp.where(found, idx, -1).astype(jnp.float32),
            jnp.where(found, masked[idx], -jnp.inf),
            jnp.sum(ok).astype(jnp.float32),
        ])
        return (usage, job_counts, banned), out

    return step


@functools.partial(jax.jit, donate_argnums=())
def place_batch(
    capacity: jax.Array,    # [N, R] total resources (fit bound)
    score_cap: jax.Array,   # [N, 2] cpu/mem minus reserved (score denominator)
    usage: jax.Array,       # [N, R] reserved + committed allocs (+/- plan deltas)
    tg_masks: jax.Array,    # [T, N] bool per task group: ready & dc & class & escaped
    job_counts: jax.Array,  # [N] int32 proposed allocs of this job per node
    demands: jax.Array,     # [P, R] per-placement resource ask
    tg_ids: jax.Array,      # [P] int32 task-group index into tg_masks
    valid: jax.Array,       # [P] bool: real placement vs padding
    noise: jax.Array,       # [N] f32 tie-break jitter in [0, 1e-3)
    penalty: jax.Array,     # f32 job anti-affinity penalty (10 service / 5 batch)
    distinct_hosts: jax.Array,  # bool: job has a distinct_hosts constraint
    banned0: jax.Array,     # [N] bool: nodes already holding this job's allocs
) -> PlacementResult:
    step = _make_step(capacity, score_cap, tg_masks, noise, penalty,
                      distinct_hosts)
    (usage, _, _), packed = jax.lax.scan(
        step, (usage, job_counts, banned0), (demands, tg_ids, valid))
    return PlacementResult(packed, usage)


@functools.partial(jax.jit, donate_argnums=())
def place_batch_multi(
    capacity: jax.Array,    # [N, R]
    score_cap: jax.Array,   # [N, 2]
    usage: jax.Array,       # [N, R] chain input (window-sequential)
    tg_masks: jax.Array,    # [T, N] shared across the window's evals
    job_counts0: jax.Array,  # [N] per-eval anti-affinity base (shared)
    demands: jax.Array,     # [E*P, R] all evals' placements, concatenated
    tg_ids: jax.Array,      # [E*P]
    valid: jax.Array,       # [E*P]
    noise: jax.Array,       # [N]
    penalty: jax.Array,     # f32
    distinct_hosts: jax.Array,  # bool (shared job shape)
    banned0: jax.Array,     # [N] per-eval distinct-hosts base (shared)
    reset: jax.Array,       # [E*P] bool: True at each eval's first step
) -> PlacementResult:
    """One scan over a WHOLE WINDOW of same-shaped evaluations.

    A registration storm's window is N near-identical evals whose prepared
    inputs dedupe to one PreparedBatch; dispatching place_batch per eval
    pays a host->device launch per eval plus an eager jnp.stack over the
    window at drain (both scale with window size and dominate on a
    remote-attached TPU). This kernel concatenates the placements and
    resets the per-JOB state (anti-affinity counts, distinct-hosts bans)
    at each eval boundary, so the whole window is ONE dispatch and ONE
    readback while usage chains exactly as the per-eval kernels did
    (reference sequencing semantics: scheduler/context.go:109-140 within
    an eval; optimistic worker chaining across evals)."""
    step = _make_step(capacity, score_cap, tg_masks, noise, penalty,
                      distinct_hosts, job_counts0=job_counts0,
                      banned0=banned0)
    (usage, _, _), packed = jax.lax.scan(
        step, (usage, job_counts0, banned0),
        (demands, tg_ids, valid, reset))
    return PlacementResult(packed, usage)


_LOG2_10_F32 = np.float32(_LOG2_10)


def place_batch_host(capacity, score_cap, usage, tg_masks, job_counts,
                     demands, tg_ids, valid, noise, penalty,
                     distinct_hosts, banned0) -> PlacementResult:
    """Numpy mirror of place_batch for SHALLOW windows.

    On a remote-attached TPU every host sync costs a fixed ~100ms round
    trip (and the first device->host transfer pins the whole process into
    that mode), so a lone eval's 50 placements are orders of magnitude
    faster as host vector ops than as a device dispatch + readback. The
    pipelined worker routes small idle-broker windows here and storms to
    the device chain; semantics are identical — same f32 BestFit-v3
    formula with its Inf/NaN edges (reference funcs.go:102-137), same
    anti-affinity penalty and noise tie-break, same in-loop usage updates
    so placement k+1 sees placement k (reference context semantics,
    scheduler/context.go:109-140). tests/test_tensor_and_kernels.py
    asserts parity against the device kernel."""
    capacity = np.asarray(capacity, np.float32)
    score_cap = np.asarray(score_cap, np.float32)
    usage = np.array(usage, np.float32, copy=True)
    job_counts = np.array(job_counts, np.int32, copy=True)
    banned = np.array(banned0, bool, copy=True)
    demands = np.asarray(demands, np.float32)
    tg_ids = np.asarray(tg_ids, np.int32)
    valid = np.asarray(valid, bool)
    noise = np.asarray(noise, np.float32)
    penalty = np.float32(penalty)
    distinct_hosts = bool(distinct_hosts)
    tg_masks = np.asarray(tg_masks, bool)

    p = len(tg_ids)
    packed = np.empty((p, 3), np.float32)
    neg_inf = np.float32(-np.inf)

    def full_scores(demand):
        """Whole-table masked-score pass — the same f32 formula as the
        device kernel's step."""
        util2 = usage[:, :2] + demand[:2]
        free_pct = np.float32(1.0) - util2 / score_cap
        total = (np.exp2(free_pct[:, 0] * _LOG2_10_F32)
                 + np.exp2(free_pct[:, 1] * _LOG2_10_F32))
        score = np.clip(np.float32(20.0) - total,
                        np.float32(0.0), np.float32(18.0))
        score = np.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)
        return score - job_counts.astype(np.float32) * penalty + noise

    def row_score(idx, demand):
        """One row of full_scores, recomputed after the row's usage or
        count changed — bit-identical to the full pass for that row."""
        util2 = usage[idx, :2] + demand[:2]
        free_pct = np.float32(1.0) - util2 / score_cap[idx]
        total = (np.exp2(free_pct[0] * _LOG2_10_F32)
                 + np.exp2(free_pct[1] * _LOG2_10_F32))
        score = np.float32(np.clip(np.float32(20.0) - total,
                                   np.float32(0.0), np.float32(18.0)))
        score = np.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)
        return (score - np.float32(job_counts[idx]) * penalty
                + noise[idx])

    with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
        # A storm places many copies of the same task group: between two
        # placements of one (tg, demand) key only ONE node row changes, so
        # the masked-score vector is computed once per key and patched at
        # the placed row afterwards — O(rows) once + O(keys) per step
        # instead of O(rows) per step. Exactly the same f32 values as the
        # naive loop (each row's score is a pure function of that row).
        cache: dict = {}  # key -> [masked, ok, n_feasible, demand, tg]
        for k in range(p):
            demand = demands[k]
            tg = int(tg_ids[k])
            key = (tg, demand.tobytes())
            ent = cache.get(key)
            if ent is None:
                eligible = tg_masks[tg]
                fits = np.all(capacity - usage >= demand[None, :], axis=1)
                ok = fits & eligible
                if distinct_hosts:
                    ok &= ~banned
                masked = np.where(ok, full_scores(demand), neg_inf)
                ent = cache[key] = [masked, ok,
                                    np.float32(np.count_nonzero(ok)),
                                    demand, tg]
            masked, ok, n_feasible = ent[0], ent[1], ent[2]
            idx = int(np.argmax(masked))
            found = bool(ok[idx]) and bool(valid[k])
            packed[k, 0] = np.float32(idx) if found else np.float32(-1)
            packed[k, 1] = masked[idx] if found else neg_inf
            packed[k, 2] = n_feasible
            if found:
                usage[idx] += demand
                job_counts[idx] += 1
                banned[idx] = True
                # Patch the changed row into every cached key: a row's
                # score/feasibility is a pure function of that row, so the
                # patched vectors stay identical to a full recompute.
                cap_row = capacity[idx]
                usage_row = usage[idx]
                for cent in cache.values():
                    cmask, cok, cn, cdemand, ctg = cent
                    old_ok = bool(cok[idx])
                    new_ok = (bool(np.all(cap_row - usage_row >= cdemand))
                              and bool(tg_masks[ctg, idx]))
                    if distinct_hosts:
                        new_ok = new_ok and not banned[idx]
                    cok[idx] = new_ok
                    cmask[idx] = (row_score(idx, cdemand) if new_ok
                                  else neg_inf)
                    if new_ok != old_ok:
                        cent[2] = np.float32(
                            cn + (1.0 if new_ok else -1.0))
    # Same result type as the device kernel; both arrays are
    # host-side numpy here — the pipelined drain dispatches on
    # isinstance(packed, np.ndarray) and skips the readback.
    return PlacementResult(packed, usage)


# Note: the system scheduler's per-node sweep and the plan applier's
# re-verification run host-side (numpy / structs.allocs_fit) — they are
# O(nodes-in-one-plan), tiny next to the placement scan, and need exact
# port-level network checks that don't tensorize. Only place_batch is hot.
