"""SystemScheduler: one allocation per eligible node (reference:
scheduler/system_sched.go).

System placement is per-specific-node (the diff pins each placement to its
node), so no scan chain is needed: the whole evaluation is one fused
feasibility/diff mask over the node axis plus a bulk columnar emit
(system_sweep.py). The exact per-node path below — class-memoized
constraint checks plus a numpy fit per pinned node — survives for
network-ask groups (port bitmaps are host state), deregisters, and as the
oracle side of the fixed-seed sweep-equivalence gate.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Dict, List, Optional

from nomad_tpu.telemetry import metrics
from nomad_tpu.structs import (
    Allocation,
    AllocMetric,
    Evaluation,
    Job,
    Plan,
    PlanResult,
    generate_uuid,
)
from nomad_tpu.structs.structs import (
    AllocClientStatusPending,
    AllocDesiredStatusRun,
    AllocDesiredStatusStop,
    EvalStatusComplete,
    EvalStatusFailed,
    EvalTriggerJobDeregister,
    EvalTriggerJobRegister,
    EvalTriggerNodeUpdate,
)
from nomad_tpu.tensor import TensorIndex, alloc_vec

from . import system_sweep
from .context import EvalContext
from .scheduler import Planner, SetStatusError, State
from .stack import SystemStack
from .util import (
    ALLOC_NODE_TAINTED,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    attempt_inplace_updates,
    diff_system_allocs,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
)

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5

# A 10k-node system sweep produces one monolithic plan whose verify+apply
# monopolizes the applier for hundreds of ms. Chunking streams it through
# the plan queue so verify(i+1) overlaps apply(i) and other evals' plans
# interleave between chunks (reference anchor: plan_apply.go:41-119's
# verify/apply overlap; the reference commits system sweeps whole, which
# is exactly the latency cliff this avoids).
SYSTEM_PLAN_CHUNK = 2048

_HANDLED = (EvalTriggerJobRegister, EvalTriggerNodeUpdate,
            EvalTriggerJobDeregister)


class SystemScheduler:
    def __init__(self, state: State, planner: Planner,
                 tindex: Optional[TensorIndex], logger: logging.Logger,
                 rng: Optional[random.Random] = None,
                 vectorized: bool = True):
        self.state = state
        self.planner = planner
        self.tindex = tindex
        self.logger = logger
        self.rng = rng or random.Random()
        # Tensor-sweep path switch; False forces the exact per-node path
        # (the equivalence gate's oracle side).
        self.vectorized = vectorized

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result: Optional[PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[SystemStack] = None
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.nodes = []
        self.node_by_dc: Dict[str, int] = {}
        # Memoized ready_nodes_in_dcs result: (state, dcs, node_version,
        # (nodes, dc_map)). Holding the state reference keeps identity
        # comparison sound (no id() reuse).
        self._ready_cache: Optional[tuple] = None

    def process(self, eval: Evaluation) -> None:
        """(reference: system_sched.go:54-102)"""
        self.eval = eval
        if eval.TriggeredBy not in _HANDLED:
            set_status(self.planner, eval, None, None, self.failed_tg_allocs,
                       EvalStatusFailed,
                       f"scheduler cannot handle '{eval.TriggeredBy}' evaluation reason")
            return
        try:
            retry_max(MAX_SYSTEM_SCHEDULE_ATTEMPTS, self._process,
                      lambda: progress_made(self.plan_result))
        except SetStatusError as e:
            set_status(self.planner, eval, None, None, self.failed_tg_allocs,
                       e.eval_status, str(e))
            return
        set_status(self.planner, eval, None, None, self.failed_tg_allocs,
                   EvalStatusComplete, "")

    def _process(self) -> bool:
        """(reference: system_sched.go:105-162)"""
        self.job = self.state.job_by_id(self.eval.JobID)
        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = {}
        self.ctx = EvalContext(self.state, self.plan, self.logger)
        if self.tindex is None:
            self.tindex = TensorIndex.from_state(self.state)
        self.stack = SystemStack(self.ctx, self.tindex)
        use_sweep = (self.vectorized
                     and system_sweep.sweep_applicable(self.job, self.tindex))
        if self.job is not None:
            if use_sweep:
                # Tensor-sweep wiring: the shared table-wide eligibility
                # replaces set_nodes/set_job's O(cluster) walk; the node
                # set IS the tensor's ready/DC mask.
                self.stack.adopt_shared(
                    self.job, self.tindex.shared_elig(self.state))
            else:
                self.nodes, self.node_by_dc = self._ready_nodes(
                    self.job.Datacenters)
                self.stack.set_nodes(self.nodes)
                self.stack.set_job(self.job)

        self._compute_job_allocs(use_sweep)

        if self.plan.is_no_op():
            return True

        result, new_state = self._submit_chunked(self.plan)
        self.plan_result = result
        if new_state is not None:
            self.state = new_state
            if self.tindex is not None and not self.tindex.attached:
                self.tindex = None
            return False
        if result is None:
            # Planner declined (e.g. a cancelled chunk after a wait
            # failure): count as a no-progress attempt, don't deref.
            return False
        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            self.logger.debug("eval %s: attempted %d placements, %d placed",
                              self.eval.ID, expected, actual)
            return False
        return True

    def _submit_chunked(self, plan: Plan):
        """Submit the sweep's plan in SYSTEM_PLAN_CHUNK-alloc chunks (node
        boundaries preserved; each node's evictions ride the same chunk as
        its placements) and merge the results. Chunking exists for
        FAIRNESS: with other plans contending for the applier, a 10k-alloc
        sweep would otherwise monopolize it for hundreds of ms while
        interactive evals queue behind it. With an empty queue the
        monolithic submit is strictly cheaper (chunk verify/apply overhead
        buys nothing without contention), so small plans and uncontended
        sweeps take the ordinary path — as do AllAtOnce plans, whose
        all-or-nothing contract the applier enforces per plan and which
        chunking would silently weaken to per-chunk."""
        n_allocs = sum(len(v) for v in plan.NodeAllocation.values())
        depth_fn = getattr(self.planner, "plan_queue_depth", None)
        contended = depth_fn is not None and depth_fn() > 0
        if n_allocs <= SYSTEM_PLAN_CHUNK or not contended \
                or plan.AllAtOnce:
            return self.planner.submit_plan(plan)

        sweep = getattr(plan, "_sweep", None)
        if (sweep is not None and not plan.NodeUpdate
                and len(sweep.node_ids) == len(plan.NodeAllocation)):
            # Columnar chunking: the sweep descriptor already lists every
            # placed node in row order, so chunks slice it instead of
            # re-walking the NodeAllocation dict — and each chunk carries
            # its slice so the applier's one-vector-op verify survives
            # chunking.
            chunks = []
            node_alloc = plan.NodeAllocation
            ids = sweep.node_ids
            i, total = 0, len(ids)
            while i < total:
                j, count = i, 0
                while j < total and count < SYSTEM_PLAN_CHUNK:
                    count += len(node_alloc[ids[j]])
                    j += 1
                chunk = Plan(EvalID=plan.EvalID, Priority=plan.Priority,
                             Job=plan.Job, AllAtOnce=plan.AllAtOnce)
                chunk.NodeAllocation = {nid: node_alloc[nid]
                                        for nid in ids[i:j]}
                chunk._sweep = sweep.slice(i, j)
                chunks.append(chunk)
                i = j
            chunks[0].Annotations = plan.Annotations
            return self._submit_chunks(chunks)

        chunks: List[Plan] = []
        current = None
        count = 0
        # Each node's evictions travel WITH its placements so the per-node
        # remove-then-add stays atomic in one chunk's verify — an eviction
        # stranded in an earlier chunk would double-count capacity against
        # the replacement under the one-sided optimistic overlay and force
        # spurious partial commits on tight nodes. Evict-only nodes fill
        # chunks like placements do (they count toward the budget, so a
        # fleet-wide destructive update cannot recreate the monolithic
        # plan as "chunk 0").
        node_ids = list(dict.fromkeys(
            list(plan.NodeAllocation) + list(plan.NodeUpdate)))
        for node_id in node_ids:
            if current is None or count >= SYSTEM_PLAN_CHUNK:
                current = Plan(EvalID=plan.EvalID, Priority=plan.Priority,
                               Job=plan.Job, AllAtOnce=plan.AllAtOnce)
                chunks.append(current)
                count = 0
            placed = plan.NodeAllocation.get(node_id)
            if placed:
                current.NodeAllocation[node_id] = placed
                count += len(placed)
            updates = plan.NodeUpdate.get(node_id)
            if updates:
                current.NodeUpdate[node_id] = updates
                count += len(updates)
        chunks[0].Annotations = plan.Annotations
        return self._submit_chunks(chunks)

    def _submit_chunks(self, chunks: List[Plan]):
        """Submit a chunk sequence through the pipelined planner seam and
        merge the per-chunk results."""
        submit = getattr(self.planner, "submit_plans", None)
        if submit is not None:
            results, new_state = submit(chunks)
        else:  # harness planners: sequential fallback
            results = []
            new_state = None
            for chunk in chunks:
                r, ns = self.planner.submit_plan(chunk)
                results.append(r)
                new_state = ns or new_state

        merged = PlanResult()
        for r in results:
            if r is None:
                return None, new_state  # _process treats None as a retry
            merged.NodeUpdate.update(r.NodeUpdate)
            merged.NodeAllocation.update(r.NodeAllocation)
            merged.RefreshIndex = max(merged.RefreshIndex, r.RefreshIndex)
            merged.AllocIndex = max(merged.AllocIndex, r.AllocIndex)
        return merged, new_state

    def _ready_nodes(self, dcs) -> tuple:
        """ready_nodes_in_dcs, memoized per (state snapshot, DC list, node
        population): the retry loop re-runs _process up to retry_max times
        per eval, and each attempt re-walked every node in state — twice
        the O(cluster) cost for zero new information. The tensor's
        node_version invalidates the memo when the population actually
        moves (covers live-store harnesses, where the state object is
        mutable); only an attached index sees those moves, so unattached
        ones skip the memo."""
        if self.tindex is None or not self.tindex.attached:
            return ready_nodes_in_dcs(self.state, dcs)
        ver = self.tindex.nt.node_version
        key = (self.state, tuple(dcs), ver)
        cached = self._ready_cache
        if cached is not None and cached[0] is key[0] \
                and cached[1] == key[1] and cached[2] == key[2]:
            return cached[3]
        res = ready_nodes_in_dcs(self.state, dcs)
        self._ready_cache = key + (res,)
        return res

    def _compute_job_allocs(self, use_sweep: bool = False) -> None:
        """(reference: system_sched.go:165-216). The tensor-sweep path
        (system_sweep.compute_job_allocs) computes the same diff + emit as
        row math over the node tensor; the exact per-node path below is
        kept for network-ask groups, deregisters, and as the equivalence
        oracle."""
        if use_sweep:
            t0 = time.monotonic()
            system_sweep.compute_job_allocs(self)
            metrics.measure_since(("nomad", "sched", "system", "sweep"), t0)
            metrics.incr_counter(("nomad", "sched", "system", "fast"))
            return
        metrics.incr_counter(("nomad", "sched", "system", "exact"))
        allocs = self.state.allocs_by_job(self.eval.JobID)
        allocs = [a for a in allocs if not a.terminal_status()]
        tainted = tainted_nodes(self.state, allocs)
        diff = diff_system_allocs(self.job, self.nodes, tainted, allocs) \
            if self.job is not None else None
        if diff is None:
            for a in allocs:
                self.plan.append_update(a, AllocDesiredStatusStop,
                                        ALLOC_NOT_NEEDED)
            return

        for tup in diff.stop:
            desc = ALLOC_NODE_TAINTED if tainted.get(tup.Alloc.NodeID) \
                else ALLOC_NOT_NEEDED
            self.plan.append_update(tup.Alloc, AllocDesiredStatusStop, desc)
        # In-place first (non-destructive changes keep the running alloc,
        # reference: system_sched.go computeJobAllocs -> inplaceUpdate);
        # the rest stop + replace on the same node.
        destructive, _ = attempt_inplace_updates(
            self.state, self.plan, self.stack.inner, self.eval.ID, self.ctx,
            diff.update)
        for tup in destructive:
            self.plan.append_update(tup.Alloc, AllocDesiredStatusStop,
                                    ALLOC_UPDATING)
            diff.place.append(tup)

        if not diff.place:
            return
        self._compute_placements(diff.place)

    def _compute_placements(self, place) -> None:
        """(reference: system_sched.go:219-281). Placements group by task
        group and run through the vectorized pinned-node batch select — a
        10k-node system sweep is a few numpy ops, not 10k constraint walks.
        Groups with network asks keep the exact per-node path (port bitmaps
        are host state)."""
        node_by_id = {n.ID: n for n in self.nodes}
        self.ctx.metrics.NodesAvailable = self.node_by_dc

        by_tg: Dict[str, List] = {}
        for tup in place:
            node = node_by_id.get(tup.Alloc.NodeID if tup.Alloc else "")
            if node is None:
                continue
            by_tg.setdefault(tup.TaskGroup.Name, []).append((tup, node))

        for pairs in by_tg.values():
            tg = pairs[0][0].TaskGroup
            options = self.stack.select_batch_on_nodes(
                tg, [node for _, node in pairs])
            if options is None:  # network asks: exact per-node path
                options = [self.stack.select(tup.TaskGroup, node)
                           for tup, node in pairs]
            # One shared metrics snapshot per TG (scoring is done by now;
            # a copy per alloc walks the metric maps P times — the same
            # O(P^2) the generic path's build_placement_allocs avoids).
            # The resource vector is likewise identical for every alloc of
            # a TG: computing it once and pre-seeding the per-instance
            # memo saves a resources_vec walk per alloc in the plan
            # applier, the usage listener, and the optimistic overlay
            # (the memo contract forbids mutation, so sharing is safe).
            shared_metric = None
            shared_vec = None
            for (tup, node), option in zip(pairs, options):
                if option is None:
                    metric = self.failed_tg_allocs.get(tup.TaskGroup.Name)
                    if metric is not None:
                        metric.CoalescedFailures += 1
                    else:
                        self.failed_tg_allocs[tup.TaskGroup.Name] = \
                            self.ctx.metrics.copy()
                    continue
                if shared_metric is None:
                    shared_metric = self.ctx.metrics.copy()
                alloc = Allocation(
                    ID=generate_uuid(),
                    EvalID=self.eval.ID,
                    Name=tup.Name,
                    JobID=self.job.ID,
                    TaskGroup=tup.TaskGroup.Name,
                    Metrics=shared_metric,
                    NodeID=node.ID,
                    TaskResources=option.task_resources,
                    DesiredStatus=AllocDesiredStatusRun,
                    ClientStatus=AllocClientStatusPending,
                )
                if shared_vec is None:
                    shared_vec = alloc_vec(alloc)
                else:
                    alloc._resvec_cache = shared_vec
                self.plan.append_alloc(alloc)
