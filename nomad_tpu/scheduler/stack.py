"""Placement stacks backed by the XLA kernels (reference: scheduler/stack.go).

The reference wires per-node iterator chains; here a stack assembles device
inputs (eligibility masks from the class-constraint compiler, usage deltas
from the plan under construction, anti-affinity counts) and runs ONE
place_batch program for all of an evaluation's placements. Network/port
assignment — inherently sequential, string/random heavy — happens host-side
for the chosen winners only, mirroring the reference's behavior of only
network-checking nodes that survive ranking (reference: rank.go:150-240).
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nomad_tpu.structs import (
    Allocation,
    Job,
    NetworkIndex,
    Node,
    Resources,
    TaskGroup,
)
from nomad_tpu.structs.structs import (
    AllocClientStatusPending,
    AllocDesiredStatusRun,
    ConstraintDistinctHosts,
    JobTypeBatch,
    generate_uuid,
)
from nomad_tpu.tensor import ClassEligibility, TensorIndex, alloc_vec, resources_vec
from nomad_tpu.tensor.node_table import DIM_NAMES, RES_DIMS

from . import kernels
from .context import EvalContext
from .util import task_group_constraints

# Anti-affinity penalties (reference: stack.go:10-19)
SERVICE_JOB_ANTI_AFFINITY_PENALTY = 10.0
BATCH_JOB_ANTI_AFFINITY_PENALTY = 5.0

_NOISE_SCALE = 1e-3


class _DeviceInputCache:
    """Content-addressed host->device transfer cache.

    On a remote-attached TPU every `jnp.asarray(numpy)` pays a fixed RTT; a
    scheduling storm re-uploads the SAME eligibility masks, demand vectors,
    and zero count/host arrays for every eval. Keying on the exact bytes
    (not an identity or semantic key) makes the cache safe under any caller:
    equal content -> same immutable device buffer. Bounded LRU."""

    def __init__(self, cap: int = 256):
        self.cap = cap
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()
        self._lock = threading.Lock()

    def get(self, arr: np.ndarray, sharding=None):
        import jax
        import jax.numpy as jnp

        arr = np.ascontiguousarray(arr)
        # 128-bit content digest as the key: exact-bytes keys would retain a
        # full host copy of every cached array (MBs at large node counts).
        # The sharding is part of the key — the same bytes placed on a mesh
        # and on a single device are different buffers.
        key = (hashlib.blake2b(arr.tobytes(), digest_size=16).digest(),
               arr.dtype.str, arr.shape, sharding)
        with self._lock:
            dev = self._entries.get(key)
            if dev is not None:
                self._entries.move_to_end(key)
                return dev
        dev = (jax.device_put(arr, sharding) if sharding is not None
               else jnp.asarray(arr))
        with self._lock:
            self._entries[key] = dev
            while len(self._entries) > self.cap:
                self._entries.popitem(last=False)
        return dev


_dev_cache = _DeviceInputCache()


def device_input(arr: np.ndarray, sharding=None):
    """Public handle on the content-addressed transfer cache for windowed
    callers outside the stack (the pipelined drain's compaction inputs are
    byte-identical across a storm's windows, so they upload once)."""
    return _dev_cache.get(arr, sharding)


class WindowAccumulator:
    """Deferred window-usage accumulator shared by every eval of a window.

    The chain-replay usage exists ONLY for exhaustion diagnostics
    (_note_exhaustion diffs against the usage the kernel actually saw), so
    an all-placed storm window must not pay a scatter per eval for an
    array nothing reads. Placements queue as (rows, demand-vec) batches;
    the first exhaustion materializes everything queued so far with ONE
    np.add.at — the same values the per-eval eager scatters produced,
    since adds commute and recs are processed in chain order."""

    __slots__ = ("n_rows", "_rows", "_vecs", "_usage")

    def __init__(self, n_rows: int):
        self.n_rows = n_rows
        self._rows: List[np.ndarray] = []
        self._vecs: List[np.ndarray] = []
        self._usage: Optional[np.ndarray] = None

    def add(self, rows: np.ndarray, vecs: np.ndarray) -> None:
        if self._usage is not None:
            np.add.at(self._usage, rows, vecs)
        else:
            self._rows.append(rows)
            self._vecs.append(vecs)

    def usage(self) -> np.ndarray:
        if self._usage is None:
            self._usage = np.zeros((self.n_rows, RES_DIMS), dtype=np.float32)
        if self._rows:
            np.add.at(self._usage,
                      np.concatenate(self._rows),
                      np.concatenate(self._vecs))
            self._rows.clear()
            self._vecs.clear()
        return self._usage

# Row-steps (node rows x padded placements) under which an eval places via
# the numpy mirror (kernels.place_batch_host) instead of a device dispatch.
# A device readback costs a fixed ~100ms sync on remote-attached TPUs; the
# host kernel's incremental same-demand caching covers this budget in
# ~10-30ms (one full table pass per unique (tg, demand) + O(1) patches per
# placement), and a lone 50-placement eval on a 1k-node table in ~2ms.
# Deep storm windows on big tables stay on the device chain.
HOST_ROW_STEP_BUDGET = 1 << 23

# Candidate-table budget for the keyed kernel (keys x candidates x devices).
# Within it, every device dispatch uses kernels.place_batch_keyed; beyond it
# (degenerate many-key mega-windows) the monolithic scan kernels take over.
KEYED_CAND_BUDGET = 1 << 17


@dataclass
class SelectedOption:
    """A chosen placement (the reference's RankedNode, rank.go:12-45)."""

    node: Node
    score: float
    task_resources: Dict[str, Resources] = field(default_factory=dict)


@dataclass
class PreparedBatch:
    """Host-assembled device inputs for one evaluation's placements.

    Split out of select_batch so the pipelined worker can dispatch many
    evals' kernels chained on device usage before any readback."""

    tgs: List[TaskGroup]
    tg_index: Dict[str, int]      # tg name -> row in tg_masks/tg_demands
    tg_masks: np.ndarray          # [U, N] bool eligibility per unique TG
    tg_demands: np.ndarray        # [U, R]
    demands: np.ndarray           # [P_pad, R]
    tg_ids: np.ndarray            # [P_pad] int32
    valid: np.ndarray             # [P_pad] bool
    p_pad: int
    evict_rows: np.ndarray        # in-plan eviction scatter
    evict_vecs: np.ndarray
    job_counts: np.ndarray        # [N] int32 anti-affinity base
    distinct: bool
    penalty: float
    noise_vec: np.ndarray         # [N] f32 tie-break jitter
    tg_mask_sums: np.ndarray      # [U] eligible-node count per unique TG
    cand_sum: int                 # candidate node count (metrics base)
    # Real (non-padding) placement count — REQUIRED: it bounds the keyed
    # kernel's candidate sets, and an understated value would silently
    # trim true winners out of the candidate table.
    n_valid: int
    # True when any task of any placed group asks for network resources:
    # those evals keep the exact per-placement build (ports are sequential
    # host state); everything else takes the vectorized window build.
    has_network_asks: bool = False
    # Memo of the resolved device-side inputs for the unmodified first
    # dispatch (no bans/placed overlays): a (kernel-kind, tuple) pair so a
    # window re-dispatching an identical prep skips the content-hash
    # lookups entirely.
    dev_inputs: Optional[tuple] = None
    # Lazily built per-unique-TG (task_resources, resource-vec) templates
    # for the vectorized build: every alloc of a TG carries value-identical
    # task resources, so the window shares ONE frozen dict + Resources set
    # per TG instead of copying per alloc (same value-frozen contract as
    # alloc._resvec_cache — anything that changes resources replaces the
    # objects).
    tr_templates: Optional[dict] = None


def _pad_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


def score_fit_rows(usage2: np.ndarray, score_cap: np.ndarray) -> np.ndarray:
    """BestFit-v3 host-side, in float64 like the Go reference
    (funcs.go:102-137): 20 - 10^freeCpuPct - 10^freeMemPct, clamped [0,18],
    NaN/Inf division edges sanitized. THE single host-side definition —
    select_on_node and the system batch path both call it so the formula
    cannot drift between them (the device twin is kernels._score).

    usage2 [K, 2]: proposed cpu/mem including reserved; score_cap [K, 2]."""
    with np.errstate(divide="ignore", invalid="ignore"):
        free_pct = 1.0 - (usage2.astype(np.float64)
                          / score_cap.astype(np.float64))
        total = (np.power(10.0, free_pct[:, 0])
                 + np.power(10.0, free_pct[:, 1]))
    scores = np.clip(20.0 - total, 0.0, 18.0)
    return np.nan_to_num(scores, nan=0.0, posinf=18.0, neginf=0.0)


def fit_lacking(cap: np.ndarray, usage: np.ndarray,
                demand: np.ndarray) -> np.ndarray:
    """Per-dimension exhaustion mask in float64 (reference AllocsFit,
    funcs.go:44-100): True where free capacity can't cover the demand.
    Shared by the single-node and batched host fit checks."""
    return ((cap.astype(np.float64) - usage.astype(np.float64))
            < demand.astype(np.float64))


def _mesh_shardings(nt):
    """(node_sh, mask_sh, rep_sh) for the table's serving mesh, or Nones
    for single-device serving. Shared by every kernel launch path so the
    fused and per-eval launches can never diverge on sharding."""
    mesh = nt.mesh
    if mesh is None:
        return None, None, None
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    return (NamedSharding(mesh, P(axis)),
            NamedSharding(mesh, P(None, axis)),
            NamedSharding(mesh, P()))


def _chain_to_device(usage, node_sh):
    """Rejoin the device chain after a host-placed window: one async
    host->device upload (uploads don't pay the sync RTT readbacks do)."""
    if not isinstance(usage, np.ndarray):
        return usage
    import jax
    import jax.numpy as jnp

    return jnp.asarray(usage) if node_sh is None else \
        jax.device_put(usage, node_sh)


def make_noise_vec(n_rows: int, rng: random.Random) -> np.ndarray:
    """Per-node tie-break jitter (the load-spreading analogue of the
    reference's node shuffle, stack.go:120-133)."""
    return np.asarray(
        np.random.default_rng(rng.randrange(2**31)).random(n_rows),
        dtype=np.float32) * _NOISE_SCALE


class GenericStack:
    """Stack for service/batch jobs (reference: stack.go:35-173)."""

    def __init__(self, ctx: EvalContext, tindex: TensorIndex, batch: bool,
                 rng: Optional[random.Random] = None,
                 columnar: bool = True):
        self.ctx = ctx
        self.tindex = tindex
        self.batch = batch
        self.rng = rng or random.Random()
        # Columnar service commits: the all-placed window build attaches a
        # SweepBatch descriptor (kind="service") so the plan replicates as
        # ONE ApplySweepBatch raft entry + SweepSegment scatter instead of
        # per-object upserts. False keeps the per-object commit (the
        # equivalence oracle and the bench A/B's object side).
        self.columnar = columnar
        self.job: Optional[Job] = None
        self.elig: Optional[ClassEligibility] = None
        self._cand_mask: Optional[np.ndarray] = None
        self._nodes_by_id: Dict[str, Node] = {}
        self._netidx_cache: Dict[str, NetworkIndex] = {}

    # ------------------------------------------------------------- wiring
    def set_job(self, job: Job) -> None:
        self.job = job
        self.elig = ClassEligibility(self.tindex.nt,
                                     list(self._nodes_by_id.values()) or [])

    def set_nodes(self, nodes: Sequence[Node]) -> None:
        nt = self.tindex.nt
        self._nodes_by_id = {n.ID: n for n in nodes}
        mask = np.zeros(nt.n_rows, dtype=bool)
        for n in nodes:
            row = nt.row_of.get(n.ID)
            if row is not None:
                mask[row] = True
        self._cand_mask = mask
        # Rebuild the eligibility cache against the new node set.
        if self.job is not None:
            self.elig = ClassEligibility(nt, nodes)

    def adopt_nodes(self, nodes_by_id: Dict[str, Node], cand_mask: np.ndarray,
                    elig: ClassEligibility) -> None:
        """Share a candidate set + eligibility cache built once for a whole
        scheduling window (pipelined worker): evals against the same snapshot
        need not re-scan the node list per eval."""
        self._nodes_by_id = nodes_by_id
        self._cand_mask = cand_mask
        self.elig = elig

    def adopt_shared(self, job: Job, elig: ClassEligibility) -> None:
        """Wire the stack for a tensor-sweep evaluation: the job plus the
        table-wide shared eligibility (TensorIndex.shared_elig), WITHOUT
        set_nodes/set_job's O(cluster) node walk. The candidate set is the
        sweep's own ready/DC row mask, so _nodes_by_id/_cand_mask stay
        empty — only the mask-based paths (sweep feasibility,
        select_on_node for in-place updates) are valid on a stack wired
        this way."""
        self.job = job
        self.elig = elig

    # ---------------------------------------------------------- selection
    def select(self, tg: TaskGroup) -> Tuple[Optional[SelectedOption], Resources]:
        opts = self.select_batch([tg])
        size = task_group_constraints(tg).size
        return opts[0], size

    def select_batch(self, tgs: Sequence[TaskGroup]
                     ) -> List[Optional[SelectedOption]]:
        """Place a sequence of task-group instances in order, each seeing the
        previous placements' usage (reference sequencing: context.go:109-140),
        as one lax.scan on device."""
        assert self.job is not None and self.elig is not None
        if self._cand_mask is None or not self._nodes_by_id:
            self.ctx.metrics.NodesEvaluated = 0
            return [None] * len(tgs)

        t0 = time.monotonic()
        nt = self.tindex.nt
        prep = self.prepare_batch(tgs)

        banned_extra = np.zeros(nt.n_rows, dtype=bool)
        results: List[Optional[SelectedOption]] = [None] * len(tgs)
        remaining = list(range(len(tgs)))
        # Effects of winners from earlier attempts of THIS call: their usage,
        # anti-affinity counts, and distinct-hosts occupancy must be visible
        # to re-run placements (they aren't in ctx.plan yet).
        placed_usage = np.zeros((nt.n_rows, RES_DIMS), dtype=np.float32)
        placed_counts = np.zeros(nt.n_rows, dtype=np.int32)
        placed_hosts = np.zeros(nt.n_rows, dtype=bool)

        # The port-collision retry loop runs at most a handful of times: a
        # winner failing host-side network assignment is masked and the
        # remaining placements re-run.
        # Small evals place host-side: a device readback pays a fixed
        # ~100ms RTT on remote-attached TPUs, far more than numpy takes
        # over a modest rows x placements product. Storms and huge evals
        # keep the device path (the budget keeps host work bounded).
        # allow_host_select mirrors ServerConfig.host_placement so that
        # host_placement=False forces the device kernel on the slow path
        # too (the multichip dry run proves the SPMD path end to end).
        use_host = (self.tindex.allow_host_select
                    and nt.n_rows * prep.p_pad <= HOST_ROW_STEP_BUDGET)
        for _attempt in range(8):
            if not remaining:
                break
            if use_host:
                res = self.dispatch_host(prep, banned=banned_extra,
                                         placed_usage=placed_usage,
                                         placed_counts=placed_counts,
                                         placed_hosts=placed_hosts,
                                         keep=remaining)
            else:
                res = self.dispatch(prep, banned=banned_extra,
                                    placed_usage=placed_usage,
                                    placed_counts=placed_counts,
                                    placed_hosts=placed_hosts,
                                    keep=remaining)
            # ONE device->host transfer: on remote-attached TPUs a readback
            # pays a fixed RTT, so results come back packed (free for the
            # host path — already numpy).
            packed = np.asarray(res.packed)
            failed_rows, remaining = self.collect(
                prep, packed, results, remaining,
                placed_usage, placed_counts, placed_hosts)
            if not failed_rows:
                break
            for row in failed_rows:
                banned_extra[row] = True

        self.ctx.metrics.AllocationTime = int((time.monotonic() - t0) * 1e9)
        return results

    def prepare_batch(self, tgs: Sequence[TaskGroup],
                      noise_vec: Optional[np.ndarray] = None) -> PreparedBatch:
        """Assemble the host-side device inputs for one eval's placements.

        noise_vec lets a windowed caller share one tie-break jitter vector
        across many evals so its upload is paid once per window, not per
        eval (the reference's analogue is one node shuffle per scheduling
        pass, stack.go:120-133 — per-eval freshness is not load-bearing)."""
        assert self.job is not None and self.elig is not None
        nt = self.tindex.nt
        job = self.job

        # Per-unique-TG eligibility masks and demand vectors.
        unique_tgs: List[TaskGroup] = []
        tg_index: Dict[str, int] = {}
        for tg in tgs:
            if tg.Name not in tg_index:
                tg_index[tg.Name] = len(unique_tgs)
                unique_tgs.append(tg)

        job_mask, _, _ = self.elig.job_mask(job.ID, job.Constraints)
        tg_masks = np.zeros((len(unique_tgs), nt.n_rows), dtype=bool)
        tg_demands = np.zeros((len(unique_tgs), RES_DIMS), dtype=np.float32)
        for i, tg in enumerate(unique_tgs):
            cons = task_group_constraints(tg)
            m, _, _ = self.elig.tg_mask(job.ID, tg.Name, cons.constraints,
                                        cons.drivers)
            tg_masks[i] = self._cand_mask & job_mask & m
            tg_demands[i] = resources_vec(cons.size)

        # Plan deltas: usage scatter for in-plan evictions; anti-affinity and
        # distinct-hosts state from proposed allocs of this job.
        evict_rows, evict_vecs = self._eviction_deltas()
        job_counts = self._job_alloc_counts()
        distinct = any(c.Operand == ConstraintDistinctHosts
                       for c in job.Constraints)
        penalty = (BATCH_JOB_ANTI_AFFINITY_PENALTY if self.batch
                   else SERVICE_JOB_ANTI_AFFINITY_PENALTY)

        p_pad = _pad_pow2(len(tgs))
        demands = np.zeros((p_pad, RES_DIMS), dtype=np.float32)
        tg_ids = np.zeros(p_pad, dtype=np.int32)
        valid = np.zeros(p_pad, dtype=bool)
        for p, tg in enumerate(tgs):
            ti = tg_index[tg.Name]
            demands[p] = tg_demands[ti]
            tg_ids[p] = ti
            valid[p] = True

        if noise_vec is None:
            noise_vec = make_noise_vec(nt.n_rows, self.rng)

        return PreparedBatch(
            tgs=list(tgs), tg_index=tg_index, tg_masks=tg_masks,
            tg_demands=tg_demands, demands=demands, tg_ids=tg_ids,
            valid=valid, p_pad=p_pad, evict_rows=evict_rows,
            evict_vecs=evict_vecs, job_counts=job_counts, distinct=distinct,
            penalty=penalty, noise_vec=noise_vec,
            tg_mask_sums=tg_masks.sum(axis=1),
            cand_sum=int(self._cand_mask.sum()), n_valid=len(tgs),
            has_network_asks=any(
                t.Resources is not None and t.Resources.Networks
                for tg in unique_tgs for t in tg.Tasks))

    def _device_kind(self, prep: PreparedBatch, n_valid: int) -> str:
        """Pick the device kernel: the keyed-candidate kernel whenever its
        candidate table stays within budget (always, in practice — the
        bound only trips on degenerate many-key mega-windows), else the
        monolithic scan. Keyed is bit-identical and does one score pass
        per unique task group instead of one per placement; on a sharded
        mesh it runs the shard-local pipeline — ZERO collectives per
        window, only winner-candidate rows cross devices (kernels.py:
        'shard-local mesh pipeline') — vs the scan's 2 per placement."""
        nt = self.tindex.nt
        n_dev = nt.mesh.devices.size if nt.mesh is not None else 1
        n_keys = prep.tg_masks.shape[0]
        if n_keys * kernels.keyed_cand_count(n_valid) * n_dev \
                <= KEYED_CAND_BUDGET:
            return "keyed"
        return "scan"

    def _launch_device(self, d, usage, kind: str, dev: tuple, n_valid: int):
        nt = self.tindex.nt
        if kind == "keyed":
            mesh = nt.mesh
            if mesh is not None and mesh.devices.size == 1:
                mesh = None  # plain jit; no shard_map needed
            return kernels.place_batch_keyed(
                mesh, d["capacity"], d["score_cap"], usage, *dev,
                n_valid=n_valid)
        if isinstance(usage, kernels.MeshChain):
            # Degenerate mega-window routed to the monolithic scan: fold
            # the chain's pending ring into the sharded usage first.
            usage = usage.materialize()
        return kernels.place_batch(d["capacity"], d["score_cap"], usage,
                                   *dev)

    def _assemble_dev(self, kind: str, prep: PreparedBatch,
                      masks: np.ndarray, counts: np.ndarray,
                      tg_ids: np.ndarray, valid: np.ndarray,
                      hosts: np.ndarray, reset: Optional[np.ndarray],
                      demands: Optional[np.ndarray] = None) -> tuple:
        """THE one assembly of the positional device-input tuple shared by
        dispatch and dispatch_multi: keyed kernels take tg_demands plus a
        reset vector; scan kernels take per-placement demands (reset only
        for the multi-eval scan). Every host array goes through the
        content-addressed transfer cache, so a storm's byte-identical
        masks/demands/zero arrays pay ZERO host->device puts per eval
        (each put is a full RTT on remote-attached TPUs)."""
        node_sh, mask_sh, rep_sh = _mesh_shardings(self.tindex.nt)
        mid = prep.tg_demands if kind == "keyed" else demands
        dev = (_dev_cache.get(masks, mask_sh),
               _dev_cache.get(counts, node_sh),
               _dev_cache.get(mid, rep_sh),
               _dev_cache.get(tg_ids, rep_sh),
               _dev_cache.get(valid, rep_sh),
               _dev_cache.get(prep.noise_vec, node_sh),
               _dev_cache.get(np.float32(prep.penalty), rep_sh),
               _dev_cache.get(np.asarray(prep.distinct), rep_sh),
               _dev_cache.get(hosts, node_sh))
        if reset is not None:
            dev = dev + (_dev_cache.get(reset, rep_sh),)
        return dev

    def dispatch(self, prep: PreparedBatch, usage_override=None,
                 banned: Optional[np.ndarray] = None,
                 placed_usage: Optional[np.ndarray] = None,
                 placed_counts: Optional[np.ndarray] = None,
                 placed_hosts: Optional[np.ndarray] = None,
                 keep: Optional[Sequence[int]] = None,
                 tables: Optional[dict] = None):
        """Launch the placement kernel; returns the device-side result without
        forcing a readback. usage_override lets a pipelined caller chain the
        previous eval's usage_after array device-side; tables lets a windowed
        caller fetch the node table's device arrays ONCE per window instead of
        paying the dirty-row refresh per eval."""
        nt = self.tindex.nt
        d = tables if tables is not None else nt.device_arrays()
        # Mesh serving: node-axis inputs shard over the mesh like the table
        # arrays; per-placement inputs replicate. The keyed kernel runs the
        # explicit shard_map program; the scan fallback relies on XLA's
        # SPMD partitioner.
        node_sh, _, _ = _mesh_shardings(nt)
        usage = usage_override if usage_override is not None else d["usage"]
        usage = _chain_to_device(usage, node_sh)
        if isinstance(usage, kernels.MeshChain) and (
                len(prep.evict_rows)
                or (placed_usage is not None and placed_usage.any())):
            # Eviction/overlay math needs a real array; fold the chain's
            # pending winner ring back into the sharded usage first (one
            # scatter dispatch, stays on the mesh).
            usage = usage.materialize()
        if len(prep.evict_rows):
            usage = usage.at[prep.evict_rows].add(-prep.evict_vecs)
        if placed_usage is not None and placed_usage.any():
            # Host accumulator stays numpy (uncommitted): the add places it
            # with `usage`, sharded or not.
            usage = usage + placed_usage

        pristine = (banned is None and placed_usage is None
                    and placed_counts is None and placed_hosts is None
                    and keep is None)
        if pristine and prep.dev_inputs is not None:
            kind, dev = prep.dev_inputs
            return self._launch_device(d, usage, kind, dev, prep.n_valid)

        masks = prep.tg_masks
        if banned is not None and banned.any():
            masks = masks & ~banned[None, :]
        sel_valid = prep.valid
        if keep is not None:
            k = np.zeros(prep.p_pad, dtype=bool)
            k[list(keep)] = True
            sel_valid = sel_valid & k
        counts_now = prep.job_counts
        if placed_counts is not None:
            counts_now = counts_now + placed_counts
        if prep.distinct:
            hosts = counts_now > 0
            if placed_hosts is not None:
                hosts = hosts | placed_hosts
        else:
            hosts = np.zeros(nt.n_rows, dtype=bool)

        n_valid = int(sel_valid.sum()) if keep is not None else prep.n_valid
        kind = self._device_kind(prep, n_valid)
        dev = self._assemble_dev(
            kind, prep, masks, counts_now, prep.tg_ids, sel_valid, hosts,
            reset=(np.zeros(prep.p_pad, dtype=bool) if kind == "keyed"
                   else None),
            demands=prep.demands)
        if pristine:
            prep.dev_inputs = (kind, dev)
        return self._launch_device(d, usage, kind, dev, n_valid)

    def dispatch_multi(self, prep: PreparedBatch, n_evals: int,
                       usage_override=None, tables: Optional[dict] = None):
        """Launch ONE kernel for n_evals same-shaped evaluations sharing
        this PreparedBatch (a storm window after prep dedup): placements
        are concatenated with per-eval resets of the job-local state, so
        the window costs one host->device dispatch and one readback
        instead of one per eval (see kernels.place_batch_multi). Only
        legal for the pristine shared-prep case: no prior allocs, no
        overlays (the fast path's _prep_sig guarantees this).

        Returns (result, e_pad): result.packed is [e_pad * p_pad, 3];
        caller slices per eval. The eval axis pads to a power of two so
        jit compiles one program per bucket, not per window fill."""
        nt = self.tindex.nt
        d = tables if tables is not None else nt.device_arrays()
        node_sh, _, _ = _mesh_shardings(nt)
        usage = usage_override if usage_override is not None else d["usage"]
        usage = _chain_to_device(usage, node_sh)

        e_pad = _pad_pow2(n_evals, floor=4)
        p = prep.p_pad
        # Tiled per-placement inputs: byte-identical across a storm's
        # windows, so the content-addressed cache uploads them once.
        tg_ids = np.tile(prep.tg_ids, e_pad)
        valid = np.tile(prep.valid, e_pad)
        valid[n_evals * p:] = False  # padding evals place nothing
        reset = np.zeros(e_pad * p, dtype=bool)
        reset[::p] = True
        hosts = np.zeros(nt.n_rows, dtype=bool)

        n_valid = n_evals * prep.n_valid
        kind = self._device_kind(prep, n_valid)
        dev = self._assemble_dev(
            kind, prep, prep.tg_masks, prep.job_counts, tg_ids, valid,
            hosts, reset=reset,
            demands=(None if kind == "keyed"
                     else np.tile(prep.demands, (e_pad, 1))))
        if kind == "keyed":
            res = self._launch_device(d, usage, kind, dev, n_valid)
        else:
            if isinstance(usage, kernels.MeshChain):
                usage = usage.materialize()
            res = kernels.place_batch_multi(d["capacity"], d["score_cap"],
                                            usage, *dev)
        return res, e_pad

    def dispatch_host(self, prep: PreparedBatch, usage_override=None,
                      banned: Optional[np.ndarray] = None,
                      placed_usage: Optional[np.ndarray] = None,
                      placed_counts: Optional[np.ndarray] = None,
                      placed_hosts: Optional[np.ndarray] = None,
                      keep: Optional[Sequence[int]] = None):
        """Host-side mirror of dispatch() for shallow windows: every host
        sync on a remote-attached TPU costs a fixed ~100ms round trip, so
        a near-idle broker's evals place faster as numpy vector ops than
        as a device dispatch + readback (kernels.place_batch_host). The
        result's packed array is already host-side; the pipelined drain
        recognizes that and skips the device RTT entirely."""
        nt = self.tindex.nt
        if usage_override is not None:
            usage = np.asarray(usage_override, np.float32)
            with nt._lock:
                capacity = nt.capacity.copy()
                score_cap = nt.score_cap.copy()
        else:
            # Snapshot under the table lock: alloc commits mutate usage
            # rows in place, and a lock-free copy could capture a torn row
            # (cpu updated, mem not) — the same hazard snapshot_rows
            # documents. The device path gets this via device_arrays().
            with nt._lock:
                usage = nt.usage.astype(np.float32, copy=True)
                capacity = nt.capacity.copy()
                score_cap = nt.score_cap.copy()
        if len(prep.evict_rows):
            usage = usage.copy()
            np.add.at(usage, prep.evict_rows, -prep.evict_vecs)
        if placed_usage is not None and placed_usage.any():
            usage = usage + placed_usage

        masks = prep.tg_masks
        if banned is not None and banned.any():
            masks = masks & ~banned[None, :]
        sel_valid = prep.valid
        if keep is not None:
            k = np.zeros(prep.p_pad, dtype=bool)
            k[list(keep)] = True
            sel_valid = sel_valid & k
        counts_now = prep.job_counts
        if placed_counts is not None:
            counts_now = counts_now + placed_counts
        if prep.distinct:
            hosts = counts_now > 0
            if placed_hosts is not None:
                hosts = hosts | placed_hosts
        else:
            hosts = np.zeros(nt.n_rows, dtype=bool)

        return kernels.place_batch_host(
            capacity, score_cap, usage, masks, counts_now,
            prep.demands, prep.tg_ids, sel_valid, prep.noise_vec,
            prep.penalty, prep.distinct, hosts)

    def collect(self, prep: PreparedBatch, packed: np.ndarray,
                results: List[Optional[SelectedOption]],
                remaining: Sequence[int],
                placed_usage: np.ndarray, placed_counts: np.ndarray,
                placed_hosts: np.ndarray) -> Tuple[set, List[int]]:
        """Materialize winners host-side: node lookup, port assignment,
        metrics. Returns (rows that failed network assignment, placement
        indexes to re-run). Mutates results and the placed_* accumulators."""
        nt = self.tindex.nt
        chosen = packed[:, 0].astype(np.int32)
        scores = packed[:, 1]
        n_feasible = packed[:, 2].astype(np.int32)

        # Hot loop: a storm window runs this for thousands of placements, so
        # locals are hoisted and the accumulator writes are batched into one
        # np.add.at per array after the loop.
        node_of = nt.node_of
        nodes_by_id = self._nodes_by_id
        tg_index = prep.tg_index
        tgs = prep.tgs
        metrics_ = self.ctx.metrics
        score_node = metrics_.score_node
        chosen_list = chosen.tolist()
        scores_list = scores.tolist()

        failed_rows: set = set()
        next_remaining: List[int] = []
        placed_ps: List[int] = []
        placed_rows: List[int] = []
        last_fill = None

        def flush_placed():
            # Exhaustion diagnostics read placed_usage, so the batched
            # accumulator writes must land before any _note_exhaustion.
            if placed_rows:
                rows_arr = np.asarray(placed_rows, dtype=np.int64)
                np.add.at(placed_usage, rows_arr, prep.demands[placed_ps])
                np.add.at(placed_counts, rows_arr, 1)
                placed_hosts[rows_arr] = True
                placed_ps.clear()
                placed_rows.clear()

        for p in remaining:
            row = chosen_list[p]
            ti = tg_index[tgs[p].Name]
            last_fill = (ti, int(n_feasible[p]))
            if row < 0:
                self._fill_metrics(prep, ti, int(n_feasible[p]))
                flush_placed()
                self._note_exhaustion(tgs[p], prep.tg_masks[ti],
                                      prep.tg_demands[ti], prep, placed_usage)
                continue  # infeasible: stays None
            node = nodes_by_id.get(node_of[row])
            if node is None:
                failed_rows.add(row)
                next_remaining.append(p)
                continue
            option = self._assign_networks(node, tgs[p], scores_list[p])
            if option is None:
                failed_rows.add(row)
                next_remaining.append(p)
                continue
            results[p] = option
            score_node(node, "binpack", scores_list[p])
            placed_ps.append(p)
            placed_rows.append(row)
        if last_fill is not None:
            # Metric fields are overwritten per placement, so only the last
            # one's values survive the reference loop — reproduce that state
            # with a single fill.
            self._fill_metrics(prep, *last_fill)
        flush_placed()
        return failed_rows, next_remaining

    def _tg_template(self, prep: PreparedBatch, ti: int) -> tuple:
        """(task_resources, resource-vec) for one unique TG, built once per
        PreparedBatch and shared by every alloc the window places for it.
        Only legal with no network asks anywhere in the group — ports are
        per-alloc offers. The shared dict/Resources are value-frozen by the
        same contract as alloc._resvec_cache (every consumer reads; a
        change replaces the objects)."""
        templates = prep.tr_templates
        if templates is None:
            templates = prep.tr_templates = {}
        ent = templates.get(ti)
        if ent is None:
            # tg_index maps name -> ti; the TG object is the first
            # placement of this ti (prep.tgs is in placement order).
            tg = next(t for t in prep.tgs if prep.tg_index[t.Name] == ti)
            tr = {}
            vec = np.zeros(RES_DIMS, dtype=np.float32)
            for task in tg.Tasks:
                r = (task.Resources.copy() if task.Resources is not None
                     else Resources())
                tr[task.Name] = r
                vec += resources_vec(r)
            ent = templates[ti] = (tr, vec)
        return ent

    def _collect_build_all_placed(self, prep: PreparedBatch, cr,
                                  eval_id: str, job: Job, place, plan,
                                  acc: "WindowAccumulator") -> bool:
        """Vectorized build for the storm case: every placement found a
        row and no group asks for networks. One fancy-index gather maps
        chosen rows to node IDs, scores land in the metrics dict via one
        zip pass, the window-usage contribution queues as one batch, and
        allocs stamp from per-TG frozen template Allocations (the sweep
        path's __dict__-clone trick) instead of running the 20-field
        dataclass constructor per winner.

        The winner rows stay COLUMNAR past the build: a SweepBatch
        descriptor (kind="service") rides the plan so the applier bulk-
        verifies it as one vector op, replicates it as one ApplySweepBatch
        raft entry, and the store scatter-applies it as a SweepSegment —
        the service window never explodes into per-object upserts. Rows
        that take the exact path today (failed placements, network asks,
        vanished nodes) never reach this build, so the descriptor always
        covers the whole plan."""
        from .system_sweep import SweepBatch

        nt = self.tindex.nt
        n = len(place)
        rows = cr.chosen[:n]
        id_arr = nt.node_id_array()
        ids = id_arr[rows]
        nodes_by_id = self._nodes_by_id
        ids_list = ids.tolist()
        for nid in set(ids_list):
            # Node vanished mid-window (row freed/reused): exact path owns
            # it — identical outcome to the per-placement lookup failing.
            if nid is None or nid not in nodes_by_id:
                return False

        metrics_ = self.ctx.metrics
        scores_list = cr.scores[:n].tolist()
        Scores = metrics_.Scores
        for nid, s in zip(ids_list, scores_list):
            Scores[f"{nid}.binpack"] = s
        tg_index = prep.tg_index
        tgs = prep.tgs
        self._fill_metrics(prep, tg_index[tgs[n - 1].Name], cr.nf_last)
        rows64 = rows.astype(np.int64, copy=False)
        acc.add(rows64, prep.demands[:n])

        # Scoring is final now: one immutable metric snapshot shared by
        # every placed alloc (reference: alloc.Metrics). Templates are
        # per-CALL (eval_id/metrics are per-eval) but their task-resource
        # dict + vector come from the shared prep memo.
        shared_metric = metrics_.copy()
        append_alloc = plan.append_alloc
        templates: List[Allocation] = []
        tpl_dicts: List[dict] = []
        tpl_of: Dict[int, int] = {}
        alloc_ids_l: List[str] = []
        names_l: List[str] = []
        alloc_tg = np.empty(n, dtype=np.int64)
        new = object.__new__
        cls = Allocation
        for p, tup in enumerate(place):
            tg = tgs[p]
            ti = tg_index[tg.Name]
            k = tpl_of.get(ti)
            if k is None:
                tr, vec = self._tg_template(prep, ti)
                template = Allocation(
                    EvalID=eval_id,
                    JobID=job.ID,
                    TaskGroup=tg.Name,
                    TaskResources=tr,
                    Metrics=shared_metric,
                    DesiredStatus=AllocDesiredStatusRun,
                    ClientStatus=AllocClientStatusPending,
                )
                template._resvec_cache = vec
                k = tpl_of[ti] = len(templates)
                templates.append(template)
                tpl_dicts.append(template.__dict__)
            alloc = new(cls)
            alloc.__dict__ = dict(tpl_dicts[k])
            alloc.ID = generate_uuid()
            alloc.Name = tup.Name
            alloc.NodeID = ids_list[p]
            alloc.Services = {}
            alloc.TaskStates = {}
            alloc_ids_l.append(alloc.ID)
            names_l.append(tup.Name)
            alloc_tg[p] = k
            append_alloc(alloc)

        if not self.columnar:
            return True
        # Columnar descriptor: unique placed rows with summed demand, plus
        # the per-alloc columns sorted into row order so chunk slices stay
        # contiguous (same layout the system sweep emits). The delta uses
        # the template resource vectors — exactly what alloc_vec() yields
        # for every stamped clone, so the applier's bulk verify and the
        # optimistic overlay account the same bytes the object path would.
        ur, inv = np.unique(rows64, return_inverse=True)
        tpl_vecs = np.stack([t._resvec_cache for t in templates])
        delta = np.zeros((len(ur), RES_DIMS), dtype=np.float32)
        np.add.at(delta, inv, tpl_vecs[alloc_tg])
        order = np.argsort(rows64, kind="stable")
        counts = np.bincount(inv, minlength=len(ur)).astype(np.int64)
        starts = np.concatenate([np.zeros(1, dtype=np.int64),
                                 np.cumsum(counts, dtype=np.int64)])
        plan._sweep = SweepBatch(
            rows=ur, node_ids=id_arr[ur].tolist(), delta=delta,
            epoch=nt.row_epoch, n_rows=nt.n_rows,
            counts=counts, starts=starts,
            alloc_ids=np.asarray(alloc_ids_l, dtype=object)[order].tolist(),
            alloc_names=np.asarray(names_l, dtype=object)[order].tolist(),
            alloc_tg=alloc_tg[order].tolist(),
            templates=templates, kind="service")
        return True

    def collect_build(self, prep: PreparedBatch, cr,
                      eval_id: str, job: Job, place,
                      plan, failed_tg_allocs,
                      acc: "WindowAccumulator") -> bool:
        """Fused collect + build_placement_allocs for the pipelined fast
        path: ONE pass from the compacted kernel output (CompactResult —
        chosen rows, scores, per-eval success) to plan allocations,
        skipping the SelectedOption list and the placed_counts/hosts
        accumulators the windowed caller never reads (they exist for the
        sync path's banned-row retry loop). The all-placed no-network case
        — the storm window — takes the vectorized build above; failures
        and network asks keep the exact per-placement loop. Returns False
        when a winner fails host-side network assignment or its node
        vanished — the caller falls back to the exact per-eval path, same
        as a non-empty failed_rows from collect()."""
        if cr.ok and not prep.has_network_asks:
            return self._collect_build_all_placed(prep, cr, eval_id, job,
                                                  place, plan, acc)

        nt = self.tindex.nt
        chosen_list = cr.chosen.tolist()
        scores_list = cr.scores.tolist()

        node_of = nt.node_of
        nodes_by_id = self._nodes_by_id
        tg_index = prep.tg_index
        tgs = prep.tgs
        metrics_ = self.ctx.metrics
        score_node = metrics_.score_node

        allocs: List[Allocation] = []
        placed_rows: List[int] = []
        placed_ps: List[int] = []
        failed_counts: Dict[str, int] = {}
        # Every alloc of a task group carries the same resource vector;
        # pre-seeding the per-instance memo (immutable by contract) saves
        # a resources_vec walk per alloc downstream (plan verify, usage
        # listener, optimistic overlay).
        shared_vecs: Dict[int, np.ndarray] = {}
        last_ti = None

        def flush_placed():
            # Exhaustion diagnostics read the window accumulator, so the
            # batched accumulation must land before any _note_exhaustion.
            if placed_rows:
                acc.add(np.asarray(placed_rows, dtype=np.int64),
                        prep.demands[placed_ps])
                placed_rows.clear()
                placed_ps.clear()

        for p, tup in enumerate(place):
            row = chosen_list[p]
            tg = tgs[p]
            ti = tg_index[tg.Name]
            last_ti = ti
            if row < 0:
                # No per-placement _fill_metrics here: intermediate fills
                # are dead stores — nothing snapshots the metrics until
                # after the final fill below, which uses the compacted
                # nf_last (the LAST placement's n_feasible, the only one
                # the reference loop's end state keeps).
                flush_placed()
                self._note_exhaustion(tg, prep.tg_masks[ti],
                                      prep.tg_demands[ti], prep,
                                      acc.usage())
                # Snapshots are deferred to after the final _fill_metrics
                # so FailedTGAllocs carries the same end-state metrics the
                # sync path's build_placement_allocs records.
                failed_counts[tg.Name] = failed_counts.get(tg.Name, 0) + 1
                continue
            node = nodes_by_id.get(node_of[row])
            if node is None:
                return False
            option = self._assign_networks(node, tg, scores_list[p])
            if option is None:
                return False
            score_node(node, "binpack", scores_list[p])
            placed_rows.append(row)
            placed_ps.append(p)
            alloc = Allocation(
                ID=generate_uuid(),
                EvalID=eval_id,
                Name=tup.Name,
                JobID=job.ID,
                TaskGroup=tg.Name,
                NodeID=node.ID,
                TaskResources=option.task_resources,
                DesiredStatus=AllocDesiredStatusRun,
                ClientStatus=AllocClientStatusPending,
            )
            vec = shared_vecs.get(ti)
            if vec is None:
                shared_vecs[ti] = alloc_vec(alloc)
            else:
                alloc._resvec_cache = vec
            allocs.append(alloc)
        if last_ti is not None:
            self._fill_metrics(prep, last_ti, cr.nf_last)
        flush_placed()
        for name, count in failed_counts.items():
            metric = failed_tg_allocs.get(name)
            if metric is None:
                metric = failed_tg_allocs[name] = metrics_.copy()
                count -= 1
            metric.CoalescedFailures += count
        if allocs:
            # Scoring is final now: one immutable metric snapshot shared
            # by every placed alloc (reference: alloc.Metrics).
            shared_metric = metrics_.copy()
            append_alloc = plan.append_alloc
            for alloc in allocs:
                alloc.Metrics = shared_metric
                append_alloc(alloc)
        return True

    # ------------------------------------------------------------- helpers
    def _eviction_deltas(self) -> Tuple[np.ndarray, np.ndarray]:
        nt = self.tindex.nt
        rows, vecs = [], []
        for node_id, updates in self.ctx.plan.NodeUpdate.items():
            row = nt.row_of.get(node_id)
            if row is None:
                continue
            for alloc in updates:
                # Look up the full alloc for resource accounting.
                full = self.ctx.state.alloc_by_id(alloc.ID) or alloc
                rows.append(row)
                vecs.append(alloc_vec(full))
        if not rows:
            return np.zeros(0, dtype=np.int32), np.zeros((0, RES_DIMS),
                                                         dtype=np.float32)
        return (np.asarray(rows, dtype=np.int32),
                np.asarray(vecs, dtype=np.float32))

    def _job_alloc_counts(self) -> np.ndarray:
        """Proposed allocs of this job per node row (anti-affinity base)."""
        nt = self.tindex.nt
        counts = np.zeros(nt.n_rows, dtype=np.int32)
        assert self.job is not None
        evicted = {a.ID
                   for updates in self.ctx.plan.NodeUpdate.values()
                   for a in updates}
        for alloc in self.ctx.state.allocs_by_job(self.job.ID):
            if alloc.terminal_status() or alloc.ID in evicted:
                continue
            row = nt.row_of.get(alloc.NodeID)
            if row is not None:
                counts[row] += 1
        for node_id, placed in self.ctx.plan.NodeAllocation.items():
            row = nt.row_of.get(node_id)
            if row is not None:
                counts[row] += sum(1 for a in placed if a.JobID == self.job.ID)
        return counts

    def _assign_networks(self, node: Node, tg: TaskGroup,
                         score: float) -> Optional[SelectedOption]:
        """Host-side port/bandwidth assignment for a chosen node."""
        if not any(t.Resources is not None and t.Resources.Networks
                   for t in tg.Tasks):
            # No network asks anywhere in the group: nothing to reserve, so
            # skip building the node's port/bandwidth index entirely (the
            # common case in large placement storms).
            option = SelectedOption(node=node, score=score)
            for task in tg.Tasks:
                option.task_resources[task.Name] = (
                    task.Resources.copy() if task.Resources is not None
                    else Resources())
            return option
        netidx = self._netidx_cache.get(node.ID)
        if netidx is None:
            netidx = NetworkIndex()
            netidx.set_node(node)
            netidx.add_allocs(self.ctx.proposed_allocs(node.ID))
            self._netidx_cache[node.ID] = netidx
        option = SelectedOption(node=node, score=score)
        staged = []
        for task in tg.Tasks:
            resources = (task.Resources.copy() if task.Resources is not None
                         else Resources())
            if task.Resources is not None and task.Resources.Networks:
                ask = task.Resources.Networks[0]
                try:
                    offer = netidx.assign_network(ask, self.rng)
                except ValueError:
                    # Staged reservations from this partial TG poison the
                    # cached index; drop it so the next user rebuilds clean.
                    self._netidx_cache.pop(node.ID, None)
                    return None
                netidx.add_reserved(offer)
                staged.append(offer)
                resources.Networks = [offer]
            option.task_resources[task.Name] = resources
        return option

    def _fill_metrics(self, prep: PreparedBatch, ti: int,
                      n_feasible: int) -> None:
        """Metrics from the per-unique-TG sums precomputed in prepare_batch
        (summing the node axis per placement would be O(P*N) per eval)."""
        m = self.ctx.metrics
        n_eligible = int(prep.tg_mask_sums[ti])
        m.NodesEvaluated = n_eligible
        m.NodesFiltered = prep.cand_sum - n_eligible
        m.NodesExhausted = max(0, n_eligible - n_feasible)

    def _note_exhaustion(self, tg: TaskGroup, mask: np.ndarray,
                         demand: np.ndarray,
                         prep: Optional[PreparedBatch] = None,
                         placed_usage: Optional[np.ndarray] = None) -> None:
        """Failed placement: record which dimensions were exhausted, against
        the EFFECTIVE usage the kernel saw (committed usage minus this plan's
        evictions plus this call's earlier placements) — diffing the stale
        host mirror can blame the wrong dimension."""
        nt = self.tindex.nt
        usage = nt.usage
        if (prep is not None and len(prep.evict_rows)) or (
                placed_usage is not None and placed_usage.any()):
            usage = usage.copy()
            if prep is not None and len(prep.evict_rows):
                np.subtract.at(usage, prep.evict_rows, prep.evict_vecs)
            if placed_usage is not None:
                usage += placed_usage
        free = nt.capacity - usage
        lacking = (free < demand[None, :]) & mask[:, None]
        per_dim = lacking.sum(axis=0)
        for d, count in enumerate(per_dim):
            if count > 0:
                name = DIM_NAMES[d]
                m = self.ctx.metrics
                m.DimensionExhausted[name] = (
                    m.DimensionExhausted.get(name, 0) + int(count))

    # -------------------------------------------- single-node host fast path
    def select_on_node(self, tg: TaskGroup, node: Node
                       ) -> Optional[SelectedOption]:
        """Feasibility + fit on one specific node, host-side (used by
        in-place updates, reference: util.go:393-426)."""
        from nomad_tpu.tensor.constraints import (
            node_has_drivers,
            node_meets_constraints,
        )

        assert self.job is not None
        nt = self.tindex.nt
        m = self.ctx.metrics
        row = nt.row_of.get(node.ID)
        if row is None:
            return None
        m.NodesEvaluated += 1
        cons = task_group_constraints(tg)
        if not nt.ready[row]:
            m.NodesFiltered += 1
            return None
        if not node_meets_constraints(node, self.job.Constraints):
            m.filter_node(node, "job constraints")  # increments NodesFiltered
            return None
        if not (node_meets_constraints(node, cons.constraints)
                and node_has_drivers(node, cons.drivers)):
            m.filter_node(node, "group constraints")
            return None
        # Usage: committed minus in-plan evictions on this node.
        usage = nt.usage[row].copy()
        for alloc in self.ctx.plan.NodeUpdate.get(node.ID, ()):
            full = self.ctx.state.alloc_by_id(alloc.ID) or alloc
            usage -= alloc_vec(full)
        for alloc in self.ctx.plan.NodeAllocation.get(node.ID, ()):
            usage += alloc_vec(alloc)
        demand = resources_vec(cons.size)
        lacking = fit_lacking(nt.capacity[row], usage, demand)
        if np.any(lacking):
            m.NodesExhausted += 1
            for d in np.flatnonzero(lacking):
                name = DIM_NAMES[int(d)]
                m.DimensionExhausted[name] = (
                    m.DimensionExhausted.get(name, 0) + 1)
            return None
        util2 = usage[:2] + demand[:2]
        score = float(score_fit_rows(util2[None, :],
                                     nt.score_cap[row][None, :])[0])
        option = SelectedOption(node=node, score=score)
        for task in tg.Tasks:
            option.task_resources[task.Name] = (
                task.Resources.copy() if task.Resources is not None
                else Resources())
        return option


class SystemStack:
    """Stack for the system scheduler: evaluates one specific node at a time
    (reference: stack.go:176-261)."""

    def __init__(self, ctx: EvalContext, tindex: TensorIndex):
        self.inner = GenericStack(ctx, tindex, batch=False)

    def set_nodes(self, nodes: Sequence[Node]) -> None:
        self.inner.set_nodes(nodes)

    def set_job(self, job: Job) -> None:
        self.inner.set_job(job)

    def adopt_shared(self, job: Job, elig) -> None:
        self.inner.adopt_shared(job, elig)

    def select(self, tg: TaskGroup, node: Node) -> Optional[SelectedOption]:
        option = self.inner.select_on_node(tg, node)
        if option is None:
            return None
        return self.inner._assign_networks(node, tg, option.score) or None

    def select_batch_on_nodes(self, tg: TaskGroup, nodes: Sequence[Node]
                              ) -> Optional[List[Optional[SelectedOption]]]:
        """Vectorized per-pinned-node selection for ONE task group: the
        system scheduler's sweep is `for node in all_nodes: select(tg,
        node)`, which at 10k nodes is 10k Python constraint walks. All the
        per-node checks are row math on the node tensor, so they run as a
        handful of numpy ops over the whole batch instead (the TPU-framework
        shape of system_sched.go:219-281's loop; the reference's per-node
        semantics are preserved exactly).

        Returns None when the group asks for network resources — port
        bitmaps are per-node host state, the caller keeps the per-node path.
        """
        inner = self.inner
        assert inner.job is not None and inner.elig is not None
        if any(t.Resources is not None and t.Resources.Networks
               for t in tg.Tasks):
            return None
        nt = inner.tindex.nt
        ctx = inner.ctx
        m = ctx.metrics

        cons = task_group_constraints(tg)
        job_mask, _, _ = inner.elig.job_mask(inner.job.ID,
                                             inner.job.Constraints)
        tg_mask, _, _ = inner.elig.tg_mask(inner.job.ID, tg.Name,
                                           cons.constraints, cons.drivers)
        demand = resources_vec(cons.size).astype(np.float64)

        results: List[Optional[SelectedOption]] = [None] * len(nodes)
        rows = np.empty(len(nodes), dtype=np.int64)
        idxs: List[int] = []
        for i, node in enumerate(nodes):
            row = nt.row_of.get(node.ID)
            if row is not None:
                rows[len(idxs)] = row
                idxs.append(i)
        rows = rows[:len(idxs)]
        if not len(rows):
            return results

        usage_rows, cap_rows = nt.snapshot_rows(rows)
        usage_rows = usage_rows.astype(np.float64)
        # In-plan deltas on these nodes (stops subtract, placements add) —
        # mirrors select_on_node's per-node walk, batched by node id.
        plan = ctx.plan
        if plan.NodeUpdate or plan.NodeAllocation:
            for k, i in enumerate(idxs):
                nid = nodes[i].ID
                for alloc in plan.NodeUpdate.get(nid, ()):
                    full = ctx.state.alloc_by_id(alloc.ID) or alloc
                    usage_rows[k] -= alloc_vec(full)
                for alloc in plan.NodeAllocation.get(nid, ()):
                    usage_rows[k] += alloc_vec(alloc)

        ready = nt.ready[rows]
        job_ok = job_mask[rows]
        tg_ok = tg_mask[rows]
        eligible = ready & job_ok & tg_ok
        lacking = fit_lacking(cap_rows, usage_rows, demand[None, :])
        fits = ~lacking.any(axis=1)
        ok = eligible & fits

        # Metrics: the exact counters select_on_node's per-node walk
        # accumulates (not-ready counts filtered-only; constraint filters
        # also record class + constraint labels via filter_node).
        m.NodesEvaluated += len(rows)
        m.NodesFiltered += int((~ready).sum())
        job_filtered = ready & ~job_ok
        tg_filtered = ready & job_ok & ~tg_ok
        for sel, label in ((job_filtered, "job constraints"),
                           (tg_filtered, "group constraints")):
            for k in np.flatnonzero(sel):
                m.filter_node(nodes[idxs[int(k)]], label)
        exhausted = eligible & ~fits
        m.NodesExhausted += int(exhausted.sum())
        if exhausted.any():
            # Per lacking dimension of each exhausted node, exactly like
            # select_on_node's flatnonzero walk.
            per_dim = (lacking & exhausted[:, None]).sum(axis=0)
            for d, count in enumerate(per_dim.tolist()):
                if count:
                    name = DIM_NAMES[d]
                    m.DimensionExhausted[name] = (
                        m.DimensionExhausted.get(name, 0) + count)

        util2 = usage_rows[:, :2] + demand[None, :2]
        scores = score_fit_rows(util2, nt.score_cap[rows])

        ok_list = ok.tolist()
        score_list = scores.tolist()
        for k, i in enumerate(idxs):
            if not ok_list[k]:
                continue
            node = nodes[i]
            option = SelectedOption(node=node, score=score_list[k])
            for task in tg.Tasks:
                option.task_resources[task.Name] = (
                    task.Resources.copy() if task.Resources is not None
                    else Resources())
            results[i] = option
        return results
