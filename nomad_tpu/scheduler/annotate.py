"""Annotate a job diff with scheduler-desired actions for `plan` output
(reference: scheduler/annotate.go:37-185).

Takes the structural JobDiff between the submitted and existing job plus the
dry-run scheduler's PlanAnnotations (per-task-group DesiredUpdates) and
decorates the diff so a human can see what the plan would actually do:
count changes force creates/destroys, task edits force in-place or
destructive updates.
"""

from __future__ import annotations

from typing import Optional

from nomad_tpu.structs.diff import (
    DiffTypeAdded,
    DiffTypeDeleted,
    DiffTypeEdited,
    DiffTypeNone,
    JobDiff,
    TaskDiff,
    TaskGroupDiff,
)

AnnotationForcesCreate = "forces create"
AnnotationForcesDestroy = "forces destroy"
AnnotationForcesInplaceUpdate = "forces in-place update"
AnnotationForcesDestructiveUpdate = "forces create/destroy update"

UpdateTypeIgnore = "ignore"
UpdateTypeCreate = "create"
UpdateTypeDestroy = "destroy"
UpdateTypeMigrate = "migrate"
UpdateTypeInplaceUpdate = "in-place update"
UpdateTypeDestructiveUpdate = "create/destroy update"


def annotate(diff: JobDiff, annotations) -> None:
    """(reference: annotate.go:37-51 Annotate)"""
    for tg_diff in diff.TaskGroups:
        _annotate_task_group(tg_diff, annotations)


def _annotate_task_group(diff: TaskGroupDiff, annotations) -> None:
    """(reference: annotate.go:53-100 annotateTaskGroup)"""
    if annotations is not None:
        tg = annotations.DesiredTGUpdates.get(diff.Name)
        if tg is not None:
            for key, count in ((UpdateTypeIgnore, tg.Ignore),
                               (UpdateTypeCreate, tg.Place),
                               (UpdateTypeMigrate, tg.Migrate),
                               (UpdateTypeDestroy, tg.Stop),
                               (UpdateTypeInplaceUpdate, tg.InPlaceUpdate),
                               (UpdateTypeDestructiveUpdate,
                                tg.DestructiveUpdate)):
                if count:
                    diff.Updates[key] = count

    _annotate_count_change(diff)
    for task_d in diff.Tasks:
        _annotate_task(task_d, diff)


def _annotate_count_change(diff: TaskGroupDiff) -> None:
    """(reference: annotate.go:103-143 annotateCountChange)"""
    count_diff = next((f for f in diff.Fields if f.Name == "Count"), None)
    if count_diff is None:
        return
    old_v = int(count_diff.Old) if count_diff.Old else 0
    new_v = int(count_diff.New) if count_diff.New else 0
    if old_v < new_v:
        count_diff.Annotations.append(AnnotationForcesCreate)
    elif new_v < old_v:
        count_diff.Annotations.append(AnnotationForcesDestroy)


def _annotate_task(diff: TaskDiff, parent: TaskGroupDiff) -> None:
    """(reference: annotate.go:146-185 annotateTask)"""
    if diff.Type == DiffTypeNone:
        return

    # Inside a wholly added/deleted group the task fate follows the group.
    if parent.Type in (DiffTypeAdded, DiffTypeDeleted):
        if diff.Type == DiffTypeAdded:
            diff.Annotations.append(AnnotationForcesCreate)
            return
        if diff.Type == DiffTypeDeleted:
            diff.Annotations.append(AnnotationForcesDestroy)
            return

    if diff.Type in (DiffTypeAdded, DiffTypeDeleted):
        diff.Annotations.append(AnnotationForcesDestructiveUpdate)
        return

    # Edited: every primitive-field change is destructive; only LogConfig,
    # Service, and Constraint object edits go in place (reference:
    # annotate.go:161-183 — note the reference is deliberately more
    # conservative here than tasksUpdated, util.go:291).
    destructive = any(f.Type != DiffTypeNone for f in diff.Fields)
    if not destructive:
        for o in diff.Objects:
            if (o.Type != DiffTypeNone
                    and o.Name not in ("LogConfig", "Service", "Constraint")):
                destructive = True
                break
    diff.Annotations.append(
        AnnotationForcesDestructiveUpdate if destructive
        else AnnotationForcesInplaceUpdate)
