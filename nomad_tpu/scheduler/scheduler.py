"""Scheduler factory and the State/Planner seams (reference:
scheduler/scheduler.go:13-96)."""

from __future__ import annotations

import logging
from typing import Callable, Dict, Optional, Protocol, Tuple

from nomad_tpu.structs import Evaluation, Plan, PlanResult


class State(Protocol):
    """Immutable snapshot reads the scheduler needs (reference:
    scheduler.go:55-76). Satisfied by StateStore and StateSnapshot."""

    def nodes(self): ...
    def node_by_id(self, node_id: str): ...
    def job_by_id(self, job_id: str): ...
    def allocs_by_job(self, job_id: str): ...
    def allocs_by_node(self, node_id: str): ...
    def allocs_by_node_terminal(self, node_id: str, terminal: bool): ...


class Planner(Protocol):
    """Write seam owned by the worker (reference: scheduler.go:78-96)."""

    def submit_plan(self, plan: Plan) -> Tuple[Optional[PlanResult], Optional[State]]:
        """Returns (result, refreshed_state_or_None)."""
        ...

    def update_eval(self, eval: Evaluation) -> None: ...
    def create_eval(self, eval: Evaluation) -> None: ...
    def reblock_eval(self, eval: Evaluation) -> None: ...


class Scheduler(Protocol):
    def process(self, eval: Evaluation) -> None: ...


class SetStatusError(Exception):
    """Terminal scheduling failure carrying the eval status to set
    (reference: generic_sched.go:42-50)."""

    def __init__(self, msg: str, eval_status: str):
        super().__init__(msg)
        self.eval_status = eval_status


def new_scheduler(name: str, state: State, planner: Planner,
                  tindex=None, logger: Optional[logging.Logger] = None,
                  impl: str = "tpu") -> Scheduler:
    """(reference: scheduler.go:30-41 NewScheduler)

    tindex is the TensorIndex backing the placement kernels; when None, one is
    built from the state snapshot (simple mode for tests/tools). impl selects
    the placement engine for the generic schedulers: "tpu" (device kernels)
    or "cpu-reference" (host-side iterator chain, the benchmark denominator).
    """
    factory = BUILTIN_SCHEDULERS.get(name)
    if factory is None:
        raise ValueError(f"unknown scheduler '{name}'")
    return factory(state, planner, tindex,
                   logger or logging.getLogger("sched"), impl)


def _service(state, planner, tindex, logger, impl="tpu"):
    from .generic_sched import GenericScheduler

    return GenericScheduler(state, planner, tindex, logger, batch=False,
                            impl=impl)


def _batch(state, planner, tindex, logger, impl="tpu"):
    from .generic_sched import GenericScheduler

    return GenericScheduler(state, planner, tindex, logger, batch=True,
                            impl=impl)


def _system(state, planner, tindex, logger, impl="tpu"):
    # The system scheduler's per-node sweep is host-side already; it has no
    # separate cpu-reference engine, so impl is accepted but moot.
    from .system_sched import SystemScheduler

    return SystemScheduler(state, planner, tindex, logger)


BUILTIN_SCHEDULERS: Dict[str, Callable] = {
    "service": _service,
    "batch": _batch,
    "system": _system,
}
