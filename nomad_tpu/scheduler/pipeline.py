"""Pipelined placement: device-resident usage chaining across evaluations.

The TPU-native throughput path. A synchronous per-eval loop pays one
device->host RTT per evaluation (expensive on remote-attached TPUs); instead
the placer chains evaluations ON DEVICE — eval i+1's usage input is eval i's
usage_after array, never copied back — dispatches asynchronously, and streams
packed results home with copy-ahead, so the RTT amortizes across the whole
in-flight window.

This is the tensor re-expression of the reference's optimistic concurrency:
N workers scheduling against snapshots with a serializing applier
(reference: nomad/worker.go:45-49, plan_apply.go:24-33) becomes a device-side
dependency chain with deferred host materialization; the plan applier still
re-verifies every placement before commit.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nomad_tpu.structs import Job, TaskGroup
from nomad_tpu.tensor import TensorIndex
from nomad_tpu.tensor.node_table import RES_DIMS, resources_vec

from . import kernels
from .util import task_group_constraints


@dataclass
class EvalRequest:
    job: Job
    tgs: List[TaskGroup]


@dataclass
class EvalPlacements:
    job: Job
    tgs: List[TaskGroup]
    chosen_rows: np.ndarray   # [P] int32, -1 = infeasible
    scores: np.ndarray        # [P] f32
    n_feasible: np.ndarray    # [P] int32


class PipelinedPlacer:
    """Streams evaluations through the placement kernel with device-resident
    usage state."""

    def __init__(self, tindex: TensorIndex, nodes, batch: bool = False,
                 rng: Optional[random.Random] = None, window: int = 16):
        import jax
        import jax.numpy as jnp

        self.tindex = tindex
        self.nodes = list(nodes)
        self.batch = batch
        self.rng = rng or random.Random()
        self.window = window
        self._jnp = jnp
        self._jax = jax

        nt = tindex.nt
        d = nt.device_arrays()
        self._capacity = d["capacity"]
        self._score_cap = d["score_cap"]
        self._usage = d["usage"]  # device-resident, chained across evals
        self._cand_mask = np.zeros(nt.n_rows, dtype=bool)
        for n in self.nodes:
            row = nt.row_of.get(n.ID)
            if row is not None:
                self._cand_mask[row] = True
        noise = np.asarray(
            np.random.default_rng(self.rng.randrange(2**31)).random(nt.n_rows),
            dtype=np.float32) * 1e-3
        self._noise = jnp.asarray(noise)
        self._zero_counts = jnp.zeros(nt.n_rows, dtype=jnp.int32)
        self._no_banned = jnp.zeros(nt.n_rows, dtype=bool)
        self._mask_cache: Dict[tuple, np.ndarray] = {}
        self._input_cache: Dict[tuple, tuple] = {}
        self._inflight: List[Tuple[EvalRequest, object]] = []
        self.results: List[EvalPlacements] = []
        self._penalty = jnp.float32(5.0 if batch else 10.0)
        self._false = jnp.asarray(False)
        # One representative node per computed class for host constraint
        # evaluation (classes << nodes).
        self._reps: Dict[int, Job] = {}
        for n in self.nodes:
            cid = nt.class_vocab.get(n.ComputedClass)
            if cid is not None and cid not in self._reps:
                self._reps[cid] = n

    # ------------------------------------------------------------- internals
    def _tg_mask(self, job: Job, tg: TaskGroup) -> np.ndarray:
        """Eligibility mask keyed by the constraint SIGNATURE, so distinct
        jobs with identical constraints share one per-class evaluation; the
        node axis is a vectorized gather, never a Python loop."""
        from nomad_tpu.tensor.constraints import (
            node_has_drivers,
            node_meets_constraints,
        )

        nt = self.tindex.nt
        cons = task_group_constraints(tg)
        key = (
            tuple((c.LTarget, c.Operand, c.RTarget) for c in job.Constraints),
            tuple((c.LTarget, c.Operand, c.RTarget) for c in cons.constraints),
            tuple(cons.drivers),
        )
        cached = self._mask_cache.get(key)
        if cached is not None:
            return cached
        table = np.zeros(max(len(nt.class_names), 1), dtype=bool)
        for cid, rep in self._reps.items():
            table[cid] = (node_meets_constraints(rep, job.Constraints)
                          and node_meets_constraints(rep, cons.constraints)
                          and node_has_drivers(rep, cons.drivers))
        mask = table[nt.class_ids] & nt.ready & self._cand_mask
        self._mask_cache[key] = mask
        return mask

    def _device_inputs(self, req: EvalRequest):
        """Device-side (masks, demands, tg_ids, valid) cached by the eval's
        placement signature: repeated workloads pay zero host->device puts."""
        jnp = self._jnp
        tgs = req.tgs
        cons_sig = tuple(
            (tg.Name,
             tuple((c.LTarget, c.Operand, c.RTarget) for c in req.job.Constraints))
            for tg in tgs)
        cached = self._input_cache.get(cons_sig)
        if cached is not None:
            return cached
        p_pad = 8
        while p_pad < len(tgs):
            p_pad *= 2
        demands = np.zeros((p_pad, RES_DIMS), dtype=np.float32)
        valid = np.zeros(p_pad, dtype=bool)
        unique: Dict[str, int] = {}
        masks: List[np.ndarray] = []
        tg_ids = np.zeros(p_pad, dtype=np.int32)
        for p, tg in enumerate(tgs):
            ti = unique.get(tg.Name)
            if ti is None:
                ti = len(masks)
                unique[tg.Name] = ti
                masks.append(self._tg_mask(req.job, tg))
            demands[p] = resources_vec(task_group_constraints(tg).size)
            tg_ids[p] = ti
            valid[p] = True
        out = (jnp.asarray(np.stack(masks)), jnp.asarray(demands),
               jnp.asarray(tg_ids), jnp.asarray(valid))
        self._input_cache[cons_sig] = out
        return out

    def submit(self, req: EvalRequest) -> None:
        """Dispatch one eval's placement program; non-blocking."""
        jnp = self._jnp
        masks, demands, tg_ids, valid = self._device_inputs(req)
        res = kernels.place_batch(
            self._capacity, self._score_cap, self._usage,
            masks, self._zero_counts, demands, tg_ids, valid,
            self._noise, self._penalty, self._false, self._no_banned)
        # Chain: next eval sees this eval's proposed usage, device-side.
        self._usage = res.usage_after
        self._inflight.append((req, res.packed))
        if len(self._inflight) >= self.window:
            self._drain_window()

    def _drain_window(self) -> None:
        """ONE readback for the whole in-flight window: per-transfer RTT on a
        remote-attached TPU amortizes across all of the window's evals."""
        jnp = self._jnp
        window = self._inflight
        self._inflight = []
        if not window:
            return
        by_shape: Dict[tuple, list] = {}
        for i, (req, packed) in enumerate(window):
            by_shape.setdefault(packed.shape, []).append((i, req, packed))
        out: List[Tuple[int, EvalPlacements]] = []
        for shape, group in by_shape.items():
            stacked = np.asarray(jnp.stack([p for _, _, p in group]))
            for (i, req, _), arr in zip(group, stacked):
                arr = arr[: len(req.tgs)]
                out.append((i, EvalPlacements(
                    job=req.job, tgs=req.tgs,
                    chosen_rows=arr[:, 0].astype(np.int32),
                    scores=arr[:, 1],
                    n_feasible=arr[:, 2].astype(np.int32))))
        out.sort(key=lambda t: t[0])
        self.results.extend(r for _, r in out)

    def flush(self) -> List[EvalPlacements]:
        self._drain_window()
        out = self.results
        self.results = []
        return out

    def sync_usage_to_host(self) -> None:
        """Materialize the chained device usage back into the host mirror."""
        nt = self.tindex.nt
        nt.usage[:] = np.asarray(self._usage)
        nt._dirty_rows.clear()
        nt._device["usage"] = self._usage
