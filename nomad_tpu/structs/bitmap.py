"""Dense bitmaps for port-collision tracking (reference: nomad/structs/bitmap.go).

Backed by numpy uint32 words so the same buffer can be shipped to the TPU
port-collision kernel (nomad_tpu/scheduler/kernels.py) without conversion.
"""

from __future__ import annotations

import numpy as np


class Bitmap:
    """Fixed-size bitmap over [0, size)."""

    __slots__ = ("size", "words")

    def __init__(self, size: int):
        if size <= 0:
            raise ValueError("bitmap must be positive size")
        self.size = size
        self.words = np.zeros((size + 31) // 32, dtype=np.uint32)

    def set(self, idx: int) -> None:
        self.words[idx >> 5] |= np.uint32(1 << (idx & 31))

    def check(self, idx: int) -> bool:
        return bool((self.words[idx >> 5] >> np.uint32(idx & 31)) & np.uint32(1))

    def clear(self) -> None:
        self.words.fill(0)

    def copy(self) -> "Bitmap":
        b = Bitmap(self.size)
        b.words = self.words.copy()
        return b

    def indexes_in_range(self, set_bits: bool, start: int, end: int) -> list[int]:
        """Indexes in [start, end] whose bit equals set_bits."""
        out = []
        for i in range(start, min(end + 1, self.size)):
            if self.check(i) == set_bits:
                out.append(i)
        return out
