"""Network resource indexing: port collision + bandwidth accounting.

Mirrors the reference's NetworkIndex semantics (reference:
nomad/structs/network.go): per-IP 65536-bit port bitmaps, per-device bandwidth
totals, dynamic port picking in [20000, 60000). The bitmaps are numpy uint32
words (see bitmap.py) so the scheduler can batch surviving candidates' port
checks on device.
"""

from __future__ import annotations

import ipaddress
import random
from typing import Dict, List, Optional

from .bitmap import Bitmap
from .structs import (
    Allocation,
    MaxDynamicPort,
    MaxValidPort,
    MinDynamicPort,
    NetworkResource,
    Node,
    Port,
)

_MAX_RAND_PORT_ATTEMPTS = 20


class NetworkIndex:
    """Indexes available and used network resources on one machine."""

    def __init__(self) -> None:
        self.avail_networks: List[NetworkResource] = []
        self.avail_bandwidth: Dict[str, int] = {}
        self.used_ports: Dict[str, Bitmap] = {}
        self.used_bandwidth: Dict[str, int] = {}

    def overcommitted(self) -> bool:
        for device, used in self.used_bandwidth.items():
            if used > self.avail_bandwidth.get(device, 0):
                return True
        return False

    def set_node(self, node: Node) -> bool:
        """Register the node's networks; True if reserved ports collide."""
        collide = False
        if node.Resources is not None:
            for n in node.Resources.Networks:
                if n.Device:
                    self.avail_networks.append(n)
                    self.avail_bandwidth[n.Device] = n.MBits
        if node.Reserved is not None:
            for n in node.Reserved.Networks:
                if self.add_reserved(n):
                    collide = True
        return collide

    def add_allocs(self, allocs: List[Allocation]) -> bool:
        collide = False
        for alloc in allocs:
            for task_res in alloc.TaskResources.values():
                if not task_res.Networks:
                    continue
                if self.add_reserved(task_res.Networks[0]):
                    collide = True
        return collide

    def add_reserved(self, n: NetworkResource) -> bool:
        collide = False
        used = self.used_ports.get(n.IP)
        if used is None:
            used = Bitmap(MaxValidPort)
            self.used_ports[n.IP] = used
        for ports in (n.ReservedPorts, n.DynamicPorts):
            for port in ports:
                if port.Value < 0 or port.Value >= MaxValidPort:
                    return True
                if used.check(port.Value):
                    collide = True
                else:
                    used.set(port.Value)
        self.used_bandwidth[n.Device] = self.used_bandwidth.get(n.Device, 0) + n.MBits
        return collide

    def _yield_ips(self):
        for n in self.avail_networks:
            try:
                net = ipaddress.ip_network(n.CIDR, strict=False)
            except ValueError:
                continue
            for ip in net:
                yield n, str(ip)

    def assign_network(self, ask: NetworkResource,
                       rng: Optional[random.Random] = None) -> NetworkResource:
        """Assign network resources for an ask; raises ValueError when unsatisfiable."""
        rng = rng or random
        err = "no networks available"
        for n, ip_str in self._yield_ips():
            avail = self.avail_bandwidth.get(n.Device, 0)
            used = self.used_bandwidth.get(n.Device, 0)
            if used + ask.MBits > avail:
                err = "bandwidth exceeded"
                continue

            used_ports = self.used_ports.get(ip_str)
            port_collision = False
            for port in ask.ReservedPorts:
                if port.Value < 0 or port.Value >= MaxValidPort:
                    raise ValueError(f"invalid port {port.Value} (out of range)")
                if used_ports is not None and used_ports.check(port.Value):
                    err = "reserved port collision"
                    port_collision = True
                    break
            if port_collision:
                continue

            offer = NetworkResource(
                Device=n.Device,
                IP=ip_str,
                MBits=ask.MBits,
                ReservedPorts=[Port(p.Label, p.Value) for p in ask.ReservedPorts],
                DynamicPorts=[Port(p.Label, p.Value) for p in ask.DynamicPorts],
            )

            ok = True
            for i in range(len(offer.DynamicPorts)):
                picked = self._pick_dynamic_port(used_ports, offer, rng)
                if picked is None:
                    err = "dynamic port selection failed"
                    ok = False
                    break
                offer.DynamicPorts[i].Value = picked
            if not ok:
                continue
            return offer
        raise ValueError(err)

    @staticmethod
    def _pick_dynamic_port(used: Optional[Bitmap], offer: NetworkResource,
                           rng) -> Optional[int]:
        taken = {p.Value for p in offer.ReservedPorts} | {p.Value for p in offer.DynamicPorts}
        for _ in range(_MAX_RAND_PORT_ATTEMPTS):
            cand = MinDynamicPort + rng.randrange(MaxDynamicPort - MinDynamicPort)
            if used is not None and used.check(cand):
                continue
            if cand in taken:
                continue
            return cand
        return None
