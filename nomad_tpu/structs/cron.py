"""Minimal cron-expression evaluator for periodic jobs.

The reference uses gorhill/cronexpr (reference: nomad/structs/structs.go:1243,
nomad/periodic.go). Supports the standard 5-field form `min hour dom month dow`
plus an optional leading seconds field, with `*`, lists, ranges, and steps.
"""

from __future__ import annotations

import calendar
import time
from dataclasses import dataclass
from typing import FrozenSet, Tuple

_MONTH_NAMES = {name.lower(): i for i, name in enumerate(calendar.month_abbr) if name}
_DAY_NAMES = {name.lower(): (i + 1) % 7 for i, name in enumerate(calendar.day_abbr)}


def _parse_field(spec: str, lo: int, hi: int, names: dict | None = None) -> FrozenSet[int]:
    values: set[int] = set()
    spec = spec.lower()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step <= 0:
                raise ValueError(f"invalid step {step}")
        if part in ("*", "?"):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start = _parse_value(a, names)
            end = _parse_value(b, names)
        else:
            start = _parse_value(part, names)
            end = start if step == 1 else hi
        if start < lo or end > hi or start > end:
            raise ValueError(f"field value out of range [{lo},{hi}]: {spec!r}")
        values.update(range(start, end + 1, step))
    return frozenset(values)


def _parse_value(s: str, names: dict | None) -> int:
    s = s.strip().lower()
    if names and s in names:
        return names[s]
    return int(s)


@dataclass(frozen=True)
class CronExpr:
    seconds: FrozenSet[int]
    minutes: FrozenSet[int]
    hours: FrozenSet[int]
    dom: FrozenSet[int]
    months: FrozenSet[int]
    dow: FrozenSet[int]
    dom_star: bool
    dow_star: bool

    @staticmethod
    def parse(spec: str) -> "CronExpr":
        spec = spec.strip()
        if spec.startswith("@"):
            spec = {
                "@yearly": "0 0 1 1 *", "@annually": "0 0 1 1 *",
                "@monthly": "0 0 1 * *", "@weekly": "0 0 * * 0",
                "@daily": "0 0 * * *", "@midnight": "0 0 * * *",
                "@hourly": "0 * * * *",
            }.get(spec, None) or _raise(ValueError(f"unknown alias {spec!r}"))
        fields = spec.split()
        if len(fields) == 5:
            fields = ["0"] + fields
        if len(fields) != 6:
            raise ValueError(f"expected 5 or 6 fields, got {len(fields)}")
        sec = _parse_field(fields[0], 0, 59)
        minute = _parse_field(fields[1], 0, 59)
        hour = _parse_field(fields[2], 0, 23)
        dom = _parse_field(fields[3], 1, 31)
        month = _parse_field(fields[4], 1, 12, _MONTH_NAMES)
        dow = _parse_field(fields[5], 0, 7, _DAY_NAMES)
        if 7 in dow:  # both 0 and 7 mean Sunday
            dow = (dow - {7}) | {0}
        return CronExpr(sec, minute, hour, dom, month, dow,
                        dom_star=fields[3] in ("*", "?"),
                        dow_star=fields[5] in ("*", "?"))

    def _day_match(self, tm: time.struct_time) -> bool:
        dom_ok = tm.tm_mday in self.dom
        dow_ok = (tm.tm_wday + 1) % 7 in self.dow
        if self.dom_star and self.dow_star:
            return True
        if self.dom_star:
            return dow_ok
        if self.dow_star:
            return dom_ok
        return dom_ok or dow_ok  # standard cron OR semantics

    def next(self, from_time: float) -> float:
        """Next matching time (unix seconds) strictly after from_time; 0.0 if none in 5y."""
        t = int(from_time) + 1
        limit = t + 5 * 366 * 24 * 3600
        while t < limit:
            tm = time.localtime(t)
            if (tm.tm_mon in self.months and self._day_match(tm)
                    and tm.tm_hour in self.hours and tm.tm_min in self.minutes
                    and tm.tm_sec in self.seconds):
                return float(t)
            # Skip forward coarsely to keep this fast.
            if tm.tm_mon not in self.months or not self._day_match(tm):
                t = int(time.mktime((tm.tm_year, tm.tm_mon, tm.tm_mday, 23, 59, 59,
                                     0, 0, -1))) + 1
            elif tm.tm_hour not in self.hours:
                t = int(time.mktime((tm.tm_year, tm.tm_mon, tm.tm_mday, tm.tm_hour,
                                     59, 59, 0, 0, -1))) + 1
            elif tm.tm_min not in self.minutes:
                t += 60 - tm.tm_sec
            else:
                t += 1
        return 0.0


def _raise(e: Exception):
    raise e
