"""Computed node class: the memoization key for feasibility checking.

Nodes with identical (Datacenter, non-unique Attributes, non-unique Meta,
NodeClass) share a computed class, so constraint feasibility is evaluated once
per class instead of per node (reference: nomad/structs/node_class.go). In the
TPU design this is also the compression axis: per-class host evaluation
produces small lookup tables that are gathered back over the node axis on
device (nomad_tpu/tensor/).
"""

from __future__ import annotations

import hashlib
from typing import List

from .structs import Constraint, Node

NODE_UNIQUE_NAMESPACE = "unique."


def unique_namespace(key: str) -> str:
    return f"{NODE_UNIQUE_NAMESPACE}{key}"


def is_unique_namespace(key: str) -> bool:
    return key.startswith(NODE_UNIQUE_NAMESPACE)


def compute_class(node: Node) -> str:
    """Stable hash of the node's non-unique scheduling-relevant fields."""
    h = hashlib.blake2b(digest_size=8)

    def feed(label: str, items):
        h.update(label.encode())
        for k, v in sorted(items):
            h.update(b"\x00")
            h.update(str(k).encode())
            h.update(b"\x01")
            h.update(str(v).encode())
        h.update(b"\x02")

    feed("dc", [("", node.Datacenter)])
    feed("class", [("", node.NodeClass)])
    feed("attrs", [(k, v) for k, v in node.Attributes.items() if not is_unique_namespace(k)])
    feed("meta", [(k, v) for k, v in node.Meta.items() if not is_unique_namespace(k)])
    return f"v1:{int.from_bytes(h.digest(), 'big')}"


def compute_node_class(node: Node) -> None:
    node.ComputedClass = compute_class(node)


def escaped_constraints(constraints: List[Constraint]) -> List[Constraint]:
    """Constraints that reference unique.* targets and therefore cannot be
    memoized by computed class (reference: node_class.go:69-94)."""
    return [c for c in constraints
            if _target_escapes(c.LTarget) or _target_escapes(c.RTarget)]


def _target_escapes(target: str) -> bool:
    return (target.startswith("${node.unique.")
            or target.startswith("${attr.unique.")
            or target.startswith("${meta.unique."))
