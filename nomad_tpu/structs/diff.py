"""Structural job diff for `plan` dry-runs (reference: nomad/structs/diff.go).

Produces a tree of typed diffs — JobDiff → TaskGroupDiff → TaskDiff →
ObjectDiff/FieldDiff — between two versions of a job. The reference flattens
structs via reflection (flatmap + hashstructure); here we flatten dataclasses
generically: primitive fields and string-keyed maps become dotted field
paths, nested dataclasses and lists of dataclasses become child ObjectDiffs
matched by a semantic key (Name / target / label).

`contextual=True` includes unchanged fields inside changed objects so the
renderer can show full context (reference: diff.go:59,177,318 `contextual`).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# Diff types, ordered Edited > Added > Deleted > None for display sorting
# (reference: diff.go:14-45).
DiffTypeNone = "None"
DiffTypeAdded = "Added"
DiffTypeDeleted = "Deleted"
DiffTypeEdited = "Edited"

_TYPE_ORDER = {DiffTypeEdited: 0, DiffTypeAdded: 1, DiffTypeDeleted: 2,
               DiffTypeNone: 3}


@dataclass
class FieldDiff:
    """A single scalar field change (reference: diff.go:846-884)."""

    Type: str = DiffTypeNone
    Name: str = ""
    Old: str = ""
    New: str = ""
    Annotations: List[str] = field(default_factory=list)


@dataclass
class ObjectDiff:
    """A nested object change (reference: diff.go:773-838)."""

    Type: str = DiffTypeNone
    Name: str = ""
    Fields: List[FieldDiff] = field(default_factory=list)
    Objects: List["ObjectDiff"] = field(default_factory=list)


@dataclass
class TaskDiff:
    """(reference: diff.go:308-315)"""

    Type: str = DiffTypeNone
    Name: str = ""
    Fields: List[FieldDiff] = field(default_factory=list)
    Objects: List[ObjectDiff] = field(default_factory=list)
    Annotations: List[str] = field(default_factory=list)


@dataclass
class TaskGroupDiff:
    """(reference: diff.go:165-172)"""

    Type: str = DiffTypeNone
    Name: str = ""
    Fields: List[FieldDiff] = field(default_factory=list)
    Objects: List[ObjectDiff] = field(default_factory=list)
    Tasks: List[TaskDiff] = field(default_factory=list)
    Updates: Dict[str, int] = field(default_factory=dict)


@dataclass
class JobDiff:
    """(reference: diff.go:48-54)"""

    Type: str = DiffTypeNone
    ID: str = ""
    Fields: List[FieldDiff] = field(default_factory=list)
    Objects: List[ObjectDiff] = field(default_factory=list)
    TaskGroups: List[TaskGroupDiff] = field(default_factory=list)


# --------------------------------------------------------------------------
# Flattening: dataclass → {dotted path: rendered string} for primitive leaves.

_PRIMITIVES = (str, int, float, bool)


def _render(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _flatten(obj: Any, prefix: str = "", filter_keys: Tuple[str, ...] = ()
             ) -> Dict[str, str]:
    """Primitive leaves of a dataclass/dict/list as {path: string}.

    Nested dataclasses and lists of dataclasses are skipped — they are
    diffed structurally as child objects, not as flat fields (the
    reference's flatmap.Flatten primitiveOnly behavior).
    """
    out: Dict[str, str] = {}
    if obj is None:
        return out
    if dataclasses.is_dataclass(obj):
        for f in dataclasses.fields(obj):
            if f.name.startswith("_") or f.name in filter_keys:
                continue
            _flatten_value(f"{prefix}{f.name}", getattr(obj, f.name), out)
        return out
    raise TypeError(f"cannot flatten {type(obj)!r}")


def _flatten_value(key: str, v: Any, out: Dict[str, str]) -> None:
    """Flatten one value: primitives directly, dicts/lists of primitives (or
    nested containers, e.g. driver Config) recursively; nested dataclasses
    are skipped — they diff structurally as child objects."""
    if isinstance(v, _PRIMITIVES):
        out[key] = _render(v)
    elif isinstance(v, dict):
        for k in sorted(v, key=str):
            _flatten_value(f"{key}[{k}]", v[k], out)
    elif isinstance(v, (list, tuple)):
        for i, vv in enumerate(v):
            if dataclasses.is_dataclass(vv):
                break
            _flatten_value(f"{key}[{i}]", vv, out)


def _field_diffs(old_flat: Dict[str, str], new_flat: Dict[str, str],
                 contextual: bool) -> List[FieldDiff]:
    """Diff two flat maps (reference: diff.go:889-933 fieldDiffs)."""
    diffs: List[FieldDiff] = []
    for name in sorted(set(old_flat) | set(new_flat)):
        old_v, new_v = old_flat.get(name), new_flat.get(name)
        if old_v == new_v:
            if contextual:
                diffs.append(FieldDiff(DiffTypeNone, name, old_v or "",
                                       new_v or ""))
            continue
        if old_v is None:
            diffs.append(FieldDiff(DiffTypeAdded, name, "", new_v))
        elif new_v is None:
            diffs.append(FieldDiff(DiffTypeDeleted, name, old_v, ""))
        else:
            diffs.append(FieldDiff(DiffTypeEdited, name, old_v, new_v))
    return diffs


def _object_diff(old: Any, new: Any, name: str, contextual: bool,
                 filter_keys: Tuple[str, ...] = ()) -> Optional[ObjectDiff]:
    """Diff two optional dataclasses into one ObjectDiff, or None if equal
    (reference: diff.go:461-493 serviceDiff et al.)."""
    if old is None and new is None:
        return None
    diff = ObjectDiff(Name=name)
    if old is None:
        diff.Type = DiffTypeAdded
        diff.Fields = _field_diffs({}, _flatten(new, filter_keys=filter_keys),
                                   contextual)
    elif new is None:
        diff.Type = DiffTypeDeleted
        diff.Fields = _field_diffs(_flatten(old, filter_keys=filter_keys), {},
                                   contextual)
    else:
        old_flat = _flatten(old, filter_keys=filter_keys)
        new_flat = _flatten(new, filter_keys=filter_keys)
        if old_flat == new_flat:
            if not contextual:
                return None
            diff.Fields = _field_diffs(old_flat, new_flat, contextual)
        else:
            diff.Type = DiffTypeEdited
            diff.Fields = _field_diffs(old_flat, new_flat, contextual)
    return diff


def _keyed_object_diffs(old_list: List[Any], new_list: List[Any],
                        name: str, key, contextual: bool) -> List[ObjectDiff]:
    """Diff two lists of dataclasses matched by `key(item)`; unmatched items
    become Added/Deleted (reference: diff.go:494-526 serviceDiffs —
    the reference matches set-wise by content hash; we match by semantic
    key so edits render as Edited rather than Deleted+Added). Duplicate
    keys are disambiguated by occurrence index so no item is collapsed."""

    def keyed(items) -> Dict[Tuple[str, int], Any]:
        seen: Dict[str, int] = {}
        out: Dict[Tuple[str, int], Any] = {}
        for item in items:
            k = str(key(item))
            n = seen.get(k, 0)
            seen[k] = n + 1
            out[(k, n)] = item
        return out

    old_by = keyed(old_list)
    new_by = keyed(new_list)
    out: List[ObjectDiff] = []
    for k in sorted(set(old_by) | set(new_by)):
        d = _object_diff(old_by.get(k), new_by.get(k), name, contextual)
        if d is not None and (d.Type != DiffTypeNone or contextual):
            out.append(d)
    return out


def _sort_objects(objs: List[ObjectDiff]) -> List[ObjectDiff]:
    return sorted(objs, key=lambda o: (_TYPE_ORDER.get(o.Type, 9), o.Name))


def _constraint_key(c) -> str:
    return f"{c.LTarget}{c.Operand}{c.RTarget}"


# --------------------------------------------------------------------------
# Per-struct diffs, mirroring the reference's coverage.


def _resources_diff(old, new, contextual: bool) -> Optional[ObjectDiff]:
    """(reference: diff.go:588-659 Resources.Diff + network diffs)"""
    d = _object_diff(old, new, "Resources", contextual)
    old_nets = list(old.Networks) if old is not None else []
    new_nets = list(new.Networks) if new is not None else []
    net_diffs: List[ObjectDiff] = []
    for i in range(max(len(old_nets), len(new_nets))):
        o = old_nets[i] if i < len(old_nets) else None
        n = new_nets[i] if i < len(new_nets) else None
        nd = _object_diff(o, n, "Network", contextual,
                          filter_keys=("Device", "CIDR", "IP"))
        if nd is None:
            # Scalars equal — ports may still differ; diff them below.
            nd = ObjectDiff(Name="Network")
        for label, getter, dyn in (
                ("Static Port", lambda x: x.ReservedPorts, False),
                ("Dynamic Port", lambda x: x.DynamicPorts, True)):
            ports = _keyed_object_diffs(
                getter(o) if o else [], getter(n) if n else [],
                label, lambda p: p.Label, contextual)
            # Dynamic port values are scheduler-assigned; hide them
            # (reference: diff.go:701-752 portDiffs `dynamic`).
            if dyn:
                for pd in ports:
                    pd.Fields = [f for f in pd.Fields if f.Name != "Value"]
            nd.Objects.extend(ports)
        if nd.Type == DiffTypeNone and any(
                od.Type != DiffTypeNone for od in nd.Objects):
            nd.Type = DiffTypeEdited
        if nd.Type != DiffTypeNone or contextual:
            net_diffs.append(nd)
    if net_diffs:
        if d is None:
            d = ObjectDiff(Type=DiffTypeEdited, Name="Resources")
        elif d.Type == DiffTypeNone and any(
                n.Type != DiffTypeNone for n in net_diffs):
            d.Type = DiffTypeEdited
        d.Objects.extend(_sort_objects(net_diffs))
    return d


def _service_diffs(old_list, new_list, contextual: bool) -> List[ObjectDiff]:
    """(reference: diff.go:461-587 service + check diffs)"""
    old_by = {s.Name: s for s in old_list}
    new_by = {s.Name: s for s in new_list}
    out: List[ObjectDiff] = []
    for name in sorted(set(old_by) | set(new_by)):
        o, n = old_by.get(name), new_by.get(name)
        d = _object_diff(o, n, "Service", contextual)
        checks = _keyed_object_diffs(
            list(o.Checks) if o else [], list(n.Checks) if n else [],
            "Check", lambda c: c.Name, contextual)
        if checks:
            changed = any(c.Type != DiffTypeNone for c in checks)
            if d is None:
                d = ObjectDiff(
                    Type=DiffTypeEdited if changed else DiffTypeNone,
                    Name="Service")
            elif d.Type == DiffTypeNone and changed:
                d.Type = DiffTypeEdited
            d.Objects.extend(checks)
        if d is not None and (d.Type != DiffTypeNone or contextual):
            out.append(d)
    return out


def task_diff(old, new, contextual: bool = False) -> TaskDiff:
    """Diff two Tasks (reference: diff.go:318-395 Task.Diff)."""
    diff = TaskDiff()
    if old is None and new is None:
        return diff
    if old is None:
        diff.Type, diff.Name = DiffTypeAdded, new.Name
        diff.Fields = _field_diffs({}, _flatten(new), contextual)
    elif new is None:
        diff.Type, diff.Name = DiffTypeDeleted, old.Name
        diff.Fields = _field_diffs(_flatten(old), {}, contextual)
    else:
        diff.Name = new.Name
        old_flat, new_flat = _flatten(old), _flatten(new)
        diff.Fields = _field_diffs(old_flat, new_flat, contextual)
        if any(f.Type != DiffTypeNone for f in diff.Fields):
            diff.Type = DiffTypeEdited

    objs: List[ObjectDiff] = []
    objs.extend(_keyed_object_diffs(
        list(old.Constraints) if old else [],
        list(new.Constraints) if new else [],
        "Constraint", _constraint_key, contextual))
    r = _resources_diff(old.Resources if old else None,
                        new.Resources if new else None, contextual)
    if r is not None and (r.Type != DiffTypeNone or contextual):
        objs.append(r)
    lc = _object_diff(old.LogConfig if old else None,
                      new.LogConfig if new else None, "LogConfig", contextual)
    if lc is not None and (lc.Type != DiffTypeNone or contextual):
        objs.append(lc)
    objs.extend(_service_diffs(list(old.Services) if old else [],
                               list(new.Services) if new else [], contextual))
    objs.extend(_keyed_object_diffs(
        list(old.Artifacts) if old else [],
        list(new.Artifacts) if new else [],
        "Artifact", lambda a: a.GetterSource, contextual))
    diff.Objects = _sort_objects(objs)
    if diff.Type == DiffTypeNone and any(
            o.Type != DiffTypeNone for o in diff.Objects):
        diff.Type = DiffTypeEdited
    return diff


def task_group_diff(old, new, contextual: bool = False) -> TaskGroupDiff:
    """Diff two TaskGroups (reference: diff.go:177-235 TaskGroup.Diff)."""
    diff = TaskGroupDiff()
    if old is None and new is None:
        return diff
    if old is None:
        diff.Type, diff.Name = DiffTypeAdded, new.Name
        diff.Fields = _field_diffs({}, _flatten(new), contextual)
    elif new is None:
        diff.Type, diff.Name = DiffTypeDeleted, old.Name
        diff.Fields = _field_diffs(_flatten(old), {}, contextual)
    else:
        diff.Name = new.Name
        diff.Fields = _field_diffs(_flatten(old), _flatten(new), contextual)
        if any(f.Type != DiffTypeNone for f in diff.Fields):
            diff.Type = DiffTypeEdited

    objs: List[ObjectDiff] = []
    objs.extend(_keyed_object_diffs(
        list(old.Constraints) if old else [],
        list(new.Constraints) if new else [],
        "Constraint", _constraint_key, contextual))
    rp = _object_diff(old.RestartPolicy if old else None,
                      new.RestartPolicy if new else None,
                      "RestartPolicy", contextual)
    if rp is not None and (rp.Type != DiffTypeNone or contextual):
        objs.append(rp)
    diff.Objects = _sort_objects(objs)

    old_tasks = {t.Name: t for t in (old.Tasks if old else [])}
    new_tasks = {t.Name: t for t in (new.Tasks if new else [])}
    tasks: List[TaskDiff] = []
    for name in sorted(set(old_tasks) | set(new_tasks)):
        td = task_diff(old_tasks.get(name), new_tasks.get(name), contextual)
        if td.Type != DiffTypeNone or contextual:
            tasks.append(td)
    diff.Tasks = sorted(tasks, key=lambda t: (_TYPE_ORDER.get(t.Type, 9),
                                              t.Name))
    if diff.Type == DiffTypeNone and (
            any(o.Type != DiffTypeNone for o in diff.Objects)
            or any(t.Type != DiffTypeNone for t in diff.Tasks)):
        diff.Type = DiffTypeEdited
    return diff


# Fields excluded from the job-level flat diff — server-maintained bookkeeping
# (reference: diff.go:61 `filter`).
_JOB_FILTER = ("ID", "Status", "StatusDescription", "CreateIndex",
               "ModifyIndex", "JobModifyIndex")


def job_diff(old, new, contextual: bool = False) -> JobDiff:
    """Diff two Jobs (reference: diff.go:59-145 Job.Diff).

    Either side may be None (pure registration / pure deregistration).
    """
    diff = JobDiff()
    if old is None and new is None:
        return diff
    if old is not None and new is not None and old.ID != new.ID:
        raise ValueError(f"cannot diff jobs with different IDs: "
                         f"{old.ID!r} vs {new.ID!r}")
    if old is None:
        diff.Type, diff.ID = DiffTypeAdded, new.ID
        diff.Fields = _field_diffs({}, _flatten(new, filter_keys=_JOB_FILTER),
                                   contextual)
    elif new is None:
        diff.Type, diff.ID = DiffTypeDeleted, old.ID
        diff.Fields = _field_diffs(_flatten(old, filter_keys=_JOB_FILTER), {},
                                   contextual)
    else:
        diff.ID = new.ID
        diff.Fields = _field_diffs(_flatten(old, filter_keys=_JOB_FILTER),
                                   _flatten(new, filter_keys=_JOB_FILTER),
                                   contextual)
        if any(f.Type != DiffTypeNone for f in diff.Fields):
            diff.Type = DiffTypeEdited

    objs: List[ObjectDiff] = []
    objs.extend(_keyed_object_diffs(
        list(old.Constraints) if old else [],
        list(new.Constraints) if new else [],
        "Constraint", _constraint_key, contextual))
    up = _object_diff(old.Update if old else None,
                      new.Update if new else None, "Update", contextual)
    if up is not None and (up.Type != DiffTypeNone or contextual):
        objs.append(up)
    per = _object_diff(old.Periodic if old else None,
                       new.Periodic if new else None, "Periodic", contextual)
    if per is not None and (per.Type != DiffTypeNone or contextual):
        objs.append(per)
    diff.Objects = _sort_objects(objs)

    old_tgs = {tg.Name: tg for tg in (old.TaskGroups if old else [])}
    new_tgs = {tg.Name: tg for tg in (new.TaskGroups if new else [])}
    tgs: List[TaskGroupDiff] = []
    for name in sorted(set(old_tgs) | set(new_tgs)):
        tgd = task_group_diff(old_tgs.get(name), new_tgs.get(name),
                              contextual)
        if tgd.Type != DiffTypeNone or contextual:
            tgs.append(tgd)
    diff.TaskGroups = sorted(tgs, key=lambda t: t.Name)
    if diff.Type == DiffTypeNone and (
            any(o.Type != DiffTypeNone for o in diff.Objects)
            or any(t.Type != DiffTypeNone for t in diff.TaskGroups)):
        diff.Type = DiffTypeEdited
    return diff
