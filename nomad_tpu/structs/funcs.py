"""Shared pure functions over the data model (reference: nomad/structs/funcs.go)."""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from .network import NetworkIndex
from .structs import Allocation, Node, Resources


def remove_allocs(allocs: List[Allocation], remove: List[Allocation]) -> List[Allocation]:
    """Remove the given allocations (by ID) from the list (reference: funcs.go:12-31)."""
    remove_ids = {a.ID for a in remove}
    return [a for a in allocs if a.ID not in remove_ids]


def filter_terminal_allocs(allocs: List[Allocation]) -> List[Allocation]:
    """Drop terminal allocations (reference: funcs.go:33-42)."""
    return [a for a in allocs if not a.terminal_status()]


def allocs_fit(node: Node, allocs: List[Allocation],
               net_idx: Optional[NetworkIndex] = None) -> Tuple[bool, str, Resources]:
    """Check whether the allocations fit on the node; returns (fit, exhausted
    dimension, used resources) (reference: funcs.go:44-100)."""
    used = Resources()

    # Reserved resources count as used.
    if node.Reserved is not None:
        used.add(node.Reserved)

    for alloc in allocs:
        if alloc.Resources is not None:
            used.add(alloc.Resources)
            continue
        if not alloc.TaskResources:
            raise ValueError(f"allocation {alloc.ID} has no resources set")
        for task_res in alloc.TaskResources.values():
            used.add(task_res)

    assert node.Resources is not None, "node has no resources"
    fit, dim = node.Resources.superset(used)
    if not fit:
        return False, dim, used

    # Network checks: build (or reuse) the index and look for port collisions
    # and bandwidth overcommit.
    if net_idx is None:
        net_idx = NetworkIndex()
        if net_idx.set_node(node):
            return False, "reserved port collision", used
        if net_idx.add_allocs(allocs):
            return False, "reserved port collision", used
    if net_idx.overcommitted():
        return False, "bandwidth exhausted", used

    return True, "", used


def score_fit(node: Node, util: Resources) -> float:
    """BestFit-v3 bin-pack score in [0, 18]; higher is better
    (reference: funcs.go:102-137, citing Google's datacenter scheduling deck)."""
    assert node.Resources is not None
    node_cpu = float(node.Resources.CPU)
    node_mem = float(node.Resources.MemoryMB)
    if node.Reserved is not None:
        node_cpu -= float(node.Reserved.CPU)
        node_mem -= float(node.Reserved.MemoryMB)

    # Degrade like Go float division: x/0 -> ±Inf, 0/0 -> NaN (no exception).
    def _div(a: float, b: float) -> float:
        if b != 0.0:
            return a / b
        if a == 0.0:
            return math.nan
        return math.copysign(math.inf, a)

    free_pct_cpu = 1.0 - _div(float(util.CPU), node_cpu)
    free_pct_ram = 1.0 - _div(float(util.MemoryMB), node_mem)

    # At 100% utilization total=2 (score 18); at 0% total=20 (score 0).
    total = math.pow(10, free_pct_cpu) + math.pow(10, free_pct_ram) \
        if not (math.isnan(free_pct_cpu) or math.isnan(free_pct_ram)) else math.nan
    score = 20.0 - total
    if math.isnan(score):
        return 0.0
    return max(0.0, min(18.0, score))
