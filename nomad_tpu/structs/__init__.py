"""Data model + wire structs (reference: nomad/structs/)."""

from .structs import (  # explicit re-exports for the commonly used names
    Allocation, AllocListStub, AllocMetric, CheckState, Constraint,
    DesiredUpdates,
    Evaluation, Job, JobListStub, JobPlanResponse, LogConfig, NetworkResource, Node,
    NodeListStub, PeriodicConfig, PeriodicLaunch, Plan, PlanAnnotations,
    PlanResult, Port, Resources, RestartPolicy, Service, ServiceCheck,
    ServiceRegistration, Task,
    TaskArtifact, TaskEvent, TaskGroup, TaskState, UpdateStrategy,
    ValidationError, generate_uuid, job_stub,
)
from .bitmap import Bitmap  # noqa: F401
from .funcs import allocs_fit, filter_terminal_allocs, remove_allocs, score_fit  # noqa: F401
from .network import NetworkIndex  # noqa: F401
from .node_class import (  # noqa: F401
    compute_class, compute_node_class, escaped_constraints, is_unique_namespace,
    unique_namespace,
)
from .codec import decode, encode, from_dict, to_dict  # noqa: F401
