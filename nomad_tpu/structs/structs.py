"""Core data model: jobs, nodes, allocations, evaluations, plans.

Semantics mirror the reference data model (reference: nomad/structs/structs.go)
— same field names (wire compatibility), same statuses, same validation rules —
but the implementation is new. Durations are integer nanoseconds, matching the
reference's Go time.Duration wire encoding.
"""

from __future__ import annotations

import copy
import re
import time as _time
import os
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional

# --- Duration helpers (Go time.Duration is int64 nanoseconds on the wire) ---
NANOSECOND = 1
MICROSECOND = 1000 * NANOSECOND
MILLISECOND = 1000 * MICROSECOND
SECOND = 1000 * MILLISECOND
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE


def ns_to_seconds(ns: int) -> float:
    return ns / SECOND


# --- Statuses and constants (reference: structs.go:547-549, 907-916,
#     1936-1938, 2294-2304, 2598-2612, 2620-2634) ---
NodeStatusInit = "initializing"
NodeStatusReady = "ready"
NodeStatusDown = "down"

JobTypeCore = "_core"
JobTypeService = "service"
JobTypeBatch = "batch"
JobTypeSystem = "system"

JobStatusPending = "pending"
JobStatusRunning = "running"
JobStatusDead = "dead"

JobMinPriority = 1
JobDefaultPriority = 50
JobMaxPriority = 100

CoreJobPriority = JobMaxPriority * 2

TaskStatePending = "pending"
TaskStateRunning = "running"
TaskStateDead = "dead"

TaskDriverFailure = "Driver Failure"
TaskReceived = "Received"
TaskFailedValidation = "Failed Validation"
TaskStarted = "Started"
TaskTerminated = "Terminated"
TaskKilled = "Killed"
TaskRestarting = "Restarting"
TaskNotRestarting = "Not Restarting"
TaskDownloadingArtifacts = "Downloading Artifacts"
TaskArtifactDownloadFailed = "Failed Artifact Download"

AllocDesiredStatusRun = "run"
AllocDesiredStatusStop = "stop"
AllocDesiredStatusEvict = "evict"
AllocDesiredStatusFailed = "failed"

AllocClientStatusPending = "pending"
AllocClientStatusRunning = "running"
AllocClientStatusComplete = "complete"
AllocClientStatusFailed = "failed"

EvalStatusBlocked = "blocked"
EvalStatusPending = "pending"
EvalStatusComplete = "complete"
EvalStatusFailed = "failed"
EvalStatusCancelled = "canceled"

EvalTriggerJobRegister = "job-register"
EvalTriggerJobDeregister = "job-deregister"
EvalTriggerPeriodicJob = "periodic-job"
EvalTriggerNodeUpdate = "node-update"
EvalTriggerScheduled = "scheduled"
EvalTriggerRollingUpdate = "rolling-update"
EvalTriggerMaxPlans = "max-plan-attempts"

CoreJobEvalGC = "eval-gc"
CoreJobNodeGC = "node-gc"
CoreJobJobGC = "job-gc"
CoreJobForceGC = "force-gc"

ConstraintDistinctHosts = "distinct_hosts"
ConstraintRegex = "regexp"
ConstraintVersion = "version"

RestartPolicyModeDelay = "delay"
RestartPolicyModeFail = "fail"

PeriodicSpecCron = "cron"
PeriodicSpecTest = "_internal_test"
PeriodicLaunchSuffix = "/periodic-"

ServiceCheckHTTP = "http"
ServiceCheckTCP = "tcp"
ServiceCheckScript = "script"

DefaultKillTimeout = 5 * SECOND

MinDynamicPort = 20000
MaxDynamicPort = 60000
MaxValidPort = 65536

# Reserved eval IDs used by plans (reference: structs.go:2849-2861)
EvalIdNotBlocked = ""


_UUID_POOL: List[str] = []


def generate_uuid() -> str:
    """Random UUID for IDs (reference: structs.go GenerateUUID, which
    likewise formats crypto/rand bytes directly). IDs are minted per
    placement on the scheduling path, so entropy is drawn in one syscall
    per 512 IDs instead of one urandom read each (a 64-eval storm window
    mints ~3200 — at 64 IDs per draw the urandom syscalls alone were a
    visible slice of the measured t_collect_ms)."""
    try:
        h = _UUID_POOL.pop()  # list.pop is GIL-atomic
    except IndexError:
        hx = os.urandom(16 * 512).hex()
        _UUID_POOL.extend(hx[i:i + 32] for i in range(32, len(hx), 32))
        h = hx[:32]
    # RFC 4122 v4 shape (version/variant nibbles fixed).
    return (f"{h[:8]}-{h[8:12]}-4{h[13:16]}-"
            f"{'89ab'[int(h[16], 16) & 3]}{h[17:20]}-{h[20:]}")


class ValidationError(Exception):
    """Aggregated validation failure (reference: go-multierror usage)."""

    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------


@dataclass
class Port:
    Label: str = ""
    Value: int = 0


@dataclass
class NetworkResource:
    """Network ask/offer on a device (reference: structs.go:840-905)."""

    Device: str = ""
    CIDR: str = ""
    IP: str = ""
    MBits: int = 0
    ReservedPorts: List[Port] = field(default_factory=list)
    DynamicPorts: List[Port] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        # Manual copy: this runs once per chosen placement on the scheduling
        # hot path; deepcopy's reflective walk is ~20x slower.
        return NetworkResource(
            Device=self.Device, CIDR=self.CIDR, IP=self.IP, MBits=self.MBits,
            ReservedPorts=[Port(p.Label, p.Value) for p in self.ReservedPorts],
            DynamicPorts=[Port(p.Label, p.Value) for p in self.DynamicPorts])

    def add(self, delta: "NetworkResource") -> None:
        self.ReservedPorts.extend(Port(p.Label, p.Value)
                                  for p in delta.ReservedPorts)
        self.MBits += delta.MBits
        self.DynamicPorts.extend(Port(p.Label, p.Value)
                                 for p in delta.DynamicPorts)

    def meets_min_resources(self) -> List[str]:
        errs = []
        if self.MBits < 1:
            errs.append(f"minimum MBits value is 1; got {self.MBits}")
        return errs

    def port_labels(self) -> Dict[str, int]:
        labels: Dict[str, int] = {}
        for p in self.ReservedPorts:
            labels[p.Label] = p.Value
        for p in self.DynamicPorts:
            labels[p.Label] = p.Value
        return labels


@dataclass
class Resources:
    """Resource ask/capacity (reference: structs.go:698-838)."""

    CPU: int = 0  # MHz
    MemoryMB: int = 0
    DiskMB: int = 0
    IOPS: int = 0
    Networks: List[NetworkResource] = field(default_factory=list)

    @staticmethod
    def default() -> "Resources":
        return Resources(CPU=100, MemoryMB=10, DiskMB=300, IOPS=0)

    def copy(self) -> "Resources":
        # Hot path: one copy per task per placement (stack._assign_networks).
        return Resources(CPU=self.CPU, MemoryMB=self.MemoryMB,
                         DiskMB=self.DiskMB, IOPS=self.IOPS,
                         Networks=[n.copy() for n in self.Networks])

    def merge(self, other: "Resources") -> None:
        if other.CPU:
            self.CPU = other.CPU
        if other.MemoryMB:
            self.MemoryMB = other.MemoryMB
        if other.DiskMB:
            self.DiskMB = other.DiskMB
        if other.IOPS:
            self.IOPS = other.IOPS
        if other.Networks:
            self.Networks = other.Networks

    def meets_min_resources(self) -> List[str]:
        errs = []
        if self.CPU < 20:
            errs.append(f"minimum CPU value is 20; got {self.CPU}")
        if self.MemoryMB < 10:
            errs.append(f"minimum MemoryMB value is 10; got {self.MemoryMB}")
        if self.DiskMB < 10:
            errs.append(f"minimum DiskMB value is 10; got {self.DiskMB}")
        if self.IOPS < 0:
            errs.append(f"minimum IOPS value is 0; got {self.IOPS}")
        for i, n in enumerate(self.Networks):
            for e in n.meets_min_resources():
                errs.append(f"network resource at index {i} failed: {e}")
        return errs

    def net_index(self, n: NetworkResource) -> int:
        for idx, net in enumerate(self.Networks):
            if net.Device == n.Device:
                return idx
        return -1

    def superset(self, other: "Resources") -> tuple[bool, str]:
        """Fit check; ignores networks (use NetworkIndex for those)."""
        if self.CPU < other.CPU:
            return False, "cpu exhausted"
        if self.MemoryMB < other.MemoryMB:
            return False, "memory exhausted"
        if self.DiskMB < other.DiskMB:
            return False, "disk exhausted"
        if self.IOPS < other.IOPS:
            return False, "iops exhausted"
        return True, ""

    def add(self, delta: Optional["Resources"]) -> None:
        if delta is None:
            return
        self.CPU += delta.CPU
        self.MemoryMB += delta.MemoryMB
        self.DiskMB += delta.DiskMB
        self.IOPS += delta.IOPS
        for n in delta.Networks:
            idx = self.net_index(n)
            if idx == -1:
                self.Networks.append(n.copy())
            else:
                self.Networks[idx].add(n)


# ---------------------------------------------------------------------------
# Constraints
# ---------------------------------------------------------------------------


@dataclass
class Constraint:
    """Scheduling constraint (reference: structs.go:2249-2291)."""

    LTarget: str = ""
    RTarget: str = ""
    Operand: str = ""

    def __str__(self) -> str:
        return f"{self.LTarget} {self.Operand} {self.RTarget}"

    def validate(self) -> List[str]:
        errs = []
        if not self.Operand:
            errs.append("Missing constraint operand")
        if self.Operand == ConstraintRegex:
            try:
                re.compile(self.RTarget)
            except re.error as e:
                errs.append(f"Regular expression failed to compile: {e}")
        elif self.Operand == ConstraintVersion:
            from .version import parse_version_constraint

            try:
                parse_version_constraint(self.RTarget)
            except ValueError as e:
                errs.append(f"Version constraint is invalid: {e}")
        return errs


# ---------------------------------------------------------------------------
# Services
# ---------------------------------------------------------------------------


@dataclass
class ServiceCheck:
    """Consul-style health check (reference: structs.go:1494-1560)."""

    Name: str = ""
    Type: str = ""
    Command: str = ""
    Args: List[str] = field(default_factory=list)
    Path: str = ""
    Protocol: str = ""
    Interval: int = 0  # ns
    Timeout: int = 0  # ns

    def validate(self) -> List[str]:
        errs = []
        t = self.Type.lower()
        if t not in (ServiceCheckTCP, ServiceCheckHTTP, ServiceCheckScript):
            errs.append(f'service check must be either http, tcp or script type, got: "{self.Type}"')
            return errs
        if t == ServiceCheckHTTP and not self.Path:
            errs.append("service checks of http type must have a valid http path")
        if t == ServiceCheckScript and not self.Command:
            errs.append("service checks of script type must have a valid script path")
        if self.Interval < 10 * SECOND:
            errs.append("interval must be at least 10s")
        return errs

    def requires_port(self) -> bool:
        return self.Type.lower() in (ServiceCheckHTTP, ServiceCheckTCP)


@dataclass
class Service:
    """Service registration spec (reference: structs.go:1563-1676)."""

    Name: str = ""
    Tags: List[str] = field(default_factory=list)
    PortLabel: str = ""
    Checks: List[ServiceCheck] = field(default_factory=list)

    _VALID_NAME = re.compile(r"^[a-zA-Z0-9\-]+$")

    def init_fields(self, job: str, task_group: str, task: str) -> None:
        self.Name = (
            self.Name.replace("${JOB}", job)
            .replace("${TASKGROUP}", task_group)
            .replace("${TASK}", task)
        )
        if not self.Name:
            self.Name = f"{job}-{task_group}-{task}"
        for check in self.Checks:
            if not check.Name:
                check.Name = f"service: {self.Name!r} check"

    def validate(self) -> List[str]:
        errs = []
        if not Service._VALID_NAME.match(self.Name):
            errs.append(
                f"service name must be valid per {Service._VALID_NAME.pattern!r}; got {self.Name!r}"
            )
        for check in self.Checks:
            for e in check.validate():
                errs.append(f"check {check.Name} validation failed: {e}")
            if not self.PortLabel and check.requires_port():
                errs.append(f"check {check.Name} is a {check.Type} check but the service has no port")
        return errs


# Service registry check/instance statuses. The registry is this framework's
# standalone replacement for the reference's external Consul dependency
# (command/agent/consul/syncer.go): registrations live in the replicated
# state store and are queryable cluster-wide with blocking queries.
CheckStatusPassing = "passing"
CheckStatusWarning = "warning"
CheckStatusCritical = "critical"
CheckStatusUnknown = "unknown"


@dataclass
class CheckState:
    """Latest result of one health check run against a registered service."""

    Name: str = ""
    Type: str = ""
    Status: str = CheckStatusUnknown
    Output: str = ""
    Timestamp: float = 0.0


@dataclass
class ServiceRegistration:
    """One live instance of a service in the cluster registry.

    The reference registers AgentServiceRegistrations with the node-local
    Consul agent (consul/syncer.go:723-743); here the registration is a
    first-class replicated object written through the FSM, so discovery
    queries hit the same MVCC store as everything else.
    """

    ID: str = ""           # unique instance id (alloc+task+service, or agent)
    ServiceName: str = ""
    Tags: List[str] = field(default_factory=list)
    JobID: str = ""
    AllocID: str = ""
    TaskName: str = ""
    NodeID: str = ""
    Address: str = ""
    Port: int = 0
    Status: str = CheckStatusUnknown  # worst check status; passing if no checks
    Checks: List[CheckState] = field(default_factory=list)
    CreateIndex: int = 0
    ModifyIndex: int = 0

    def copy(self) -> "ServiceRegistration":
        out = replace(self)
        out.Tags = list(self.Tags)
        out.Checks = [replace(c) for c in self.Checks]
        return out

    def derive_status(self) -> str:
        """Worst-of over check states (Consul health aggregation order)."""
        if not self.Checks:
            return CheckStatusPassing
        order = (CheckStatusCritical, CheckStatusUnknown, CheckStatusWarning,
                 CheckStatusPassing)
        for status in order:
            if any(c.Status == status for c in self.Checks):
                return status
        return CheckStatusUnknown


# ---------------------------------------------------------------------------
# Tasks
# ---------------------------------------------------------------------------


@dataclass
class LogConfig:
    """Task log rotation config (reference: structs.go:1678-1702)."""

    MaxFiles: int = 10
    MaxFileSizeMB: int = 10

    def validate(self) -> List[str]:
        errs = []
        if self.MaxFiles < 1:
            errs.append(f"minimum number of files is 1; got {self.MaxFiles}")
        if self.MaxFileSizeMB < 1:
            errs.append(f"minimum file size is 1MB; got {self.MaxFileSizeMB}")
        return errs


@dataclass
class TaskArtifact:
    """Remote artifact to fetch into the task dir (reference: structs.go:2142-2240)."""

    GetterSource: str = ""
    GetterOptions: Dict[str, str] = field(default_factory=dict)
    RelativeDest: str = "local/"

    def validate(self) -> List[str]:
        errs = []
        if not self.GetterSource:
            errs.append("source must be specified")
        # Verify the destination doesn't escape the task's directory.
        import posixpath

        dest = posixpath.normpath(posixpath.join("/", self.RelativeDest))
        if not dest.startswith("/"):
            errs.append("destination escapes task's directory")
        return errs


@dataclass
class Task:
    """A unit of work executed by a driver (reference: structs.go:1704-1934)."""

    Name: str = ""
    Driver: str = ""
    User: str = ""
    Config: Dict[str, Any] = field(default_factory=dict)
    Env: Dict[str, str] = field(default_factory=dict)
    Services: List[Service] = field(default_factory=list)
    Constraints: List[Constraint] = field(default_factory=list)
    Resources: Optional[Resources] = None
    Meta: Dict[str, str] = field(default_factory=dict)
    KillTimeout: int = DefaultKillTimeout  # ns
    LogConfig: Optional[LogConfig] = None
    Artifacts: List[TaskArtifact] = field(default_factory=list)

    _VALID_NAME = re.compile(r"^[a-zA-Z0-9\-_]{1,128}$")

    def copy(self) -> "Task":
        return copy.deepcopy(self)

    def init_fields(self, job: "Job", tg: "TaskGroup") -> None:
        if self.LogConfig is None:
            self.LogConfig = LogConfig()
        for service in self.Services:
            service.init_fields(job.Name, tg.Name, self.Name)

    def validate(self) -> List[str]:
        errs = []
        if not self.Name:
            errs.append("Missing task name")
        elif not Task._VALID_NAME.match(self.Name):
            errs.append(
                "Task name must consist of alphanumeric characters, dashes or underscores"
            )
        if not self.Driver:
            errs.append("Missing task driver")
        if self.KillTimeout < 0:
            errs.append("KillTimeout must be a positive value")
        if self.Resources is None:
            errs.append("Missing task resources")
        else:
            errs.extend(self.Resources.meets_min_resources())
            # Ensure the task isn't asking for disk in networks.
            labels: Dict[str, int] = {}
            for net in self.Resources.Networks:
                for port in list(net.ReservedPorts) + list(net.DynamicPorts):
                    if port.Label in labels:
                        errs.append(f"Port label {port.Label} used more than once")
                    labels[port.Label] = port.Value
            for service in self.Services:
                if service.PortLabel and service.PortLabel not in labels:
                    errs.append(
                        f"port label {service.PortLabel!r} referenced by service {service.Name!r} does not exist"
                    )
        if self.LogConfig is not None and self.Resources is not None:
            log_usage = self.LogConfig.MaxFiles * self.LogConfig.MaxFileSizeMB
            if self.Resources.DiskMB <= log_usage:
                errs.append(
                    f"log storage ({log_usage} MB) must be less than requested disk capacity ({self.Resources.DiskMB} MB)"
                )
        for i, constr in enumerate(self.Constraints):
            for e in constr.validate():
                errs.append(f"Constraint {i + 1} validation failed: {e}")
        for service in self.Services:
            errs.extend(service.validate())
        if self.LogConfig is not None:
            errs.extend(self.LogConfig.validate())
        for i, artifact in enumerate(self.Artifacts):
            for e in artifact.validate():
                errs.append(f"Artifact {i + 1} validation failed: {e}")
        return errs


@dataclass
class TaskState:
    """Client-side task lifecycle state (reference: structs.go:1941-1998)."""

    State: str = TaskStatePending
    Events: List["TaskEvent"] = field(default_factory=list)

    def successful(self) -> bool:
        if self.State != TaskStateDead:
            return False
        if not self.Events:
            return False
        last = self.Events[-1]
        return last.Type == TaskTerminated and last.ExitCode == 0


@dataclass
class TaskEvent:
    """Typed task lifecycle event (reference: structs.go:2037-2140)."""

    Type: str = ""
    Time: int = 0  # unix nanoseconds
    RestartReason: str = ""
    DriverError: str = ""
    ExitCode: int = 0
    Signal: int = 0
    Message: str = ""
    KillError: str = ""
    StartDelay: int = 0
    DownloadError: str = ""
    ValidationError: str = ""

    @staticmethod
    def new(event_type: str) -> "TaskEvent":
        return TaskEvent(Type=event_type, Time=_time.time_ns())


# ---------------------------------------------------------------------------
# Task groups and jobs
# ---------------------------------------------------------------------------


@dataclass
class RestartPolicy:
    """Task restart policy (reference: structs.go:1280-1366)."""

    Attempts: int = 0
    Interval: int = 0  # ns
    Delay: int = 0  # ns
    Mode: str = RestartPolicyModeDelay

    @staticmethod
    def for_job_type(job_type: str) -> Optional["RestartPolicy"]:
        if job_type in (JobTypeService, JobTypeSystem):
            return RestartPolicy(Attempts=2, Interval=1 * MINUTE, Delay=15 * SECOND,
                                 Mode=RestartPolicyModeDelay)
        if job_type == JobTypeBatch:
            return RestartPolicy(Attempts=15, Interval=7 * 24 * HOUR, Delay=15 * SECOND,
                                 Mode=RestartPolicyModeDelay)
        return None

    def validate(self) -> List[str]:
        errs = []
        if self.Mode not in (RestartPolicyModeDelay, RestartPolicyModeFail):
            errs.append(f"Unsupported restart mode: {self.Mode!r}")
            return errs
        if self.Attempts == 0 and self.Mode != RestartPolicyModeFail:
            errs.append(f"Restart policy {self.Mode!r} with {self.Attempts} attempts is ambiguous")
        if self.Interval == 0:
            return errs
        if self.Attempts * self.Delay > self.Interval:
            errs.append(
                f"Nomad can't restart the TaskGroup {self.Attempts} times in an interval "
                f"of {self.Interval} with a delay of {self.Delay}"
            )
        return errs


@dataclass
class TaskGroup:
    """Atomic unit of placement (reference: structs.go:1368-1488)."""

    Name: str = ""
    Count: int = 1
    Constraints: List[Constraint] = field(default_factory=list)
    RestartPolicy: Optional[RestartPolicy] = None
    Tasks: List[Task] = field(default_factory=list)
    Meta: Dict[str, str] = field(default_factory=dict)

    _VALID_NAME = Task._VALID_NAME

    def copy(self) -> "TaskGroup":
        return copy.deepcopy(self)

    def init_fields(self, job: "Job") -> None:
        if self.RestartPolicy is None:
            self.RestartPolicy = RestartPolicy.for_job_type(job.Type)
        for task in self.Tasks:
            task.init_fields(job, self)

    def validate(self) -> List[str]:
        errs = []
        if not self.Name:
            errs.append("Missing task group name")
        elif not TaskGroup._VALID_NAME.match(self.Name):
            errs.append(
                "Task group name must consist of alphanumeric characters, dashes or underscores"
            )
        if self.Count <= 0:
            errs.append("Task group count must be positive")
        if not self.Tasks:
            errs.append("Missing tasks for task group")
        for i, constr in enumerate(self.Constraints):
            for e in constr.validate():
                errs.append(f"Constraint {i + 1} validation failed: {e}")
        if self.RestartPolicy is not None:
            errs.extend(self.RestartPolicy.validate())
        else:
            errs.append("Task Group must have a restart policy")
        tasks: Dict[str, int] = {}
        for idx, task in enumerate(self.Tasks):
            if task.Name in tasks:
                errs.append(f"Task {task.Name} defined multiple times")
            tasks[task.Name] = idx
        for task in self.Tasks:
            for e in task.validate():
                errs.append(f"Task {task.Name} validation failed: {e}")
        return errs

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.Tasks:
            if t.Name == name:
                return t
        return None


@dataclass
class UpdateStrategy:
    """Rolling-update config (reference: structs.go:1152-1168)."""

    Stagger: int = 0  # ns
    MaxParallel: int = 0

    def rolling(self) -> bool:
        return self.Stagger > 0 and self.MaxParallel > 0


@dataclass
class PeriodicConfig:
    """Periodic (cron) launch config (reference: structs.go:1177-1266)."""

    Enabled: bool = False
    Spec: str = ""
    SpecType: str = PeriodicSpecCron
    ProhibitOverlap: bool = False

    def validate(self) -> List[str]:
        if not self.Enabled:
            return []
        errs = []
        if not self.Spec:
            errs.append("Must specify a spec")
            return errs
        if self.SpecType == PeriodicSpecCron:
            from .cron import CronExpr

            try:
                CronExpr.parse(self.Spec)
            except ValueError as e:
                errs.append(f"Invalid cron spec {self.Spec!r}: {e}")
        elif self.SpecType == PeriodicSpecTest:
            pass
        else:
            errs.append(f"Unknown periodic specification type {self.SpecType!r}")
        return errs

    def next(self, from_time: float) -> float:
        """Next launch time (unix seconds) strictly after from_time.

        Returns 0.0 when there is no next launch (reference: structs.go:1243-1263).
        """
        if self.SpecType == PeriodicSpecCron:
            from .cron import CronExpr

            return CronExpr.parse(self.Spec).next(from_time)
        if self.SpecType == PeriodicSpecTest:
            if not self.Spec:
                return 0.0
            times = [float(s) for s in self.Spec.split(",") if s]
            for t in times:
                if t > from_time:
                    return t
            return 0.0
        return 0.0


@dataclass
class Job:
    """Declarative workload specification (reference: structs.go:940-1150)."""

    Region: str = ""
    ID: str = ""
    ParentID: str = ""
    Name: str = ""
    Type: str = ""
    Priority: int = 0
    AllAtOnce: bool = False
    Datacenters: List[str] = field(default_factory=list)
    Constraints: List[Constraint] = field(default_factory=list)
    TaskGroups: List[TaskGroup] = field(default_factory=list)
    Update: UpdateStrategy = field(default_factory=UpdateStrategy)
    Periodic: Optional[PeriodicConfig] = None
    Meta: Dict[str, str] = field(default_factory=dict)
    Status: str = ""
    StatusDescription: str = ""
    CreateIndex: int = 0
    ModifyIndex: int = 0
    JobModifyIndex: int = 0

    def init_fields(self) -> None:
        for tg in self.TaskGroups:
            tg.init_fields(self)

    def copy(self) -> "Job":
        return copy.deepcopy(self)

    def validate(self) -> List[str]:
        errs = []
        if not self.Region:
            errs.append("Missing job region")
        if not self.ID:
            errs.append("Missing job ID")
        elif " " in self.ID:
            errs.append("Job ID contains a space")
        if not self.Name:
            errs.append("Missing job name")
        if not self.Type:
            errs.append("Missing job type")
        if self.Priority < JobMinPriority or self.Priority > JobMaxPriority:
            errs.append(f"Job priority must be between [{JobMinPriority}, {JobMaxPriority}]")
        if not self.Datacenters:
            errs.append("Missing job datacenters")
        if not self.TaskGroups:
            errs.append("Missing job task groups")
        for idx, constr in enumerate(self.Constraints):
            for e in constr.validate():
                errs.append(f"Constraint {idx + 1} validation failed: {e}")

        taskGroups: Dict[str, int] = {}
        for idx, tg in enumerate(self.TaskGroups):
            if not tg.Name:
                errs.append(f"Job task group {idx + 1} missing name")
            elif tg.Name in taskGroups:
                errs.append(f"Job task group {tg.Name} defined multiple times")
            taskGroups[tg.Name] = idx
            if self.Type == JobTypeSystem and tg.Count != 1:
                errs.append(
                    f"Job task group {tg.Name} should have a count of 1, got {tg.Count}"
                )
        for tg in self.TaskGroups:
            for e in tg.validate():
                errs.append(f"Task group {tg.Name} validation failed: {e}")
        if self.Periodic is not None and self.Periodic.Enabled:
            if self.Type != JobTypeBatch:
                errs.append(f"Periodic can only be used with {JobTypeBatch!r} scheduler")
            errs.extend(self.Periodic.validate())
        return errs

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.TaskGroups:
            if tg.Name == name:
                return tg
        return None

    def is_periodic(self) -> bool:
        return self.Periodic is not None and self.Periodic.Enabled


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


@dataclass
class Node:
    """A client machine in the cluster (reference: structs.go:551-688)."""

    ID: str = ""
    Datacenter: str = ""
    Name: str = ""
    HTTPAddr: str = ""
    Attributes: Dict[str, str] = field(default_factory=dict)
    Resources: Optional[Resources] = None
    Reserved: Optional[Resources] = None
    Links: Dict[str, str] = field(default_factory=dict)
    Meta: Dict[str, str] = field(default_factory=dict)
    NodeClass: str = ""
    ComputedClass: str = ""
    Drain: bool = False
    Status: str = ""
    StatusDescription: str = ""
    StatusUpdatedAt: int = 0
    CreateIndex: int = 0
    ModifyIndex: int = 0

    def copy(self) -> "Node":
        return copy.deepcopy(self)

    def terminal_status(self) -> bool:
        return self.Status == NodeStatusDown

    def stub(self) -> "NodeListStub":
        return NodeListStub(
            ID=self.ID,
            Datacenter=self.Datacenter,
            Name=self.Name,
            NodeClass=self.NodeClass,
            Drain=self.Drain,
            Status=self.Status,
            StatusDescription=self.StatusDescription,
            CreateIndex=self.CreateIndex,
            ModifyIndex=self.ModifyIndex,
        )


@dataclass
class NodeListStub:
    ID: str = ""
    Datacenter: str = ""
    Name: str = ""
    NodeClass: str = ""
    Drain: bool = False
    Status: str = ""
    StatusDescription: str = ""
    CreateIndex: int = 0
    ModifyIndex: int = 0


def should_drain_node(status: str) -> bool:
    """(reference: structs.go:ShouldDrainNode)"""
    if status in (NodeStatusInit, NodeStatusReady):
        return False
    return status == NodeStatusDown


def valid_node_status(status: str) -> bool:
    return status in (NodeStatusInit, NodeStatusReady, NodeStatusDown)


# ---------------------------------------------------------------------------
# Allocations
# ---------------------------------------------------------------------------


@dataclass
class AllocMetric:
    """Per-placement scheduling telemetry (reference: structs.go:2497-2595)."""

    NodesEvaluated: int = 0
    NodesFiltered: int = 0
    NodesAvailable: Dict[str, int] = field(default_factory=dict)
    ClassFiltered: Dict[str, int] = field(default_factory=dict)
    ConstraintFiltered: Dict[str, int] = field(default_factory=dict)
    NodesExhausted: int = 0
    ClassExhausted: Dict[str, int] = field(default_factory=dict)
    DimensionExhausted: Dict[str, int] = field(default_factory=dict)
    Scores: Dict[str, float] = field(default_factory=dict)
    AllocationTime: int = 0  # ns
    CoalescedFailures: int = 0

    def copy(self) -> "AllocMetric":
        # Hot path: every placed allocation snapshots the eval's metrics
        # (reference: alloc.Metrics). Values are scalars; dict() per field
        # replaces deepcopy's reflective walk.
        return AllocMetric(
            NodesEvaluated=self.NodesEvaluated,
            NodesFiltered=self.NodesFiltered,
            NodesAvailable=dict(self.NodesAvailable),
            ClassFiltered=dict(self.ClassFiltered),
            ConstraintFiltered=dict(self.ConstraintFiltered),
            NodesExhausted=self.NodesExhausted,
            ClassExhausted=dict(self.ClassExhausted),
            DimensionExhausted=dict(self.DimensionExhausted),
            Scores=dict(self.Scores),
            AllocationTime=self.AllocationTime,
            CoalescedFailures=self.CoalescedFailures)

    def evaluate_node(self) -> None:
        self.NodesEvaluated += 1

    def filter_node(self, node: Optional[Node], constraint: str) -> None:
        self.NodesFiltered += 1
        if node is not None and node.NodeClass:
            self.ClassFiltered[node.NodeClass] = self.ClassFiltered.get(node.NodeClass, 0) + 1
        if constraint:
            self.ConstraintFiltered[constraint] = self.ConstraintFiltered.get(constraint, 0) + 1

    def exhausted_node(self, node: Optional[Node], dimension: str) -> None:
        self.NodesExhausted += 1
        if node is not None and node.NodeClass:
            self.ClassExhausted[node.NodeClass] = self.ClassExhausted.get(node.NodeClass, 0) + 1
        if dimension:
            self.DimensionExhausted[dimension] = self.DimensionExhausted.get(dimension, 0) + 1

    def score_node(self, node: Node, name: str, score: float) -> None:
        key = f"{node.ID}.{name}"
        self.Scores[key] = score


@dataclass
class Allocation:
    """A placement of a task group on a node (reference: structs.go:2308-2495)."""

    ID: str = ""
    EvalID: str = ""
    Name: str = ""
    NodeID: str = ""
    JobID: str = ""
    Job: Optional[Job] = None
    TaskGroup: str = ""
    Resources: Optional[Resources] = None
    TaskResources: Dict[str, Resources] = field(default_factory=dict)
    Services: Dict[str, str] = field(default_factory=dict)
    Metrics: Optional[AllocMetric] = None
    DesiredStatus: str = ""
    DesiredDescription: str = ""
    ClientStatus: str = ""
    ClientDescription: str = ""
    TaskStates: Dict[str, TaskState] = field(default_factory=dict)
    CreateIndex: int = 0
    ModifyIndex: int = 0
    AllocModifyIndex: int = 0

    def copy(self) -> "Allocation":
        return copy.deepcopy(self)

    def terminal_status(self) -> bool:
        """Terminal by desired or client state (reference: structs.go:2377-2394)."""
        if self.DesiredStatus in (AllocDesiredStatusStop, AllocDesiredStatusEvict,
                                  AllocDesiredStatusFailed):
            return True
        return self.ClientStatus in (AllocClientStatusComplete, AllocClientStatusFailed)

    def ran_successfully(self) -> bool:
        if not self.TaskStates:
            return False
        return all(ts.successful() for ts in self.TaskStates.values())

    def stub(self) -> "AllocListStub":
        return AllocListStub(
            ID=self.ID,
            EvalID=self.EvalID,
            Name=self.Name,
            NodeID=self.NodeID,
            JobID=self.JobID,
            TaskGroup=self.TaskGroup,
            DesiredStatus=self.DesiredStatus,
            DesiredDescription=self.DesiredDescription,
            ClientStatus=self.ClientStatus,
            ClientDescription=self.ClientDescription,
            TaskStates=self.TaskStates,
            CreateIndex=self.CreateIndex,
            ModifyIndex=self.ModifyIndex,
        )


@dataclass
class AllocListStub:
    ID: str = ""
    EvalID: str = ""
    Name: str = ""
    NodeID: str = ""
    JobID: str = ""
    TaskGroup: str = ""
    DesiredStatus: str = ""
    DesiredDescription: str = ""
    ClientStatus: str = ""
    ClientDescription: str = ""
    TaskStates: Dict[str, TaskState] = field(default_factory=dict)
    CreateIndex: int = 0
    ModifyIndex: int = 0


@dataclass
class JobListStub:
    ID: str = ""
    ParentID: str = ""
    Name: str = ""
    Type: str = ""
    Priority: int = 0
    Status: str = ""
    StatusDescription: str = ""
    CreateIndex: int = 0
    ModifyIndex: int = 0


def job_stub(j: Job) -> JobListStub:
    return JobListStub(
        ID=j.ID, ParentID=j.ParentID, Name=j.Name, Type=j.Type, Priority=j.Priority,
        Status=j.Status, StatusDescription=j.StatusDescription,
        CreateIndex=j.CreateIndex, ModifyIndex=j.ModifyIndex,
    )


# ---------------------------------------------------------------------------
# Evaluations and plans
# ---------------------------------------------------------------------------


@dataclass
class Evaluation:
    """A scheduling work item (reference: structs.go:2642-2843)."""

    ID: str = ""
    Priority: int = 0
    Type: str = ""
    TriggeredBy: str = ""
    JobID: str = ""
    # Home region of the eval's job (federation): stamped at creation
    # when ServerConfig.federation is enabled so the broker can route
    # region-aware; "" (the default, and the only value when federation
    # is off) means region-agnostic — pre-federation behavior.
    Region: str = ""
    JobModifyIndex: int = 0
    NodeID: str = ""
    NodeModifyIndex: int = 0
    Status: str = ""
    StatusDescription: str = ""
    Wait: int = 0  # ns
    NextEval: str = ""
    PreviousEval: str = ""
    BlockedEval: str = ""
    FailedTGAllocs: Dict[str, AllocMetric] = field(default_factory=dict)
    ClassEligibility: Dict[str, bool] = field(default_factory=dict)
    EscapedComputedClass: bool = False
    AnnotatePlan: bool = False
    SnapshotIndex: int = 0
    CreateIndex: int = 0
    ModifyIndex: int = 0

    def copy(self) -> "Evaluation":
        # Hot path: every eval completion copies the eval for its status
        # write. All fields are scalars except the two dicts; deepcopy's
        # reflective walk costs ~100x this.
        out = replace(self)
        out.FailedTGAllocs = {k: v.copy()
                              for k, v in self.FailedTGAllocs.items()}
        out.ClassEligibility = dict(self.ClassEligibility)
        return out

    def terminal_status(self) -> bool:
        return self.Status in (EvalStatusComplete, EvalStatusFailed, EvalStatusCancelled)

    def should_enqueue(self) -> bool:
        if self.Status == EvalStatusPending:
            return True
        if self.Status in (EvalStatusComplete, EvalStatusFailed, EvalStatusBlocked,
                           EvalStatusCancelled):
            return False
        raise ValueError(f"unhandled evaluation ({self.ID}) status {self.Status}")

    def should_block(self) -> bool:
        if self.Status == EvalStatusBlocked:
            return True
        if self.Status in (EvalStatusComplete, EvalStatusFailed, EvalStatusPending,
                           EvalStatusCancelled):
            return False
        raise ValueError(f"unhandled evaluation ({self.ID}) status {self.Status}")

    def make_plan(self, job: Optional[Job], copy_job: bool = True) -> "Plan":
        """(reference: structs.go:2795-2808). copy_job=False lets a hot
        caller alias the snapshot's committed Job — safe because jobs are
        value-frozen in the state store (updates replace the object) and the
        plan only reads it; the reference aliases the pointer the same way."""
        plan = Plan(EvalID=self.ID, Priority=self.Priority)
        if job is not None:
            plan.Job = job.copy() if copy_job else job
            plan.AllAtOnce = job.AllAtOnce
        return plan

    def next_rolling_eval(self, wait: int) -> "Evaluation":
        """(reference: structs.go:2810-2825)"""
        return Evaluation(
            ID=generate_uuid(),
            Priority=self.Priority,
            Type=self.Type,
            TriggeredBy=EvalTriggerRollingUpdate,
            JobID=self.JobID,
            Region=self.Region,
            JobModifyIndex=self.JobModifyIndex,
            Status=EvalStatusPending,
            Wait=wait,
            PreviousEval=self.ID,
        )

    def create_blocked_eval(self, class_eligibility: Dict[str, bool],
                            escaped: bool) -> "Evaluation":
        """(reference: structs.go:2827-2843)"""
        return Evaluation(
            ID=generate_uuid(),
            Priority=self.Priority,
            Type=self.Type,
            TriggeredBy=self.TriggeredBy,
            JobID=self.JobID,
            Region=self.Region,
            JobModifyIndex=self.JobModifyIndex,
            Status=EvalStatusBlocked,
            PreviousEval=self.ID,
            ClassEligibility=class_eligibility,
            EscapedComputedClass=escaped,
        )


@dataclass
class Plan:
    """Scheduler output submitted to the plan applier (reference: structs.go:2845-2928)."""

    EvalID: str = ""
    EvalToken: str = ""
    Priority: int = 0
    AllAtOnce: bool = False
    Job: Optional[Job] = None
    NodeUpdate: Dict[str, List[Allocation]] = field(default_factory=dict)
    NodeAllocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    Annotations: Optional["PlanAnnotations"] = None

    def append_update(self, alloc: Allocation, status: str, desc: str) -> None:
        # Strip the embedded job from a SHALLOW copy before deep-copying:
        # the plan carries the job once, so deep-copying it per evicted alloc
        # would dominate plan construction — and the shallow copy means the
        # store-shared alloc object is never mutated (other threads read it).
        shallow = copy.copy(alloc)
        shallow.Job = None
        new_alloc = copy.deepcopy(shallow)
        new_alloc.DesiredStatus = status
        new_alloc.DesiredDescription = desc
        self.NodeUpdate.setdefault(alloc.NodeID, []).append(new_alloc)

    def pop_update(self, alloc: Allocation) -> None:
        existing = self.NodeUpdate.get(alloc.NodeID, [])
        if existing and existing[-1].ID == alloc.ID:
            existing.pop()
            if not existing:
                self.NodeUpdate.pop(alloc.NodeID, None)

    def append_alloc(self, alloc: Allocation) -> None:
        self.NodeAllocation.setdefault(alloc.NodeID, []).append(alloc)

    def is_no_op(self) -> bool:
        return not self.NodeUpdate and not self.NodeAllocation


@dataclass
class PlanResult:
    """Plan applier's verdict (reference: structs.go:2931-2966)."""

    NodeUpdate: Dict[str, List[Allocation]] = field(default_factory=dict)
    NodeAllocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    RefreshIndex: int = 0
    AllocIndex: int = 0

    def full_commit(self, plan: Plan) -> tuple[bool, int, int]:
        expected = 0
        actual = 0
        for _, allocs in plan.NodeAllocation.items():
            expected += len(allocs)
        for _, allocs in self.NodeAllocation.items():
            actual += len(allocs)
        return expected == actual, expected, actual


@dataclass
class DesiredUpdates:
    Ignore: int = 0
    Place: int = 0
    Migrate: int = 0
    Stop: int = 0
    InPlaceUpdate: int = 0
    DestructiveUpdate: int = 0


@dataclass
class PlanAnnotations:
    """Dry-run plan annotations (reference: structs.go:2970-2984)."""

    DesiredTGUpdates: Dict[str, DesiredUpdates] = field(default_factory=dict)


@dataclass
class JobPlanResponse:
    """Dry-run plan reply (reference: structs.go JobPlanResponse,
    job_endpoint.go:422-526)."""

    Diff: Optional[Any] = None  # structs.diff.JobDiff
    Annotations: Optional["PlanAnnotations"] = None
    FailedTGAllocs: Dict[str, "AllocMetric"] = field(default_factory=dict)
    NextPeriodicLaunch: float = 0.0
    JobModifyIndex: int = 0
    CreatedEvals: List["Evaluation"] = field(default_factory=list)


@dataclass
class PeriodicLaunch:
    """Last launch time of a periodic job (reference: structs.go:1270-1278)."""

    ID: str = ""
    Launch: float = 0.0  # unix seconds
    CreateIndex: int = 0
    ModifyIndex: int = 0
