"""Generic dataclass <-> dict <-> msgpack codec for the wire structs.

The reference serializes all RPC structs with a msgpack codec generated per
struct (reference: nomad/structs/structs.go:3007-3018, structs_codegen.go).
Here a single reflective codec covers every dataclass: field names are the
wire names (the data model uses the reference's CamelCase field naming so the
HTTP API and client library are drop-in compatible).
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Optional, Union, get_args, get_origin, get_type_hints

import msgpack

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def to_dict(obj: Any) -> Any:
    """Recursively convert a dataclass (or container of them) to plain data."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            out[f.name] = to_dict(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def _resolve_hints(cls: type) -> dict[str, Any]:
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = get_type_hints(cls)
        _HINTS_CACHE[cls] = hints
    return hints


def _build(tp: Any, value: Any) -> Any:
    if value is None:
        return None
    origin = get_origin(tp)
    if origin is Union:  # Optional[T] and friends
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return _build(args[0], value)
        return value
    if origin in (list, tuple):
        (item_tp,) = get_args(tp) or (Any,)
        return [_build(item_tp, v) for v in value]
    if origin is dict:
        args = get_args(tp)
        val_tp = args[1] if len(args) == 2 else Any
        return {k: _build(val_tp, v) for k, v in value.items()}
    if isinstance(tp, type) and dataclasses.is_dataclass(tp):
        return from_dict(tp, value)
    return value


def from_dict(cls: type, data: Any) -> Any:
    """Build a dataclass instance from plain data, using type hints."""
    if data is None:
        return None
    if not dataclasses.is_dataclass(cls):
        return data
    hints = _resolve_hints(cls)
    kwargs = {}
    field_names = {f.name for f in dataclasses.fields(cls)}
    for key, value in data.items():
        if key not in field_names:
            continue  # forward compatibility: ignore unknown fields
        kwargs[key] = _build(hints.get(key, Any), value)
    return cls(**kwargs)


def encode(obj: Any) -> bytes:
    """Encode a dataclass to msgpack bytes (reference: structs.go:3007)."""
    return msgpack.packb(to_dict(obj), use_bin_type=True)


def decode(cls: type, buf: bytes) -> Any:
    """Decode msgpack bytes into a dataclass (reference: structs.go:3013)."""
    return from_dict(cls, msgpack.unpackb(buf, raw=False, strict_map_key=False))
