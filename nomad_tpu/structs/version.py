"""Semantic-version constraint parsing and matching.

The reference relies on hashicorp/go-version for `version` constraint operands
(reference: scheduler/feasible.go:407-427). This is a small standalone
implementation of the same constraint grammar: comma-separated clauses of
`[op] version` where op ∈ {=, !=, >, <, >=, <=, ~>} (default `=`).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Tuple

_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)([-.]?(?:[0-9A-Za-z\-~]+(?:\.[0-9A-Za-z\-~]+)*))?$"
)
_CONSTRAINT_RE = re.compile(r"^\s*(=|!=|>=|<=|>|<|~>)?\s*(.+?)\s*$")


@dataclass(frozen=True)
class Version:
    segments: Tuple[int, ...]
    prerelease: str = ""

    @staticmethod
    def parse(s: str) -> "Version":
        m = _VERSION_RE.match(s.strip())
        if not m:
            raise ValueError(f"malformed version: {s!r}")
        segs = tuple(int(p) for p in m.group(1).split("."))
        pre = (m.group(2) or "").lstrip("-.")
        # Pad to 3 segments for comparison stability.
        while len(segs) < 3:
            segs = segs + (0,)
        return Version(segs, pre)

    def _cmp(self, other: "Version") -> int:
        n = max(len(self.segments), len(other.segments))
        a = self.segments + (0,) * (n - len(self.segments))
        b = other.segments + (0,) * (n - len(other.segments))
        if a != b:
            return -1 if a < b else 1
        # A prerelease sorts before the release it precedes.
        if self.prerelease == other.prerelease:
            return 0
        if not self.prerelease:
            return 1
        if not other.prerelease:
            return -1
        return _cmp_prerelease(self.prerelease, other.prerelease)

    def __lt__(self, other):  # type: ignore[override]
        return self._cmp(other) < 0

    def __le__(self, other):  # type: ignore[override]
        return self._cmp(other) <= 0

    def __gt__(self, other):  # type: ignore[override]
        return self._cmp(other) > 0

    def __ge__(self, other):  # type: ignore[override]
        return self._cmp(other) >= 0


def _cmp_prerelease(a: str, b: str) -> int:
    """Semver dot-segment comparison: numeric identifiers compare as
    integers (rc.9 < rc.10), numeric < alphanumeric, alphanumeric compare
    ASCII-lexically, shorter sequence < longer when equal so far."""
    for sa, sb in zip(a.split("."), b.split(".")):
        na, nb = sa.isdigit(), sb.isdigit()
        if na and nb:
            ia, ib = int(sa), int(sb)
            if ia != ib:
                return -1 if ia < ib else 1
        elif na != nb:
            return -1 if na else 1
        elif sa != sb:
            return -1 if sa < sb else 1
    la, lb = len(a.split(".")), len(b.split("."))
    if la != lb:
        return -1 if la < lb else 1
    return 0


@dataclass(frozen=True)
class _Clause:
    op: str
    version: Version
    raw: str

    def check(self, v: Version) -> bool:
        c = v._cmp(self.version)
        if self.op == "=":
            return c == 0
        if self.op == "!=":
            return c != 0
        if self.op == ">":
            return c > 0
        if self.op == "<":
            return c < 0
        if self.op == ">=":
            return c >= 0
        if self.op == "<=":
            return c <= 0
        if self.op == "~>":
            # Pessimistic: >= version, and the leading segments (all but the
            # last specified one) must match.
            if c < 0:
                return False
            raw_segs = self.raw.split(".")
            # "~> 1.2.3" locks 1.2; "~> 1.2" locks 1; "~> 1" still locks 1
            # (>=1, <2), matching go-version's pessimistic operator.
            lock = max(1, len(raw_segs) - 1)
            return v.segments[:lock] == self.version.segments[:lock]
        raise ValueError(f"unknown operator {self.op!r}")


class VersionConstraint:
    def __init__(self, clauses: List[_Clause]):
        self.clauses = clauses

    def check(self, v: Version) -> bool:
        return all(c.check(v) for c in self.clauses)


def parse_version_constraint(spec: str) -> VersionConstraint:
    clauses = []
    for part in spec.split(","):
        m = _CONSTRAINT_RE.match(part)
        if not m or not m.group(2):
            raise ValueError(f"malformed constraint: {part!r}")
        op = m.group(1) or "="
        raw = m.group(2).lstrip("v")
        clauses.append(_Clause(op, Version.parse(raw), raw.split("-")[0]))
    return VersionConstraint(clauses)


def check_version_constraint(lhs_version: str, constraint: str) -> bool:
    """True when lhs_version satisfies the constraint string."""
    try:
        v = Version.parse(lhs_version)
        c = parse_version_constraint(constraint)
    except ValueError:
        return False
    return c.check(v)
