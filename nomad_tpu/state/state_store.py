"""MVCC in-memory state store with secondary indexes, snapshots, and watches.

Equivalent to the reference's go-memdb-backed StateStore (reference:
nomad/state/state_store.go, nomad/state/schema.go) but designed around
per-key version chains instead of immutable radix trees:

  * every write appends (index, value) to the key's version chain and updates
    a live dict; `snapshot()` is O(1) — it just pins the current index as a
    watermark and resolves reads through the chains;
  * secondary indexes (allocs by node/job/eval, evals by job, periodic jobs)
    are ever-membership sets — valid because the relation keys (NodeID, JobID,
    EvalID) are immutable for the life of an object — resolved through the
    primary chains at the snapshot watermark and pruned on compaction;
  * mutations collect watch Items which are notified after commit, powering
    blocking queries (reference: nomad/rpc.go:294-349).

Writes take an externally supplied monotonically increasing `index` (the Raft
log index in a replicated deployment, a local counter in dev mode).
"""

from __future__ import annotations

import threading
import time
import weakref
from bisect import bisect_right
from dataclasses import replace
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

from nomad_tpu.analysis import guarded_by, requires_lock
from nomad_tpu.telemetry import metrics
from nomad_tpu.structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    PeriodicLaunch,
    from_dict,
    to_dict,
)
from nomad_tpu.structs.structs import (
    AllocClientStatusFailed,
    AllocClientStatusRunning,
    CheckStatusCritical,
    EvalStatusBlocked,
    JobStatusDead,
    JobStatusPending,
    JobStatusRunning,
    NodeStatusDown,
    NodeStatusReady,
)

from .watch import Item, Items, NotifyGroup


class _Chain:
    """Version chain for one key: parallel arrays of indexes and values."""

    __slots__ = ("indexes", "values")

    def __init__(self) -> None:
        self.indexes: List[int] = []
        self.values: List[Any] = []

    def append(self, index: int, value: Any) -> None:
        self.indexes.append(index)
        self.values.append(value)

    def at(self, watermark: int) -> Any:
        """Latest value with index <= watermark (None if absent/tombstone)."""
        i = bisect_right(self.indexes, watermark)
        if i == 0:
            return None
        return self.values[i - 1]

    def compact(self, min_watermark: int) -> bool:
        """Drop versions superseded before min_watermark; True if chain empty."""
        i = bisect_right(self.indexes, min_watermark)
        if i > 1:
            del self.indexes[: i - 1]
            del self.values[: i - 1]
        return len(self.values) == 1 and self.values[0] is None


class _Table:
    __slots__ = ("chains", "current")

    def __init__(self) -> None:
        self.chains: Dict[str, _Chain] = {}
        self.current: Dict[str, Any] = {}

    def write(self, index: int, key: str, value: Any) -> None:
        chain = self.chains.get(key)
        if chain is None:
            chain = _Chain()
            self.chains[key] = chain
        chain.append(index, value)
        if value is None:
            self.current.pop(key, None)
        else:
            self.current[key] = value


class SweepSegment:
    """Columnar alloc storage for ONE committed sweep batch: per-alloc id /
    instance-name / node columns plus a frozen per-task-group template the
    rows share everything else with. A 10k-alloc system sweep commits as
    one of these — no per-alloc objects, chains, member-set inserts or
    watch items on the apply path. Rows materialize a real Allocation only
    on first read (`materialize`), and any MUTATION promotes the row out
    of the segment into the exact per-object chain path
    (StateStore._col_promote_locked), so write semantics are unchanged.

    Concurrency: all fields are guarded by the owning StateStore's _lock
    (segments are never shared between stores)."""

    __slots__ = ("index", "job_id", "eval_id", "templates", "tg_idx",
                 "alloc_ids", "names", "node_ids", "live", "n_live",
                 "kind", "_objs")

    def __init__(self, index: int, job_id: str, eval_id: str,
                 templates: List[Allocation], tg_idx: Optional[List[int]],
                 alloc_ids: List[str], names: List[str],
                 node_ids: List[str], kind: str = "system"):
        self.index = index
        self.job_id = job_id
        self.eval_id = eval_id
        self.templates = templates
        self.tg_idx = tg_idx  # None => single template for every row
        self.alloc_ids = alloc_ids
        self.names = names
        self.node_ids = node_ids
        # Which commit path built the batch ("system" sweep / "service"
        # window) — operator observability only, no read-path semantics.
        self.kind = kind
        self.live = [True] * len(alloc_ids)
        self.n_live = len(alloc_ids)
        self._objs: Dict[int, Allocation] = {}  # pos -> materialized

    def materialize(self, pos: int) -> Allocation:
        """Stamp (and cache) the real Allocation for one row. The clone is
        bit-equal to what the per-object commit path would have stored:
        template fields shared (value-frozen contract), identity fields
        and the client-mutable containers fresh, raft indexes = the
        segment's commit index."""
        obj = self._objs.get(pos)
        if obj is not None:
            return obj
        template = self.templates[self.tg_idx[pos] if self.tg_idx else 0]
        obj = object.__new__(Allocation)
        obj.__dict__ = dict(template.__dict__)
        obj.ID = self.alloc_ids[pos]
        obj.Name = self.names[pos]
        obj.NodeID = self.node_ids[pos]
        obj.Services = {}
        obj.TaskStates = {}
        obj.CreateIndex = self.index
        obj.ModifyIndex = self.index
        obj.AllocModifyIndex = self.index
        vec = getattr(template, "_resvec_cache", None)
        if vec is not None:
            obj._resvec_cache = vec
        self._objs[pos] = obj
        return obj

    def serialize(self) -> Dict[str, Any]:
        """Plain-data dump of the LIVE rows for raft snapshot persist.
        No watermark filter is needed: a promoted row's chain version is
        written at this segment's own index, so for every watermark that
        can see this segment the chain dump already carries exactly the
        promoted rows and `live` carries the rest. Shape round-trips
        through msgpack and `deserialize`."""
        keep = [i for i, alive in enumerate(self.live) if alive]
        return {
            "Index": self.index,
            "JobID": self.job_id,
            "EvalID": self.eval_id,
            "Kind": self.kind,
            "Templates": [to_dict(t) for t in self.templates],
            "TGIdx": ([self.tg_idx[i] for i in keep]
                      if self.tg_idx else None),
            "AllocIDs": [self.alloc_ids[i] for i in keep],
            "Names": [self.names[i] for i in keep],
            "NodeIDs": [self.node_ids[i] for i in keep],
        }

    @staticmethod
    def deserialize(data: Dict[str, Any]) -> "SweepSegment":
        templates = [t if isinstance(t, Allocation)
                     else from_dict(Allocation, t)
                     for t in data["Templates"]]
        return SweepSegment(
            index=int(data["Index"]), job_id=data["JobID"],
            eval_id=data["EvalID"], templates=templates,
            tg_idx=(list(data["TGIdx"]) if data.get("TGIdx") else None),
            alloc_ids=list(data["AllocIDs"]), names=list(data["Names"]),
            node_ids=list(data["NodeIDs"]),
            kind=data.get("Kind", "system"))


class _ReadAPI:
    """Read operations shared by StateStore (live view) and StateSnapshot."""

    # Subclasses define _get(table, key) and _iter(table) and _members(...)
    # plus the columnar hooks _col_alloc / _col_members / _col_allocs_all
    # (lazy views over SweepSegment rows).

    # -- nodes --
    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._get("nodes", node_id)

    def nodes(self) -> List[Node]:
        return self._iter("nodes")

    # -- jobs --
    def job_by_id(self, job_id: str) -> Optional[Job]:
        return self._get("jobs", job_id)

    def jobs(self) -> List[Job]:
        return self._iter("jobs")

    def jobs_by_id_prefix(self, prefix: str) -> List[Job]:
        return [j for j in self._iter("jobs") if j.ID.startswith(prefix)]

    def jobs_by_periodic(self, periodic: bool = True) -> List[Job]:
        return [j for j in self._iter("jobs") if j.is_periodic() == periodic]

    def jobs_by_scheduler(self, scheduler_type: str) -> List[Job]:
        return [j for j in self._iter("jobs") if j.Type == scheduler_type]

    def jobs_by_gc(self, gc: bool = True) -> List[Job]:
        # A job is GC-able when it is batch-type (reference: schema.go jobIsGCable)
        from nomad_tpu.structs.structs import JobTypeBatch

        return [j for j in self._iter("jobs") if (j.Type == JobTypeBatch) == gc]

    # -- evals --
    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._get("evals", eval_id)

    def evals(self) -> List[Evaluation]:
        return self._iter("evals")

    def evals_by_job(self, job_id: str) -> List[Evaluation]:
        return self._members("eval_job", job_id, "evals")

    # -- allocs --
    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        found = self._get("allocs", alloc_id)
        if found is None:
            found = self._col_alloc(alloc_id)
        return found

    def allocs(self) -> List[Allocation]:
        return self._iter("allocs") + self._col_allocs_all()

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        return (self._members("alloc_node", node_id, "allocs")
                + self._col_members("node", node_id))

    def allocs_by_node_terminal(self, node_id: str, terminal: bool) -> List[Allocation]:
        return [a for a in self.allocs_by_node(node_id)
                if a.terminal_status() == terminal]

    def allocs_by_job(self, job_id: str) -> List[Allocation]:
        return (self._members("alloc_job", job_id, "allocs")
                + self._col_members("job", job_id))

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        return (self._members("alloc_eval", eval_id, "allocs")
                + self._col_members("eval", eval_id))

    # -- periodic launches --
    def periodic_launch_by_id(self, job_id: str) -> Optional[PeriodicLaunch]:
        return self._get("periodic_launch", job_id)

    def periodic_launches(self) -> List[PeriodicLaunch]:
        return self._iter("periodic_launch")

    # -- service registry --
    def service_by_id(self, reg_id: str):
        return self._get("services", reg_id)

    def services(self) -> List:
        return self._iter("services")

    def services_by_name(self, name: str) -> List:
        return self._members("service_name", name, "services")

    def services_by_node(self, node_id: str) -> List:
        return self._members("service_node", node_id, "services")

    def services_by_alloc(self, alloc_id: str) -> List:
        return self._members("service_alloc", alloc_id, "services")


TABLES = ("nodes", "jobs", "evals", "allocs", "periodic_launch", "services")
_MEMBER_INDEXES = {
    "eval_job": ("evals", lambda e: e.JobID),
    "alloc_node": ("allocs", lambda a: a.NodeID),
    "alloc_job": ("allocs", lambda a: a.JobID),
    "alloc_eval": ("allocs", lambda a: a.EvalID),
    "service_name": ("services", lambda s: s.ServiceName),
    "service_node": ("services", lambda s: s.NodeID),
    "service_alloc": ("services", lambda s: s.AllocID),
}


class StateStore(_ReadAPI):
    """The authoritative in-memory store behind the FSM."""

    # Columnar alloc tables (SweepSegment) and their lazy secondary
    # indexes: commits append whole segments; the per-row id/node indexes
    # are merged in on first READ (_col_flush_locked), so index
    # maintenance never rides the serialized FSM apply.
    _concurrency = guarded_by(
        "_lock", "_col_segments", "_col_by_job", "_col_by_eval",
        "_col_alloc_index", "_col_node_index", "_col_unindexed",
        "_col_batches", "_col_promoted")

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._tables: Dict[str, _Table] = {t: _Table() for t in TABLES}
        self._member_sets: Dict[str, Dict[str, Set[str]]] = {
            name: {} for name in _MEMBER_INDEXES
        }
        self._table_index: Dict[str, int] = {}
        self._latest_index = 0
        self._notify = NotifyGroup()
        self._watermarks: Dict[int, int] = {}  # snapshot token -> watermark
        self._next_token = 0
        # Columnar alloc tables: one SweepSegment per committed sweep
        # batch, plus segment-level (job/eval) and lazily-merged per-row
        # (alloc id / node) indexes.
        self._col_segments: List[SweepSegment] = []
        self._col_by_job: Dict[str, List[SweepSegment]] = {}
        self._col_by_eval: Dict[str, List[SweepSegment]] = {}
        self._col_alloc_index: Dict[str, Tuple[SweepSegment, int]] = {}
        self._col_node_index: Dict[str, List[Tuple[SweepSegment, int]]] = {}
        self._col_unindexed: List[SweepSegment] = []
        # Operator counters (sched-stats `Store` block): columnar batches
        # committed per kind ("system" sweep / "service" window) and rows
        # promoted onto the object chain by mutations, since boot.
        self._col_batches: Dict[str, int] = {}
        self._col_promoted = 0
        # Relaxed fast-path flag (deliberately OUTSIDE the guarded set):
        # set under the lock when the first segment commits, read lock-free
        # by the columnar hooks so non-sweep deployments never pay an extra
        # lock round per alloc read. Monotonic once a store has seen a
        # sweep; a racing reader at the flip boundary just orders before
        # the commit.
        self._has_col = False
        # Change listeners: cb(kind, old, new) fired post-commit. Used to keep
        # the device-resident node tensor in sync (nomad_tpu/tensor/).
        self._listeners: List[Callable[[str, Any, Any], None]] = []

    def add_change_listener(self, cb: Callable[[str, Any, Any], None]) -> None:
        self._listeners.append(cb)

    def _emit(self, events: List[Tuple[str, Any, Any]]) -> None:
        for cb in self._listeners:
            # Batch-aware listeners (the tensor index) take the whole
            # commit's events in one call — a 50-alloc plan then costs one
            # lock acquisition, not fifty.
            batch = getattr(cb, "on_change_batch", None)
            if batch is not None:
                batch(events)
                continue
            for kind, old, new in events:
                cb(kind, old, new)

    def transaction(self):
        """The store's write lock, for callers that must make SEVERAL
        write calls atomic with respect to readers — the FSM wraps one
        raft entry's groups (a sweep group's stops + its segment, plus
        any object co-groups) in `with state.transaction():` so no
        blocking query can observe a torn entry. Reentrant: the inner
        write methods re-acquire freely."""
        return self._lock

    # ------------------------------------------------------------------ reads
    def _get(self, table: str, key: str):
        return self._tables[table].current.get(key)

    def _iter(self, table: str):
        with self._lock:
            return list(self._tables[table].current.values())

    def _members(self, index_name: str, key: str, table: str):  # type: ignore[override]
        with self._lock:
            ids = self._members_sets(index_name).get(key, ())
            cur = self._tables[table].current
            return [cur[i] for i in ids if i in cur]

    def _members_sets(self, index_name: str) -> Dict[str, Set[str]]:
        return self._member_sets[index_name]

    # ------------------------------------------------- columnar alloc reads
    def _col_flush_locked(self) -> None:
        """Merge freshly committed segments into the per-row indexes.
        Runs on the first read that needs them — off the commit path —
        and costs O(rows) once per segment, amortized."""
        if not self._col_unindexed:
            return
        for seg in self._col_unindexed:
            by_alloc = self._col_alloc_index
            by_node = self._col_node_index
            for pos, (aid, nid) in enumerate(zip(seg.alloc_ids,
                                                 seg.node_ids)):
                if not seg.live[pos]:
                    continue  # promoted before the first index merge
                by_alloc[aid] = (seg, pos)
                bucket = by_node.get(nid)
                if bucket is None:
                    by_node[nid] = [(seg, pos)]
                else:
                    bucket.append((seg, pos))
        self._col_unindexed = []

    def _col_alloc(self, alloc_id: str) -> Optional[Allocation]:
        if not self._has_col:
            return None
        with self._lock:
            self._col_flush_locked()
            hit = self._col_alloc_index.get(alloc_id)
            if hit is None:
                return None
            seg, pos = hit
            if not seg.live[pos]:
                return None
            return seg.materialize(pos)

    def _col_members(self, kind: str, key: str) -> List[Allocation]:
        if not self._has_col:
            return []
        with self._lock:
            if kind == "node":
                self._col_flush_locked()
                return [seg.materialize(pos)
                        for seg, pos in self._col_node_index.get(key, ())
                        if seg.live[pos]]
            segs = (self._col_by_job if kind == "job"
                    else self._col_by_eval).get(key, ())
            return [seg.materialize(pos)
                    for seg in segs for pos in range(len(seg.alloc_ids))
                    if seg.live[pos]]

    def _col_allocs_all(self) -> List[Allocation]:
        if not self._has_col:
            return []
        with self._lock:
            return [seg.materialize(pos)
                    for seg in self._col_segments
                    for pos in range(len(seg.alloc_ids))
                    if seg.live[pos]]

    def client_alloc_map(self, node_id: str) -> Tuple[Dict[str, int], int]:
        """The client pull signal — {alloc_id: AllocModifyIndex} plus the
        blocking-query index — WITHOUT materializing columnar rows: a
        sweep-placed alloc's identity and index live in the segment
        columns, so a node's 30s poll never stamps objects it won't run."""
        with self._lock:
            out: Dict[str, int] = {}
            idx = 0
            ids = self._members_sets("alloc_node").get(node_id, ())
            cur = self._tables["allocs"].current
            for aid in ids:
                a = cur.get(aid)
                if a is not None:
                    out[aid] = a.AllocModifyIndex
                    if a.AllocModifyIndex > idx:
                        idx = a.AllocModifyIndex
            if self._col_segments:
                self._col_flush_locked()
                for seg, pos in self._col_node_index.get(node_id, ()):
                    if seg.live[pos]:
                        out[seg.alloc_ids[pos]] = seg.index
                        if seg.index > idx:
                            idx = seg.index
            if not out:
                idx = self.get_index("allocs")
            return out, idx

    def columnar_stats(self) -> Dict[str, Any]:
        """Operator snapshot of the columnar alloc tables (sched-stats
        `Store` block): live segment/row counts, rows promoted onto the
        object chain, and committed batches split by commit path — the
        "which path did the storm take" answer."""
        with self._lock:
            return {
                "Segments": len(self._col_segments),
                "LiveRows": sum(s.n_live for s in self._col_segments),
                "PromotedRows": self._col_promoted,
                "Batches": dict(self._col_batches),
            }

    def get_index(self, table: str) -> int:
        return self._table_index.get(table, 0)

    def latest_index(self) -> int:
        return self._latest_index

    # ------------------------------------------------------------------ watch
    def watch(self, items: Iterable[Item], event: threading.Event) -> None:
        self._notify.watch(items, event)

    def stop_watch(self, items: Iterable[Item], event: threading.Event) -> None:
        self._notify.stop_watch(items, event)

    # ----------------------------------------------------------------- writes
    def _commit(self, index: int, tables: Iterable[str], watch_items: Items,
                scoped: Optional[Dict[str, Set[str]]] = None) -> None:
        # Dedup order is immaterial: every table gets the SAME index and
        # watch items land in a set — no replicated value depends on it.
        # lint: allow(apply_pure, order-independent index assignment)
        for t in set(tables):
            self._table_index[t] = index
            watch_items.add(Item(table=t))
        if index > self._latest_index:
            self._latest_index = index
        self._notify.notify(watch_items, scoped=scoped)

    def _member_add(self, index_name: str, key: str, obj_id: str) -> None:
        self._members_sets(index_name).setdefault(key, set()).add(obj_id)

    # --------------------------------------------------- columnar alloc writes
    def apply_sweep_segment(self, index: int, seg: SweepSegment,
                            rows=None, delta=None, row_node_ids=None,
                            epoch: int = -1) -> None:
        """Commit one columnar sweep batch as ONE scatter: register the
        segment, bump indexes, fire ONE batched trigger set (job/eval/table
        items plus a waiter-intersection over the touched node/alloc keys),
        and hand the per-row usage delta to batch-aware listeners (the
        tensor index) as one scatter-add. No per-alloc work happens here —
        per-row secondary indexes merge lazily on first read, and real
        Allocation objects stamp lazily on first touch."""
        # lint: allow(apply_pure, local metrics timer; never enters state)
        t0 = time.monotonic()
        with self._lock:
            self._col_segments.append(seg)
            self._col_unindexed.append(seg)
            self._col_by_job.setdefault(seg.job_id, []).append(seg)
            self._col_by_eval.setdefault(seg.eval_id, []).append(seg)
            self._col_batches[seg.kind] = \
                self._col_batches.get(seg.kind, 0) + 1
            self._has_col = True
            watch_items = Items([Item(alloc_job=seg.job_id),
                                 Item(alloc_eval=seg.eval_id)])
            # Job status: one live alloc <=> RUNNING, and every segment row
            # is live — skip the O(fleet) derivation when already there.
            jobs: Dict[str, str] = {}
            job = self._get("jobs", seg.job_id)
            if job is not None and job.Status != JobStatusRunning:
                jobs[seg.job_id] = ""
            touched = self._set_job_statuses(index, watch_items, jobs,
                                             eval_delete=False)
            self._commit(index, ["allocs"] + touched, watch_items,
                         scoped={"alloc_node": set(seg.node_ids),
                                 "alloc": set(seg.alloc_ids)})
            for cb in self._listeners:
                sweep_cb = getattr(cb, "on_sweep_batch", None)
                if sweep_cb is not None and delta is not None:
                    sweep_cb(row_node_ids, rows, delta, epoch)
                    continue
                # Generic listener fallback: per-event contract needs the
                # objects — correctness over speed for foreign listeners.
                events = [("alloc", None, seg.materialize(pos))
                          for pos in range(len(seg.alloc_ids))]
                batch = getattr(cb, "on_change_batch", None)
                if batch is not None:
                    batch(events)
                else:
                    for kind, old, new in events:
                        cb(kind, old, new)
        metrics.measure_since(("nomad", "state", "scatter"), t0)
        metrics.incr_counter(("nomad", "state", "sweep_allocs"),
                             len(seg.alloc_ids))
        # Per-path segment counter; the trailing segment is dynamic
        # ("system"/"service"), like the per-type fsm keys.
        metrics.incr_counter(("nomad", "state", "segments", seg.kind))

    def _col_promote_locked(self, alloc_id: str) -> Optional[Allocation]:
        """Promote a columnar row into the exact per-object chain path.
        The materialized value is written into the chain AT THE SEGMENT'S
        COMMIT INDEX, so every snapshot watermark keeps seeing exactly what
        it saw before — the row just changes representation. Callers then
        mutate through the ordinary object path. Caller holds _lock."""
        if not self._has_col:
            return None
        self._col_flush_locked()
        hit = self._col_alloc_index.pop(alloc_id, None)
        if hit is None:
            return None
        seg, pos = hit
        if not seg.live[pos]:
            return None
        obj = seg.materialize(pos)
        seg.live[pos] = False
        seg.n_live -= 1
        self._col_promoted += 1
        self._tables["allocs"].write(seg.index, alloc_id, obj)
        self._member_add("alloc_node", obj.NodeID, alloc_id)
        self._member_add("alloc_job", obj.JobID, alloc_id)
        self._member_add("alloc_eval", obj.EvalID, alloc_id)
        metrics.incr_counter(("nomad", "state", "promote"))
        return obj

    def upsert_node(self, index: int, node: Node) -> None:
        """(reference: state_store.go:91 UpsertNode) Preserves CreateIndex and
        keeps drain/status transitions consistent."""
        with self._lock:
            existing = self._get("nodes", node.ID)
            if existing is not None:
                node.CreateIndex = existing.CreateIndex
            else:
                node.CreateIndex = index
            node.ModifyIndex = index
            self._tables["nodes"].write(index, node.ID, node)
            self._commit(index, ["nodes"], Items([Item(node=node.ID)]))
            self._emit([("node", existing, node)])

    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            existing = self._get("nodes", node_id)
            if existing is None:
                raise KeyError(f"node not found: {node_id}")
            self._tables["nodes"].write(index, node_id, None)
            watch_items = Items([Item(node=node_id)])
            # Cascade: a deregistered node's service instances are gone
            # (the reference relies on the node-local Consul agent dying
            # with the node; the replicated registry must prune explicitly).
            tables = ["nodes"]
            for reg in self._members("service_node", node_id, "services"):
                self._tables["services"].write(index, reg.ID, None)
                watch_items.add(Item(service_name=reg.ServiceName))
                tables.append("services")
            self._commit(index, tables, watch_items)
            self._emit([("node", existing, None)])

    # ------------------------------------------------------- service registry
    def upsert_services(self, index: int, regs: List) -> None:
        """Write service registrations (client sync / server self-reg).

        Identical payloads are skipped entirely: clients re-push ALL of
        their registrations every anti-entropy full sync, and rewriting
        an unchanged registration would bump the services table index —
        waking every blocking query on the name and replaying a no-op
        through every watcher — at a cadence of once per 30s per node.
        """
        with self._lock:
            watch_items = Items()
            touched = False
            for reg in regs:
                existing = self._get("services", reg.ID)
                if existing is not None and self._service_equal(existing, reg):
                    continue
                reg.CreateIndex = (existing.CreateIndex if existing is not None
                                   else index)
                reg.ModifyIndex = index
                self._tables["services"].write(index, reg.ID, reg)
                self._member_add("service_name", reg.ServiceName, reg.ID)
                self._member_add("service_node", reg.NodeID, reg.ID)
                self._member_add("service_alloc", reg.AllocID, reg.ID)
                watch_items.add(Item(service_name=reg.ServiceName))
                touched = True
            if touched:
                self._commit(index, ["services"], watch_items)

    @staticmethod
    def _service_equal(a, b) -> bool:
        """Content equality modulo raft indexes (which the store assigns)."""
        return (a.ServiceName == b.ServiceName and a.Tags == b.Tags
                and a.JobID == b.JobID and a.AllocID == b.AllocID
                and a.TaskName == b.TaskName and a.NodeID == b.NodeID
                and a.Address == b.Address and a.Port == b.Port
                and a.Status == b.Status
                # Modulo Timestamp: every check run re-stamps its state, so
                # including it would defeat the dedup for any checked service.
                and [(c.Name, c.Type, c.Status, c.Output) for c in a.Checks]
                == [(c.Name, c.Type, c.Status, c.Output) for c in b.Checks])

    def delete_services(self, index: int, reg_ids: List[str]) -> None:
        with self._lock:
            watch_items = Items()
            touched = False
            for rid in reg_ids:
                existing = self._get("services", rid)
                if existing is None:
                    continue  # idempotent: double-deregister is normal
                self._tables["services"].write(index, rid, None)
                watch_items.add(Item(service_name=existing.ServiceName))
                touched = True
            if touched:
                self._commit(index, ["services"], watch_items)

    def update_node_status(self, index: int, node_id: str, status: str) -> None:
        with self._lock:
            existing = self._get("nodes", node_id)
            if existing is None:
                raise KeyError(f"node not found: {node_id}")
            node = existing.copy()
            node.Status = status
            node.ModifyIndex = index
            self._tables["nodes"].write(index, node_id, node)
            watch_items = Items([Item(node=node_id)])
            tables = ["nodes"]
            # A down node can't run its checks: its service instances must
            # stop being served as healthy (the reference gets this from
            # Consul's serfHealth check; the replicated registry marks them
            # critical explicitly). When the node recovers, its service
            # manager's periodic full sync restores the true statuses
            # (services/manager.py FULL_SYNC_INTERVAL).
            if status == NodeStatusDown:
                for reg in self._members("service_node", node_id, "services"):
                    if reg.Status == CheckStatusCritical:
                        continue
                    down = reg.copy()
                    down.Status = CheckStatusCritical
                    for check in down.Checks:
                        check.Status = CheckStatusCritical
                        check.Output = "node down"
                    down.ModifyIndex = index
                    self._tables["services"].write(index, down.ID, down)
                    watch_items.add(Item(service_name=down.ServiceName))
                    tables.append("services")
            self._commit(index, tables, watch_items)
            self._emit([("node", existing, node)])

    def update_node_drain(self, index: int, node_id: str, drain: bool) -> None:
        with self._lock:
            existing = self._get("nodes", node_id)
            if existing is None:
                raise KeyError(f"node not found: {node_id}")
            node = existing.copy()
            node.Drain = drain
            node.ModifyIndex = index
            self._tables["nodes"].write(index, node_id, node)
            self._commit(index, ["nodes"], Items([Item(node=node_id)]))
            self._emit([("node", existing, node)])

    def upsert_job(self, index: int, job: Job) -> None:
        """(reference: state_store.go:280 UpsertJob) Derives initial status."""
        with self._lock:
            watch_items = Items([Item(job=job.ID)])
            existing = self._get("jobs", job.ID)
            if existing is not None:
                job.CreateIndex = existing.CreateIndex
                job.JobModifyIndex = index
            else:
                job.CreateIndex = index
                job.JobModifyIndex = index
            job.ModifyIndex = index
            job.Status = self._derive_job_status(job, eval_delete=False)
            self._tables["jobs"].write(index, job.ID, job)
            self._commit(index, ["jobs"], watch_items)

    def delete_job(self, index: int, job_id: str) -> None:
        with self._lock:
            if self._get("jobs", job_id) is None:
                raise KeyError(f"job not found: {job_id}")
            self._tables["jobs"].write(index, job_id, None)
            # Also clean the periodic launch entry if any.
            tables = ["jobs"]
            if self._get("periodic_launch", job_id) is not None:
                self._tables["periodic_launch"].write(index, job_id, None)
                tables.append("periodic_launch")
            self._commit(index, tables, Items([Item(job=job_id)]))

    def upsert_evals(self, index: int, evals: List[Evaluation]) -> None:
        """(reference: state_store.go:476 UpsertEvals) Also refreshes the
        status of every touched job."""
        with self._lock:
            watch_items = Items()
            jobs: Dict[str, str] = {}
            for ev in evals:
                existing = self._get("evals", ev.ID)
                if existing is not None:
                    ev.CreateIndex = existing.CreateIndex
                else:
                    ev.CreateIndex = index
                ev.ModifyIndex = index
                self._tables["evals"].write(index, ev.ID, ev)
                self._member_add("eval_job", ev.JobID, ev.ID)
                watch_items.add(Item(eval=ev.ID))
                jobs.setdefault(ev.JobID, "")
            touched = self._set_job_statuses(index, watch_items, jobs,
                                             eval_delete=False)
            self._commit(index, ["evals"] + touched, watch_items)

    def delete_eval(self, index: int, eval_ids: List[str],
                    alloc_ids: List[str]) -> None:
        """GC path: remove evals and allocs together (reference:
        state_store.go DeleteEval)."""
        with self._lock:
            watch_items = Items()
            jobs: Dict[str, str] = {}
            events = []
            for eid in eval_ids:
                existing = self._get("evals", eid)
                if existing is None:
                    continue
                self._tables["evals"].write(index, eid, None)
                watch_items.add(Item(eval=eid))
                jobs.setdefault(existing.JobID, "")
            for aid in alloc_ids:
                existing = self._get("allocs", aid)
                if existing is None:
                    # GC of a columnar row: promote (chain gets the value
                    # at the segment index), then tombstone as usual.
                    existing = self._col_promote_locked(aid)
                if existing is None:
                    continue
                self._tables["allocs"].write(index, aid, None)
                watch_items.add(Item(alloc=aid))
                watch_items.add(Item(alloc_eval=existing.EvalID))
                watch_items.add(Item(alloc_job=existing.JobID))
                watch_items.add(Item(alloc_node=existing.NodeID))
                events.append(("alloc", existing, None))
            touched = self._set_job_statuses(index, watch_items, jobs,
                                             eval_delete=True)
            self._commit(index, ["evals", "allocs"] + touched, watch_items)
            self._emit(events)

    def upsert_allocs(self, index: int, allocs: List[Allocation]) -> None:
        """(reference: state_store.go:792 UpsertAllocs) Used by the plan
        applier; refreshes job statuses."""
        with self._lock:
            watch_items = Items()
            jobs: Dict[str, str] = {}
            events = []
            # Relation watch keys dedupe through cheap string sets first: a
            # 50-placement plan repeats the same eval/job ids per alloc, and
            # hashing a frozen 9-field Item costs ~10x a str.
            evals: set = set()
            nodes: set = set()
            nonterminal_jobs: set = set()
            # Hot loop: a system sweep commits one alloc per node, so the
            # per-alloc work below runs 10k times per chunk; the table and
            # member-set lookups are hoisted out of it.
            alloc_table = self._tables["allocs"]
            alloc_current = alloc_table.current.get
            alloc_write = alloc_table.write
            add_item = watch_items.add
            members_node = self._members_sets("alloc_node")
            members_job = self._members_sets("alloc_job")
            members_eval = self._members_sets("alloc_eval")
            has_col = self._has_col
            for alloc in allocs:
                existing = alloc_current(alloc.ID)
                if existing is None and has_col:
                    # A mutation of a columnar row (eviction, preemption,
                    # in-place replace) first promotes it onto the exact
                    # object path, preserving upsert semantics verbatim.
                    existing = self._col_promote_locked(alloc.ID)
                if existing is None:
                    alloc.CreateIndex = index
                    alloc.ModifyIndex = index
                    alloc.AllocModifyIndex = index
                else:
                    alloc.CreateIndex = existing.CreateIndex
                    alloc.ModifyIndex = index
                    alloc.AllocModifyIndex = index
                    # Keep client-reported state (server-side upsert must not
                    # clobber what the client said).
                    alloc.ClientStatus = existing.ClientStatus
                    alloc.ClientDescription = existing.ClientDescription
                    alloc.TaskStates = existing.TaskStates
                add_item(Item(alloc=alloc.ID))
                alloc_write(index, alloc.ID, alloc)
                members_node.setdefault(alloc.NodeID, set()).add(alloc.ID)
                members_job.setdefault(alloc.JobID, set()).add(alloc.ID)
                members_eval.setdefault(alloc.EvalID, set()).add(alloc.ID)
                evals.add(alloc.EvalID)
                nodes.add(alloc.NodeID)
                jobs.setdefault(alloc.JobID, "")
                if not alloc.terminal_status():
                    nonterminal_jobs.add(alloc.JobID)
                events.append(("alloc", existing, alloc))
            for ev_id in evals:
                watch_items.add(Item(alloc_eval=ev_id))
            for job_id in jobs:
                watch_items.add(Item(alloc_job=job_id))
            for node_id in nodes:
                watch_items.add(Item(alloc_node=node_id))
            # A RUNNING job that just received a non-terminal alloc cannot
            # change status (one live alloc <=> running): skip the
            # derivation, which walks every alloc of the job — O(fleet)
            # per chunk for 10k-alloc system sweeps.
            for job_id in nonterminal_jobs:
                if job_id in jobs:
                    job = self._get("jobs", job_id)
                    if job is not None and job.Status == JobStatusRunning:
                        del jobs[job_id]
            touched = self._set_job_statuses(index, watch_items, jobs,
                                             eval_delete=False)
            self._commit(index, ["allocs"] + touched, watch_items)
            self._emit(events)

    def update_alloc_from_client(self, index: int, alloc: Allocation) -> None:
        """Client status sync (reference: state_store.go UpdateAllocFromClient):
        merges the client-reported fields into the server's copy."""
        with self._lock:
            existing = self._get("allocs", alloc.ID)
            if existing is None:
                # Client status for a sweep-committed row: promote it out
                # of the columnar table, then merge exactly as before.
                existing = self._col_promote_locked(alloc.ID)
            if existing is None:
                raise KeyError(f"alloc not found: {alloc.ID}")
            copy_alloc = existing.copy()
            copy_alloc.ClientStatus = alloc.ClientStatus
            copy_alloc.ClientDescription = alloc.ClientDescription
            copy_alloc.TaskStates = alloc.TaskStates
            copy_alloc.ModifyIndex = index
            self._tables["allocs"].write(index, alloc.ID, copy_alloc)
            watch_items = Items([
                Item(alloc=alloc.ID),
                Item(alloc_eval=copy_alloc.EvalID),
                Item(alloc_job=copy_alloc.JobID),
                Item(alloc_node=copy_alloc.NodeID),
            ])
            touched = self._set_job_statuses(index, watch_items,
                                             {copy_alloc.JobID: ""},
                                             eval_delete=False)
            self._commit(index, ["allocs"] + touched, watch_items)
            self._emit([("alloc", existing, copy_alloc)])

    def upsert_periodic_launch(self, index: int, launch: PeriodicLaunch) -> None:
        with self._lock:
            existing = self._get("periodic_launch", launch.ID)
            if existing is not None:
                launch.CreateIndex = existing.CreateIndex
            else:
                launch.CreateIndex = index
            launch.ModifyIndex = index
            self._tables["periodic_launch"].write(index, launch.ID, launch)
            self._commit(index, ["periodic_launch"], Items())

    def delete_periodic_launch(self, index: int, job_id: str) -> None:
        with self._lock:
            if self._get("periodic_launch", job_id) is None:
                raise KeyError(f"periodic launch not found: {job_id}")
            self._tables["periodic_launch"].write(index, job_id, None)
            self._commit(index, ["periodic_launch"], Items())

    # --------------------------------------------------- derived job statuses
    def _set_job_statuses(self, index: int, watch_items: Items,
                          jobs: Dict[str, str], eval_delete: bool) -> List[str]:
        """Recompute status for touched jobs (reference: state_store.go:1029).
        Returns the list of extra tables touched."""
        touched: List[str] = []
        for job_id, force in jobs.items():
            job = self._get("jobs", job_id)
            if job is None:
                continue
            new_status = force or self._derive_job_status(job, eval_delete)
            if job.Status == new_status:
                continue
            # Committed jobs are value-frozen: share the nested task tree
            # and replace only the scalars that change. A deepcopy here
            # walks the whole job (~1ms) inside the serialized FSM apply,
            # once per eval at storm rates.
            updated = replace(job, Status=new_status, ModifyIndex=index)
            self._tables["jobs"].write(index, job_id, updated)
            watch_items.add(Item(job=job_id))
            touched.append("jobs")
        return touched

    @requires_lock("_lock")
    def _derive_job_status(self, job: Job, eval_delete: bool) -> str:
        """(reference: state_store.go:1097 getJobStatus)"""
        has_alloc = False
        # Columnar rows are live (non-terminal) by construction — any
        # segment row means RUNNING without materializing anything.
        for seg in self._col_by_job.get(job.ID, ()):
            if seg.n_live:
                return JobStatusRunning
            has_alloc = True
        for alloc in self._members("alloc_job", job.ID, "allocs"):
            has_alloc = True
            if not alloc.terminal_status():
                return JobStatusRunning
        has_eval = False
        for ev in self._members("eval_job", job.ID, "evals"):
            has_eval = True
            if not ev.terminal_status():
                return JobStatusPending
        if eval_delete or has_eval or has_alloc:
            return JobStatusDead
        if job.is_periodic():
            return JobStatusRunning
        return JobStatusPending

    # -------------------------------------------------------------- snapshots
    def snapshot(self) -> "StateSnapshot":
        """O(1) point-in-time snapshot pinned at the current index."""
        with self._lock:
            token = self._next_token
            self._next_token += 1
            watermark = self._latest_index
            self._watermarks[token] = watermark
            snap = StateSnapshot(self, watermark, token)
            weakref.finalize(snap, self._release_snapshot, token)
            return snap

    def _release_snapshot(self, token: int) -> None:
        with self._lock:
            self._watermarks.pop(token, None)

    def compact(self) -> None:
        """Drop version history older than the oldest live snapshot."""
        with self._lock:
            min_mark = min(self._watermarks.values(), default=self._latest_index)
            for name, table in self._tables.items():
                dead = [k for k, chain in table.chains.items()
                        if chain.compact(min_mark)]
                for k in dead:
                    del table.chains[k]
            # Prune member sets whose objects are fully gone.
            for index_name, (table_name, _) in _MEMBER_INDEXES.items():
                chains = self._tables[table_name].chains
                sets = self._members_sets(index_name)
                for key in list(sets):
                    sets[key] = {i for i in sets[key] if i in chains}
                    if not sets[key]:
                        del sets[key]
            # Drop fully-promoted segments: every row's value now lives in
            # its chain (written at the segment index), so no watermark can
            # still need the columnar view. Rebuild the per-row indexes
            # without the dead segments' entries.
            dead_segs = [s for s in self._col_segments if s.n_live == 0]
            if dead_segs:
                gone = set(map(id, dead_segs))
                self._col_segments = [s for s in self._col_segments
                                      if id(s) not in gone]
                self._col_unindexed = [s for s in self._col_unindexed
                                       if id(s) not in gone]
                for by in (self._col_by_job, self._col_by_eval):
                    for key in list(by):
                        by[key] = [s for s in by[key] if id(s) not in gone]
                        if not by[key]:
                            del by[key]
                for key in list(self._col_node_index):
                    kept = [(s, p) for s, p in self._col_node_index[key]
                            if id(s) not in gone]
                    if kept:
                        self._col_node_index[key] = kept
                    else:
                        del self._col_node_index[key]

    # ---------------------------------------------------------------- restore
    def restore(self) -> "Restore":
        return Restore(self)


class StateSnapshot(_ReadAPI):
    """Point-in-time read view resolved through the version chains."""

    def __init__(self, store: StateStore, watermark: int, token: int):
        self._store = store
        self.watermark = watermark
        self._token = token

    def _get(self, table: str, key: str):
        chain = self._store._tables[table].chains.get(key)
        if chain is None:
            return None
        return chain.at(self.watermark)

    def _iter(self, table: str):
        with self._store._lock:
            out = []
            for chain in self._store._tables[table].chains.values():
                v = chain.at(self.watermark)
                if v is not None:
                    out.append(v)
            return out

    def _members(self, index_name: str, key: str, table: str):
        with self._store._lock:
            ids = self._store._members_sets(index_name).get(key, ())
            chains = self._store._tables[table].chains
            out = []
            for i in ids:
                chain = chains.get(i)
                if chain is None:
                    continue
                v = chain.at(self.watermark)
                if v is not None:
                    out.append(v)
            return out

    # ----------------------------------------------- columnar (at watermark)
    # A segment is visible iff it committed at or before the watermark;
    # promoted rows left the columnar view FOR EVERY WATERMARK (their chain
    # version is written at the segment's own commit index), so `live` is
    # the only per-row check needed.
    def _col_alloc(self, alloc_id: str):
        store = self._store
        if not store._has_col:
            return None
        with store._lock:
            store._col_flush_locked()
            hit = store._col_alloc_index.get(alloc_id)
            if hit is None:
                return None
            seg, pos = hit
            if seg.index > self.watermark or not seg.live[pos]:
                return None
            return seg.materialize(pos)

    def _col_members(self, kind: str, key: str):
        store = self._store
        if not store._has_col:
            return []
        with store._lock:
            if kind == "node":
                store._col_flush_locked()
                return [seg.materialize(pos)
                        for seg, pos in store._col_node_index.get(key, ())
                        if seg.index <= self.watermark and seg.live[pos]]
            segs = (store._col_by_job if kind == "job"
                    else store._col_by_eval).get(key, ())
            return [seg.materialize(pos)
                    for seg in segs if seg.index <= self.watermark
                    for pos in range(len(seg.alloc_ids))
                    if seg.live[pos]]

    def _col_allocs_all(self):
        store = self._store
        if not store._has_col:
            return []
        with store._lock:
            return [seg.materialize(pos)
                    for seg in store._col_segments
                    if seg.index <= self.watermark
                    for pos in range(len(seg.alloc_ids))
                    if seg.live[pos]]

    def alloc_dump(self):
        """(chain allocs, serialized live columnar segments) read under ONE
        store lock hold — the raft snapshot's alloc state. Two separate
        reads could straddle a promotion and lose the row from both views;
        this can't."""
        store = self._store
        with store._lock:
            chain_allocs = self._iter("allocs")
            segments = [seg.serialize()
                        for seg in store._col_segments
                        if seg.index <= self.watermark and seg.n_live]
            return chain_allocs, segments

    def get_index(self, table: str) -> int:
        # Table indexes are monotone; clamp to the watermark.
        return min(self._store.get_index(table), self.watermark)

    def latest_index(self) -> int:
        return self.watermark


class Restore:
    """Bulk loader used by FSM snapshot restore (reference: state_store.go
    Restore/NodeRestore/JobRestore/...).

    ATOMIC CUTOVER: every *_restore call writes into STAGING structures
    owned by this Restore, never into the live store. `commit()` swaps the
    staged tables in under one lock hold. A restore abandoned mid-stream —
    a torn snapshot chunk, an injected fault, a killed install — therefore
    leaves the live store bit-identical to its pre-restore state; readers
    never observe a half-loaded snapshot."""

    def __init__(self, store: StateStore):
        self._store = store
        self._max_index = 0
        # Staging mirrors of every structure a snapshot populates.
        self._tables: Dict[str, _Table] = {t: _Table() for t in TABLES}
        self._member_sets: Dict[str, Dict[str, Set[str]]] = {
            name: {} for name in _MEMBER_INDEXES}
        self._table_index: Dict[str, int] = {}
        self._col_segments: List[SweepSegment] = []
        self._col_by_job: Dict[str, List[SweepSegment]] = {}
        self._col_by_eval: Dict[str, List[SweepSegment]] = {}
        self._committed = False

    def _bump(self, index: int) -> None:
        self._max_index = max(self._max_index, index)

    def _member_add(self, index_name: str, key: str, obj_id: str) -> None:
        self._member_sets[index_name].setdefault(key, set()).add(obj_id)

    def node_restore(self, node: Node) -> None:
        self._tables["nodes"].write(node.ModifyIndex, node.ID, node)
        self._bump(node.ModifyIndex)

    def job_restore(self, job: Job) -> None:
        self._tables["jobs"].write(job.ModifyIndex, job.ID, job)
        self._bump(job.ModifyIndex)

    def eval_restore(self, ev: Evaluation) -> None:
        self._tables["evals"].write(ev.ModifyIndex, ev.ID, ev)
        self._member_add("eval_job", ev.JobID, ev.ID)
        self._bump(ev.ModifyIndex)

    def alloc_restore(self, alloc: Allocation) -> None:
        self._tables["allocs"].write(alloc.ModifyIndex, alloc.ID, alloc)
        self._member_add("alloc_node", alloc.NodeID, alloc.ID)
        self._member_add("alloc_job", alloc.JobID, alloc.ID)
        self._member_add("alloc_eval", alloc.EvalID, alloc.ID)
        self._bump(alloc.ModifyIndex)

    def columnar_restore(self, seg_data: Dict[str, Any]) -> None:
        """Re-register one serialized columnar segment: the snapshot
        round-trips the columnar tables columnar — a 1M-row restore never
        explodes into per-alloc objects."""
        seg = (seg_data if isinstance(seg_data, SweepSegment)
               else SweepSegment.deserialize(seg_data))
        self._col_segments.append(seg)
        self._col_by_job.setdefault(seg.job_id, []).append(seg)
        self._col_by_eval.setdefault(seg.eval_id, []).append(seg)
        self._bump(seg.index)

    def periodic_launch_restore(self, launch: PeriodicLaunch) -> None:
        self._tables["periodic_launch"].write(launch.ModifyIndex,
                                              launch.ID, launch)
        self._bump(launch.ModifyIndex)

    def service_restore(self, reg) -> None:
        self._tables["services"].write(reg.ModifyIndex, reg.ID, reg)
        self._member_add("service_name", reg.ServiceName, reg.ID)
        self._member_add("service_node", reg.NodeID, reg.ID)
        self._member_add("service_alloc", reg.AllocID, reg.ID)
        self._bump(reg.ModifyIndex)

    def index_restore(self, table: str, index: int) -> None:
        self._table_index[table] = index
        self._bump(index)

    def commit(self) -> None:
        """Swap the staged snapshot in as THE store state, atomically with
        respect to readers, then wake every blocking query (a restore can
        change anything) and tell listeners to rebuild their derived state
        (the device-resident node tensor re-seeds from the store — its
        incremental feed never saw the staged writes)."""
        store = self._store
        if self._committed:
            raise RuntimeError("restore already committed")
        self._committed = True
        with store._lock:
            store._tables = self._tables
            store._member_sets = self._member_sets
            store._table_index = self._table_index
            for t in TABLES:
                store._table_index.setdefault(t, 0)
            store._col_segments = self._col_segments
            store._col_by_job = self._col_by_job
            store._col_by_eval = self._col_by_eval
            store._col_unindexed = list(self._col_segments)
            store._col_alloc_index = {}
            store._col_node_index = {}
            store._has_col = bool(self._col_segments)
            if self._max_index > store._latest_index:
                store._latest_index = self._max_index
            # Every blocking query must re-read. Blocking queries
            # register FINE-GRAINED items only (Item(job=...),
            # Item(alloc_node=...)), so table-level notifies would strand
            # them until their max-wait expiry: wake everyone.
            store._notify.notify_all()
            listeners = list(store._listeners)
        for cb in listeners:
            on_restore = getattr(cb, "on_restore", None)
            if on_restore is not None:
                on_restore(store)
