"""Watch items: fine-grained notification keys for blocking queries.

(reference: nomad/watch/watch.go, nomad/state/notify.py analog)
A watch Item identifies one thing to watch: a table, a specific object, or an
object scoped to a relation (allocs of a node, evals of a job, ...).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Set


class Item:
    """One watchable key. Set exactly one field (or one scoped pair).

    Accepted fields: alloc, alloc_eval, alloc_job, alloc_node, eval, job,
    node, service_name, table. Stored as a single (field, value) key with a
    precomputed hash: every state-store commit builds and hashes dozens of
    Items (one per written object plus relation keys), so construction and
    hashing are on the FSM apply hot path — a 9-field frozen dataclass costs
    ~4x as much per commit for the same set semantics."""

    __slots__ = ("_key", "_hash")

    FIELDS = frozenset((
        "alloc", "alloc_eval", "alloc_job", "alloc_node", "eval", "job",
        "node", "service_name", "table"))

    def __init__(self, **kw):
        if len(kw) == 1:
            self._key = next(iter(kw.items()))
            if self._key[0] not in Item.FIELDS:
                raise TypeError(f"unknown watch field: {self._key[0]}")
        else:  # scoped pair (rare): canonical order keeps equality stable
            for k in kw:
                if k not in Item.FIELDS:
                    raise TypeError(f"unknown watch field: {k}")
            self._key = tuple(sorted(kw.items()))
        self._hash = hash(self._key)

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return isinstance(other, Item) and self._key == other._key

    def __repr__(self) -> str:  # debugging aid only
        return f"Item({self._key!r})"


class Items(set):
    """A set of watch Items (reference: watch.Items)."""

    def __init__(self, items: Iterable[Item] = ()):  # noqa: D401
        super().__init__(items)

    def add_item(self, item: Item) -> None:
        self.add(item)


class NotifyGroup:
    """Fan-out notifications to registered waiters (reference: state/notify.go)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._waiters: Dict[Item, Set[threading.Event]] = {}

    def watch(self, items: Iterable[Item], event: threading.Event) -> None:
        with self._lock:
            for item in items:
                self._waiters.setdefault(item, set()).add(event)

    def stop_watch(self, items: Iterable[Item], event: threading.Event) -> None:
        with self._lock:
            for item in items:
                waiters = self._waiters.get(item)
                if waiters is not None:
                    waiters.discard(event)
                    if not waiters:
                        self._waiters.pop(item, None)

    def notify(self, items: Iterable[Item],
               scoped: "Dict[str, Set[str]]" = None) -> None:
        """Wake waiters of `items`, plus — via `scoped` — waiters whose
        single-field key falls inside a bulk key set ({field: {values}}).

        The scoped form exists for columnar batch commits: a 10k-alloc
        sweep touches 10k (alloc, alloc_node) keys, and building+hashing
        an Item per key would put an O(batch) loop back on the commit
        path. Intersecting against the REGISTERED waiters instead costs
        O(waiters), and waiters are bounded by connected blocking queries,
        not by batch size."""
        with self._lock:
            fired: Set[threading.Event] = set()
            for item in items:
                for ev in self._waiters.get(item, ()):
                    fired.add(ev)
            if scoped:
                for item, evs in self._waiters.items():
                    key = item._key
                    if not (isinstance(key[0], str)):
                        continue
                    values = scoped.get(key[0])
                    if values is not None and key[1] in values:
                        fired.update(evs)
        for ev in fired:
            ev.set()

    def notify_all(self) -> None:
        """Wake EVERY registered waiter. For whole-store events — a
        snapshot restore swaps every table, so any blocked query's
        object may have changed regardless of which keys it watches.
        O(waiters), and restores are rare."""
        with self._lock:
            fired = {ev for evs in self._waiters.values() for ev in evs}
        for ev in fired:
            ev.set()
