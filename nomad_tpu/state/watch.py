"""Watch items: fine-grained notification keys for blocking queries.

(reference: nomad/watch/watch.go, nomad/state/notify.py analog)
A watch Item identifies one thing to watch: a table, a specific object, or an
object scoped to a relation (allocs of a node, evals of a job, ...).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Set


class Item:
    """One watchable key. Set exactly one field (or one scoped pair).

    Accepted fields: alloc, alloc_eval, alloc_job, alloc_node, eval, job,
    node, service_name, table. Stored as a single (field, value) key with a
    precomputed hash: every state-store commit builds and hashes dozens of
    Items (one per written object plus relation keys), so construction and
    hashing are on the FSM apply hot path — a 9-field frozen dataclass costs
    ~4x as much per commit for the same set semantics."""

    __slots__ = ("_key", "_hash")

    FIELDS = frozenset((
        "alloc", "alloc_eval", "alloc_job", "alloc_node", "eval", "job",
        "node", "service_name", "table"))

    def __init__(self, **kw):
        if len(kw) == 1:
            self._key = next(iter(kw.items()))
            if self._key[0] not in Item.FIELDS:
                raise TypeError(f"unknown watch field: {self._key[0]}")
        else:  # scoped pair (rare): canonical order keeps equality stable
            for k in kw:
                if k not in Item.FIELDS:
                    raise TypeError(f"unknown watch field: {k}")
            self._key = tuple(sorted(kw.items()))
        self._hash = hash(self._key)

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return isinstance(other, Item) and self._key == other._key

    def __repr__(self) -> str:  # debugging aid only
        return f"Item({self._key!r})"


class Items(set):
    """A set of watch Items (reference: watch.Items)."""

    def __init__(self, items: Iterable[Item] = ()):  # noqa: D401
        super().__init__(items)

    def add_item(self, item: Item) -> None:
        self.add(item)


class NotifyGroup:
    """Fan-out notifications to registered waiters (reference: state/notify.go)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._waiters: Dict[Item, Set[threading.Event]] = {}

    def watch(self, items: Iterable[Item], event: threading.Event) -> None:
        with self._lock:
            for item in items:
                self._waiters.setdefault(item, set()).add(event)

    def stop_watch(self, items: Iterable[Item], event: threading.Event) -> None:
        with self._lock:
            for item in items:
                waiters = self._waiters.get(item)
                if waiters is not None:
                    waiters.discard(event)
                    if not waiters:
                        self._waiters.pop(item, None)

    def notify(self, items: Iterable[Item]) -> None:
        with self._lock:
            fired: Set[threading.Event] = set()
            for item in items:
                for ev in self._waiters.get(item, ()):
                    fired.add(ev)
        for ev in fired:
            ev.set()
