"""Watch items: fine-grained notification keys for blocking queries.

(reference: nomad/watch/watch.go, nomad/state/notify.py analog)
A watch Item identifies one thing to watch: a table, a specific object, or an
object scoped to a relation (allocs of a node, evals of a job, ...).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, Set


@dataclass(frozen=True)
class Item:
    """One watchable key. Set exactly one field (or one scoped pair)."""

    alloc: str = ""
    alloc_eval: str = ""
    alloc_job: str = ""
    alloc_node: str = ""
    eval: str = ""
    job: str = ""
    node: str = ""
    service_name: str = ""
    table: str = ""


class Items(set):
    """A set of watch Items (reference: watch.Items)."""

    def __init__(self, items: Iterable[Item] = ()):  # noqa: D401
        super().__init__(items)

    def add_item(self, item: Item) -> None:
        self.add(item)


class NotifyGroup:
    """Fan-out notifications to registered waiters (reference: state/notify.go)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._waiters: Dict[Item, Set[threading.Event]] = {}

    def watch(self, items: Iterable[Item], event: threading.Event) -> None:
        with self._lock:
            for item in items:
                self._waiters.setdefault(item, set()).add(event)

    def stop_watch(self, items: Iterable[Item], event: threading.Event) -> None:
        with self._lock:
            for item in items:
                waiters = self._waiters.get(item)
                if waiters is not None:
                    waiters.discard(event)
                    if not waiters:
                        self._waiters.pop(item, None)

    def notify(self, items: Iterable[Item]) -> None:
        with self._lock:
            fired: Set[threading.Event] = set()
            for item in items:
                for ev in self._waiters.get(item, ()):
                    fired.add(ev)
        for ev in fired:
            ev.set()
