"""RPC listener: accepts connections, demuxes stream types, dispatches
msgpack-RPC requests on worker threads (reference: nomad/rpc.go:56-132
listen/handleConn + the per-request goroutine model of net/rpc).
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Any, Callable, Dict, Optional

from nomad_tpu.telemetry import trace

from .pool import DroppedRPCError
from .wire import (
    RPC_NOMAD,
    RPC_RAFT,
    RPC_TLS,
    MessageCodec,
    recv_frame,
    send_frame,
)

logger = logging.getLogger("nomad.rpc")

Handler = Callable[[str, Any], Any]


class RPCServer:
    """One TCP port for both application RPC and raft traffic; with a TLS
    context, TLS-prefixed streams unwrap and re-dispatch (reference:
    rpc.go:88-132 handleConn's rpcTLS arm)."""

    def __init__(self, bind_addr: str = "127.0.0.1", port: int = 0,
                 rpc_handler: Optional[Handler] = None,
                 raft_handler: Optional[Handler] = None,
                 tls_context=None, require_tls: bool = False):
        self.rpc_handler = rpc_handler
        self.raft_handler = raft_handler
        self.tls_context = tls_context
        # verify_incoming semantics: plaintext streams are refused outright.
        self.require_tls = require_tls
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind_addr, port))
        self._sock.listen(128)
        self.addr = "%s:%d" % self._sock.getsockname()[:2]
        self._shutdown = threading.Event()
        self._conns: set = set()
        self._lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"rpc-{self.addr}")
        self._accept_thread.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        try:
            # shutdown() wakes the blocked accept(); close() alone leaves
            # the kernel socket alive under the accept thread on Linux.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    # -------------------------------------------------------------- accept
    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True, name="rpc-conn").start()

    def _handle_conn(self, conn: socket.socket) -> None:
        """(reference: handleConn byte-prefix dispatch, rpc.go:88-132)"""
        try:
            prefix = conn.recv(1)
            if not prefix:
                return
            stream_type = prefix[0]
            if stream_type == RPC_TLS:
                if self.tls_context is None:
                    logger.warning(
                        "rpc: TLS connection attempted, server not "
                        "configured for TLS")
                    return
                import ssl

                raw = conn
                try:
                    conn = self.tls_context.wrap_socket(conn,
                                                        server_side=True)
                except (ssl.SSLError, OSError) as e:
                    logger.warning("rpc: TLS handshake failed: %s", e)
                    return
                # Track the SSLSocket, not the detached raw socket: the
                # finally-block discard and shutdown()'s force-close must
                # see the live object.
                with self._lock:
                    self._conns.discard(raw)
                    self._conns.add(conn)
                inner = conn.recv(1)
                if not inner:
                    return
                stream_type = inner[0]
            elif self.require_tls:
                logger.warning(
                    "rpc: non-TLS connection rejected (verify_incoming)")
                return
            if stream_type == RPC_NOMAD:
                self._serve_rpc(conn, self.rpc_handler)
            elif stream_type == RPC_RAFT:
                self._serve_rpc(conn, self.raft_handler)
            else:
                logger.warning("rpc: unknown stream type %#x", stream_type)
        except OSError:
            pass
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_rpc(self, conn: socket.socket, handler: Optional[Handler]
                   ) -> None:
        if handler is None:
            return
        send_lock = threading.Lock()
        while not self._shutdown.is_set():
            try:
                frame = recv_frame(conn)
            except OSError:
                return
            if frame is None:
                return
            # Each request on its own thread: blocking queries must not
            # head-of-line block the stream (reference: rpc.go:294-349).
            threading.Thread(
                target=self._dispatch,
                args=(conn, send_lock, handler, frame), daemon=True,
                name=f"rpc-dispatch-{frame.get('Method', '?')}").start()

    def _dispatch(self, conn: socket.socket, send_lock: threading.Lock,
                  handler: Handler, frame: Dict[str, Any]) -> None:
        seq = frame.get("Seq", 0)
        try:
            # Attach the caller's trace context (if the envelope carried
            # one) so handler spans join the remote trace.
            with trace.attach(frame.get("Trace")):
                result = handler(frame["Method"], frame.get("Body"))
            resp = MessageCodec.response(seq, body=result)
        except DroppedRPCError:
            # A black-holed request (rpc.server.handle drop failpoint):
            # kill the connection instead of answering, so the caller
            # sees a transport failure and runs its failover path. Only
            # the injected type — a real ConnError out of a handler
            # (dead leader forward) serializes as a remote error like
            # any other handler exception.
            try:
                conn.close()
            except OSError:
                pass
            return
        # lint: allow(swallow, error crosses the wire as the RPC response)
        except Exception as exc:  # errors cross the wire as strings
            resp = MessageCodec.response(seq, error=_err_string(exc))
        try:
            with send_lock:
                # lint: allow(lock_blocking, lock exists to serialize socket writes)
                send_frame(conn, resp)
        except OSError:
            pass


def _err_string(exc: Exception) -> str:
    """Stable, parseable error strings (the reference forwards well-known
    errors like structs.ErrNoLeader by string match, rpc.go:207-216)."""
    name = type(exc).__name__
    return f"{name}: {exc}"
