"""Wire framing: stream-type prefix byte + length-prefixed msgpack frames
(reference: nomad/rpc.go:25-30 rpcNomad/rpcRaft/rpcMultiplex/rpcTLS byte
constants and handleConn:88-132).
"""

from __future__ import annotations

import socket
import struct
from typing import Any, Dict, Optional

import msgpack

# Stream type prefix bytes (reference: rpc.go:25-30)
RPC_NOMAD = 0x01
RPC_RAFT = 0x02
RPC_TLS = 0x03  # TLS wrapper: handshake, then the inner type byte again

_LEN = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024  # reference warns at 1MB raft entries; cap hard


class WireError(Exception):
    pass


def send_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    raw = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(_LEN.pack(len(raw)) + raw)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """Returns None on clean EOF."""
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise WireError(f"frame of {length} bytes exceeds cap")
    raw = _recv_exact(sock, length)
    if raw is None:
        return None
    return msgpack.unpackb(raw, raw=False)


class MessageCodec:
    """Request/response envelope helpers."""

    @staticmethod
    def request(seq: int, method: str, body: Any,
                trace: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        out: Dict[str, Any] = {"Seq": seq, "Method": method, "Body": body}
        if trace:
            # Trace carrier (telemetry/trace.py): rides the envelope, not
            # the body, so handlers never see it and one trace connects
            # caller and callee processes.
            out["Trace"] = trace
        return out

    @staticmethod
    def response(seq: int, body: Any = None,
                 error: Optional[str] = None) -> Dict[str, Any]:
        out: Dict[str, Any] = {"Seq": seq}
        if error is not None:
            out["Error"] = error
        else:
            out["Body"] = body
        return out
