"""Connection pool: one multiplexed connection per target with seq-routed
concurrent requests and reconnect (reference: nomad/pool.go ConnPool — pooled
yamux sessions with stream reuse; here sequence multiplexing serves the same
concurrency purpose with one socket).
"""

from __future__ import annotations

import itertools
import socket
import threading
from typing import Any, Dict, Optional

from nomad_tpu.resilience import failpoints
from nomad_tpu.resilience.retry import Backoff, RetryPolicy
from nomad_tpu.telemetry import trace

from .wire import RPC_NOMAD, MessageCodec, recv_frame, send_frame


class RPCError(Exception):
    """Remote handler raised; .remote_type carries the exception class name
    so callers can react to NotLeaderError etc. across the wire."""

    def __init__(self, message: str):
        super().__init__(message)
        self.remote_type = message.split(":", 1)[0] if ":" in message else ""


class ConnError(Exception):
    pass


class DroppedRPCError(ConnError):
    """A request black-holed by the `rpc.server.handle` drop failpoint.
    Distinct from plain ConnError so the RPC server kills only injected
    drops: a REAL ConnError out of a handler (e.g. a dead leader
    forward) still serializes to the caller as a remote error, exactly
    as it did before failpoints existed."""


class _Conn:
    def __init__(self, addr: str, stream_type: int, timeout: float,
                 tls_context=None):
        host, port = addr.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)),
                                             timeout=timeout)
        if tls_context is not None:
            # TLS byte in plaintext, handshake, then the inner stream type
            # rides encrypted (reference: rpc.go rpcTLS).
            from .wire import RPC_TLS

            self.sock.sendall(bytes([RPC_TLS]))
            self.sock = tls_context.wrap_socket(
                self.sock,
                server_hostname=host if tls_context.check_hostname
                else None)
        self.sock.settimeout(None)
        self.sock.sendall(bytes([stream_type]))
        self._seq = itertools.count(1)
        self._send_lock = threading.Lock()
        self._waiters: Dict[int, "queue_like"] = {}
        self._waiter_lock = threading.Lock()
        self._dead = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"rpc-pool-read-{addr}")
        self._reader.start()

    def _read_loop(self) -> None:
        while True:
            try:
                frame = recv_frame(self.sock)
            except OSError:
                frame = None
            if frame is None:
                self._fail_all()
                return
            with self._waiter_lock:
                waiter = self._waiters.pop(frame.get("Seq", -1), None)
            if waiter is not None:
                waiter["frame"] = frame
                waiter["event"].set()

    def _fail_all(self) -> None:
        self._dead = True
        try:
            # Close promptly: a half-open CLOSE_WAIT socket pins the peer's
            # port in FIN_WAIT_2 and blocks listener rebinds.
            self.sock.close()
        except OSError:
            pass
        with self._waiter_lock:
            waiters = list(self._waiters.values())
            self._waiters.clear()
        for w in waiters:
            w["event"].set()

    def call(self, method: str, body: Any,
             timeout: Optional[float]) -> Any:
        if self._dead:
            raise ConnError("connection closed")
        seq = next(self._seq)
        waiter = {"event": threading.Event(), "frame": None}
        with self._waiter_lock:
            self._waiters[seq] = waiter
        try:
            with self._send_lock:
                # lint: allow(lock_blocking, lock exists to serialize socket writes)
                send_frame(self.sock, MessageCodec.request(
                    seq, method, body, trace=trace.inject()))
        except OSError as exc:
            with self._waiter_lock:
                self._waiters.pop(seq, None)
            self._fail_all()
            raise ConnError(str(exc))
        if not waiter["event"].wait(timeout):
            with self._waiter_lock:
                self._waiters.pop(seq, None)
            raise TimeoutError(f"rpc {method} timed out")
        frame = waiter["frame"]
        if frame is None:
            raise ConnError("connection closed mid-request")
        if "Error" in frame:
            raise RPCError(frame["Error"])
        return frame.get("Body")

    def close(self) -> None:
        self._dead = True
        try:
            self.sock.close()
        except OSError:
            pass


class ConnPool:
    """addr -> shared multiplexed connection, created on demand, dropped on
    failure (reference: pool.go:111-180 acquire/release lifecycle)."""

    def __init__(self, stream_type: int = RPC_NOMAD,
                 connect_timeout: float = 5.0,
                 call_timeout: float = 330.0,
                 tls_context=None):
        # call_timeout must exceed the 300s blocking-query cap PLUS the
        # server's herd jitter of up to wait/16 (300 * 17/16 = 318.75s;
        # reference: rpc.go:33-47 maxQueryTime + :334-343 jitter).
        self.stream_type = stream_type
        self.connect_timeout = connect_timeout
        self.call_timeout = call_timeout
        self.tls_context = tls_context
        self._conns: Dict[str, _Conn] = {}
        self._lock = threading.Lock()
        self._addr_locks: Dict[str, threading.Lock] = {}

    def _get(self, addr: str) -> _Conn:
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn._dead:
                return conn
            addr_lock = self._addr_locks.setdefault(addr, threading.Lock())
        # Connect outside the pool-wide lock: raft heartbeats to every peer
        # share this pool, so a hung connect to one partitioned address must
        # not stall calls to healthy peers for connect_timeout seconds.
        with addr_lock:
            with self._lock:
                conn = self._conns.get(addr)
                if conn is not None and not conn._dead:
                    return conn
            conn = _Conn(addr, self.stream_type, self.connect_timeout,
                         tls_context=self.tls_context)
            with self._lock:
                self._conns[addr] = conn
            return conn

    def call(self, addr: str, method: str, body: Any = None,
             timeout: Optional[float] = None) -> Any:
        """One RPC. Retries once through a fresh connection on transport
        failure (NOT on remote errors) via the shared RetryPolicy."""
        timeout = timeout if timeout is not None else self.call_timeout
        if failpoints.fire("rpc.pool.call") == "drop":
            raise ConnError(f"rpc {method} to {addr} dropped (failpoint)")

        def evict_stale(exc, attempt, delay):
            with self._lock:
                stale = self._conns.pop(addr, None)
            if stale is not None:
                stale.close()

        policy = RetryPolicy(max_attempts=2,
                             backoff=Backoff(base=0.005, cap=0.05),
                             retry_on=(ConnError, OSError),
                             on_retry=evict_stale)
        return policy.call(
            lambda: self._get(addr).call(method, body, timeout))

    def close(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()
