"""RPC endpoints: the wire surface of a Server (reference:
nomad/*_endpoint.go services registered in server.go:152-162, with region +
leader forwarding from rpc.go:177-242 and watch-based blocking queries from
rpc.go:294-349).

All bodies are plain msgpack-able data; structs cross as their codec dicts.
Every handler runs on the receiving server; writes hit the raft seam and
raise NotLeaderError on followers, which `handle` turns into one forwarding
hop to the current leader (node ids are "host:port" addresses).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nomad_tpu.federation import (
    FORWARD_DEDUPED,
    ForwardDedup,
    NoRegionPathError,
    RegionForwarder,
    federation_enabled,
    health_payload,
)
from nomad_tpu.raft.node import NotLeaderError
from nomad_tpu.resilience import failpoints
from nomad_tpu.state.watch import Item
from nomad_tpu.telemetry import metrics, trace
from nomad_tpu.structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    Plan,
    from_dict,
    to_dict,
)

from .pool import ConnPool, DroppedRPCError, RPCError

MAX_BLOCK_TIME = 300.0  # reference: rpc.go:33-47 maxQueryTime

# NoRegionPathError moved to federation/routing.py with the hardened
# forwarder; re-exported here so existing callers keep importing it from
# the endpoint module.
__all__ = ["Endpoints", "NoRegionPathError", "blocking_query"]


def blocking_query(state, items: List[Item], min_index: int,
                   max_wait: float,
                   run: Callable[[], Tuple[Any, int]]) -> Tuple[Any, int]:
    """Run `run` until its index passes min_index or the wait expires
    (reference: blockingRPC, rpc.go:294-349). `run` returns (result, index).

    The wait is jittered by up to wait/16 (reference: rpc.go:334-343):
    thousands of clients watching the same object re-arm their queries in
    lockstep after a change; without jitter every later expiry becomes a
    synchronized thundering herd on the leader.
    """
    # Clamp FIRST, then jitter without re-clamping (reference order,
    # rpc.go:334-343): re-clamping after the add would cancel the jitter
    # exactly for full-length queries — the synchronized-expiry case the
    # jitter exists to break.
    max_wait = min(max_wait, MAX_BLOCK_TIME)
    if max_wait > 0:
        max_wait += random.random() * (max_wait / 16.0)
    deadline = time.monotonic() + max_wait
    if min_index <= 0:
        return run()
    event = threading.Event()
    state.watch(items, event)
    try:
        while True:
            result, index = run()
            if index > min_index:
                return result, index
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return result, index
            event.clear()
            event.wait(remaining)
    finally:
        state.stop_watch(items, event)


class Endpoints:
    """Dispatch table + forwarding wrapper around one Server."""

    def __init__(self, server, pool: Optional[ConnPool] = None,
                 region_router: Optional[Callable[[str], Optional[str]]] = None,
                 region_lister: Optional[Callable[[], List[str]]] = None):
        self.server = server
        self.pool = pool or ConnPool()
        # region -> a server address in that region (gossip fills this in;
        # reference: Server.peers map fed by Serf, server.go:100-104).
        self.region_router = region_router
        self.region_lister = region_lister
        self._methods: Dict[str, Callable[[Dict[str, Any]], Any]] = {
            "Status.Ping": self.status_ping,
            "Status.Leader": self.status_leader,
            "Status.Peers": self.status_peers,
            "Status.RaftStats": self.status_raft_stats,
            "Job.Register": self.job_register,
            "Job.Deregister": self.job_deregister,
            "Job.GetJob": self.job_get,
            "Job.List": self.job_list,
            "Job.Allocations": self.job_allocations,
            "Job.Evaluations": self.job_evaluations,
            "Job.Evaluate": self.job_evaluate,
            "Job.Plan": self.job_plan,
            "Periodic.Force": self.periodic_force,
            "Node.Register": self.node_register,
            "Node.Heartbeat": self.node_heartbeat,
            "Node.UpdateStatus": self.node_update_status,
            "Node.UpdateDrain": self.node_update_drain,
            "Node.Deregister": self.node_deregister,
            "Node.Evaluate": self.node_evaluate,
            "Node.GetNode": self.node_get,
            "Node.List": self.node_list,
            "Node.GetAllocs": self.node_get_allocs,
            "Node.GetClientAllocs": self.node_get_client_allocs,
            "Node.UpdateAlloc": self.node_update_alloc,
            "Eval.GetEval": self.eval_get,
            "Eval.List": self.eval_list,
            "Eval.Allocations": self.eval_allocations,
            "Eval.Dequeue": self.eval_dequeue,
            "Eval.Ack": self.eval_ack,
            "Eval.Nack": self.eval_nack,
            "Eval.Update": self.eval_update,
            "Plan.Submit": self.plan_submit,
            "Alloc.List": self.alloc_list,
            "Alloc.GetAlloc": self.alloc_get,
            "Alloc.GetAllocs": self.alloc_get_many,
            "Region.List": self.region_list,
            "Service.Sync": self.service_sync,
            "Service.List": self.service_list,
            "Service.GetService": self.service_get,
            "System.GC": self.system_gc,
            "Agent.Members": self.agent_members,
            "Agent.Join": self.agent_join,
            "Agent.ForceLeave": self.agent_force_leave,
            "Federation.Health": self.federation_health,
        }
        # populated by ClusterServer.enable_gossip (server/membership.py)
        self.membership = None
        # Cross-region forwarding (federation/routing.py): retrying +
        # breaker-guarded + write-deduped. The forwarder is built lazily
        # so it picks up gossip membership and the server's federation
        # config once wired; the dedupe cache answers replayed forwarded
        # writes (ForwardID) on the receiving side.
        self._forwarder: Optional[RegionForwarder] = None
        self._forward_dedup = ForwardDedup()

    # Read RPCs that forward to the leader unless the caller passes
    # AllowStale (reference: every endpoint's `if done, err := s.forward(...)`
    # prologue + QueryOptions.AllowStale — a follower's replica may lag the
    # write the caller just made).
    _READ_FORWARD = frozenset({
        "Job.GetJob", "Job.List", "Job.Allocations", "Job.Evaluations",
        "Node.GetNode", "Node.List", "Node.GetAllocs",
        "Node.GetClientAllocs",
        "Eval.GetEval", "Eval.List", "Eval.Allocations",
        "Alloc.List", "Alloc.GetAlloc", "Alloc.GetAllocs",
        "Service.List", "Service.GetService",
    })

    # Chatty/long-poll methods that must not each mint a fresh trace when
    # tracing is enabled (they still JOIN a caller's trace via the wire
    # carrier): heartbeats, pings, and blocking watch queries.
    _UNTRACED_ROOTS = frozenset({
        "Status.Ping", "Status.Leader", "Status.Peers",
        "Node.Heartbeat", "Node.GetClientAllocs", "Node.GetAllocs",
        "Eval.Dequeue", "Agent.Members",
    })

    # ------------------------------------------------------------- dispatch
    def handle(self, method: str, body: Any) -> Any:
        """Every RPC is timed under nomad.rpc.<Method> (reference: the
        per-endpoint MeasureSince calls, e.g. eval_endpoint.go:73) and,
        with tracing enabled, spanned as rpc.<Method> — the trace ingress
        for the evaluation lifecycle."""
        opener = (trace.span if method in self._UNTRACED_ROOTS
                  else trace.root_span)
        with opener("rpc." + method, method=method):
            return self._handle(method, body)

    def _handle(self, method: str, body: Any) -> Any:
        start = time.monotonic()
        metrics.incr_counter(("nomad", "rpc", "request"))
        try:
            if failpoints.fire("rpc.server.handle") == "drop":
                # A black-holed request surfaces to the caller as a dead
                # connection, driving its failover path.
                raise DroppedRPCError(
                    f"rpc {method} dropped (failpoint)")
            body = dict(body or {})
            region = body.get("Region") or self.server.config.region
            if region != self.server.config.region:
                return self._forward_region(region, method, body)
            if (method in self._READ_FORWARD
                    and not body.get("AllowStale")
                    and not body.get("Forwarded")
                    and not self.server.is_leader()):
                return self._forward_leader(method, body,
                                            NotLeaderError(None))
            # Forwarded-write replay dedupe (federation/routing.py): a
            # cross-region retry whose original attempt WAS delivered
            # (response lost on the WAN) replays its ForwardID; answer
            # from the cache instead of re-executing — exactly-once
            # registration, no duplicate evals. Keyed lookups only when
            # the body carries an ID, so un-forwarded traffic never pays.
            fid = (body.get("ForwardID")
                   if method in FORWARD_DEDUPED else None)
            if fid:
                # begin() RESERVES the id: a replay landing while this
                # delivery is still executing parks on the reservation
                # instead of re-executing the write concurrently (the
                # ambiguous-WAN race), and answers from the cache once
                # this execution resolves. put/abort below MUST resolve
                # every reservation.
                hit, cached = self._forward_dedup.begin(fid)
                if hit:
                    return cached
            try:
                try:
                    result = self._methods[method](body)
                except NotLeaderError as exc:
                    result = self._forward_leader(method, body, exc)
            except BaseException:
                if fid:
                    # Nothing committed from this delivery's point of
                    # view: parked replays wake and re-execute.
                    self._forward_dedup.abort(fid)
                raise
            if fid:
                self._forward_dedup.put(fid, result)
            return result
        finally:
            metrics.measure_since(("nomad", "rpc", method), start)

    # ---------------------------------------------- cross-region forwarding
    def _fed(self):
        """The server's FederationConfig (None = federation off)."""
        return getattr(self.server, "fed", None)

    def _region_candidates(self, region: str) -> List[str]:
        """Every known live server of a region — gossip's view when
        federated; the static router (tests / manual wiring) degrades to
        a single candidate."""
        if self.membership is not None:
            return self.membership.region_servers(region)
        addr = self.region_router(region) if self.region_router else None
        return [addr] if addr else []

    def _get_forwarder(self) -> RegionForwarder:
        if self._forwarder is None:
            self._forwarder = RegionForwarder(
                self.pool, self._region_candidates, fed=self._fed())
        return self._forwarder

    def _forward_region(self, region: str, method: str,
                        body: Dict[str, Any]) -> Any:
        """(reference: forwardRegion, rpc.go:223-242 — hardened: retries
        across region peers under RetryPolicy, per-peer CircuitBreaker
        quarantine, ForwardID-deduped writes, `rpc.forward_region`
        failpoint. See federation/routing.py.)"""
        return self._get_forwarder().forward(region, method, body)

    def _forward_leader(self, method: str, body: Dict[str, Any],
                        exc: NotLeaderError) -> Any:
        """(reference: forward leader hop, rpc.go:177-221)"""
        if body.get("Forwarded"):
            raise exc
        leader = exc.leader_hint or getattr(self.server.raft, "leader_id",
                                            None)
        if not leader or leader == getattr(self.server.config, "node_id", ""):
            raise exc
        body = dict(body)
        body["Forwarded"] = True
        return self.pool.call(leader, method, body)

    # --------------------------------------------------------------- status
    def status_ping(self, body) -> bool:
        return True

    def status_leader(self, body) -> str:
        raft = self.server.raft
        return getattr(raft, "leader_id", None) or ""

    def status_peers(self, body) -> List[str]:
        raft = self.server.raft
        if hasattr(raft, "node"):
            return raft.node.peers()
        return [self.server.config.node_id or "dev"]

    def status_raft_stats(self, body) -> Dict[str, Any]:
        """Raft introspection for gossip bootstrap-expect: a non-zero log
        index means a cluster already exists, so virgin joiners must not
        self-bootstrap (reference: maybeBootstrap probing peers,
        nomad/serf.go:80-139)."""
        raft = self.server.raft
        if hasattr(raft, "stats"):
            stats = raft.stats()
            # A node counts as bootstrapped when it holds log/snapshot
            # state, knows peers BEYOND itself, or carries an explicit
            # cluster configuration ("configured": bootstrap_cluster /
            # Config admission / explicit peers). The last covers the
            # window between bootstrap_cluster and the first leader's noop
            # entry, when the log index is still 0 but a late joiner must
            # not form a SECOND cluster. Virgin servers always have
            # themselves in the peer set, so raw peer-set truthiness is
            # meaningless — round-3 regression: every virgin server
            # reported true and no cluster ever formed.
            return {"Bootstrapped": stats.get("last_log_index", 0) > 0
                    or stats.get("snapshot_index", 0) > 0
                    or stats.get("num_peers", 0) > 1
                    or bool(stats.get("configured")),
                    "Stats": stats}
        return {"Bootstrapped": True, "Stats": {}}  # dev mode

    # ---------------------------------------------------------------- agent
    # (reference: the serf-backed agent self RPCs behind `server-members`,
    # `join`, `force-leave` — command/agent/agent_endpoint.go + serf.go)
    def agent_members(self, body) -> List[Dict[str, Any]]:
        if self.membership is None:
            return []
        return self.membership.members()

    def agent_join(self, body) -> Dict[str, Any]:
        if self.membership is None:
            raise RuntimeError("gossip not enabled on this server")
        n = self.membership.join(list(body.get("Addresses") or []))
        return {"NumJoined": n}

    def agent_force_leave(self, body) -> Dict[str, Any]:
        if self.membership is None:
            raise RuntimeError("gossip not enabled on this server")
        ok = self.membership.force_leave(body["Node"])
        return {"Ok": ok}

    # ------------------------------------------------------------------ job
    def job_register(self, body) -> Dict[str, Any]:
        job = from_dict(Job, body["Job"])
        # Region-local authority (federation): a job whose home Region
        # differs from this server's forwards at ingress, BEFORE any
        # raft write — the job, its eval, and its allocs are owned by
        # the home region's raft domain. The remote-shed check consults
        # the cached federation health view first so a forward into a
        # region already shedding this tier bounces at the local edge
        # (typed 429-retryable) without paying the WAN hop.
        fed = self._fed()
        local = self.server.config.region
        if (federation_enabled(fed) and job.Region
                and job.Region != local):
            self.server.admit_forward(job.Region, job.Priority)
            return self._forward_region(job.Region, "Job.Register",
                                        dict(body, Region=job.Region))
        # Collected BEFORE the register mutates the job: warnings must
        # reach the submitter even when nothing else is wrong (reference
        # shape: JobRegisterResponse.Warnings). Best-effort: the schema
        # metadata lives in the client driver package, and a server-only
        # host where those modules can't import must still register jobs
        # — just without the advisory warnings.
        try:
            from nomad_tpu.client.driver import job_config_warnings

            warnings = job_config_warnings(job)
        except ImportError:
            warnings = []
        enforce = body.get("EnforceIndex")
        eval_id, jmi, index = self.server.job_register(
            job, enforce_index=enforce)
        if eval_id:
            # Async-hop link: the broker/worker/applier/client stages of
            # this evaluation resume THIS trace by eval id. (The broker
            # also links at enqueue; this covers replicated mode, where
            # the FSM hook runs on the raft apply thread with no ambient
            # context.)
            trace.link("eval", eval_id)
            trace.add_event("eval.created", eval=eval_id, job=job.ID)
        return {"EvalID": eval_id, "JobModifyIndex": jmi, "Index": index,
                "Warnings": warnings}

    def job_deregister(self, body) -> Dict[str, Any]:
        eval_id, index = self.server.job_deregister(body["JobID"])
        return {"EvalID": eval_id, "Index": index}

    def job_get(self, body) -> Dict[str, Any]:
        state = self.server.state

        def run():
            job = state.job_by_id(body["JobID"])
            return (to_dict(job) if job else None,
                    state.get_index("jobs"))

        result, index = blocking_query(
            state, [Item(job=body["JobID"])],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Job": result, "Index": index}

    def job_list(self, body) -> Dict[str, Any]:
        state = self.server.state

        def run():
            jobs = [to_dict(j) for j in state.jobs()]
            return jobs, state.get_index("jobs")

        result, index = blocking_query(
            state, [Item(table="jobs")],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Jobs": result, "Index": index}

    def job_allocations(self, body) -> Dict[str, Any]:
        state = self.server.state

        def run():
            allocs = state.allocs_by_job(body["JobID"])
            idx = max([a.ModifyIndex for a in allocs],
                      default=state.get_index("allocs"))
            return [to_dict(a) for a in allocs], idx

        result, index = blocking_query(
            state, [Item(alloc_job=body["JobID"])],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Allocations": result, "Index": index}

    def job_evaluations(self, body) -> Dict[str, Any]:
        state = self.server.state
        evals = state.evals_by_job(body["JobID"])
        return {"Evaluations": [to_dict(e) for e in evals],
                "Index": state.get_index("evals")}

    def job_evaluate(self, body) -> Dict[str, Any]:
        fed = self._fed()
        if federation_enabled(fed):
            # A job living in another region (pre-federation data, or a
            # caller that skipped the Region query param) re-evaluates in
            # its HOME region — forwarded before any raft write, like
            # registration.
            job = self.server.state.job_by_id(body["JobID"])
            local = self.server.config.region
            if (job is not None and job.Region
                    and job.Region != local):
                self.server.admit_forward(job.Region, job.Priority)
                return self._forward_region(job.Region, "Job.Evaluate",
                                            dict(body, Region=job.Region))
        eval_id, index = self.server.job_evaluate(body["JobID"])
        if eval_id:
            trace.link("eval", eval_id)
        return {"EvalID": eval_id, "Index": index}

    def job_plan(self, body) -> Dict[str, Any]:
        job = from_dict(Job, body["Job"])
        resp = self.server.job_plan(job, want_diff=body.get("Diff", True))
        return to_dict(resp)

    def periodic_force(self, body) -> Dict[str, Any]:
        self.server.periodic_force(body["JobID"])
        return {}

    # ----------------------------------------------------------------- node
    def _server_info(self) -> Dict[str, Any]:
        """Server list piggybacked on heartbeat responses so clients track
        cluster membership (reference: NodeServerInfo in UpdateStatus
        replies, node_endpoint.go:194+)."""
        return {"LeaderRPCAddr": self.status_leader({}),
                "Servers": self.status_peers({})}

    def node_register(self, body) -> Dict[str, Any]:
        node = from_dict(Node, body["Node"])
        ttl, index = self.server.node_register(node)
        return {"HeartbeatTTL": ttl, "Index": index, **self._server_info()}

    def node_heartbeat(self, body) -> Dict[str, Any]:
        """TTL refresh only — no raft write (reference: UpdateStatus with
        unchanged status skips the raft apply, node_endpoint.go:194-235).
        Heartbeat timers live on the leader (heartbeat.go), so forward."""
        if not self.server.is_leader():
            raise NotLeaderError(self.status_leader(body) or None)
        ttl = self.server.node_heartbeat(body["NodeID"])
        return {"HeartbeatTTL": ttl, **self._server_info()}

    def node_update_status(self, body) -> Dict[str, Any]:
        ttl, index = self.server.node_update_status(
            body["NodeID"], body["Status"])
        return {"HeartbeatTTL": ttl, "Index": index, **self._server_info()}

    def node_update_drain(self, body) -> Dict[str, Any]:
        index = self.server.node_update_drain(body["NodeID"], body["Drain"])
        return {"Index": index}

    def node_deregister(self, body) -> Dict[str, Any]:
        index = self.server.node_deregister(body["NodeID"])
        return {"Index": index}

    def node_evaluate(self, body) -> Dict[str, Any]:
        eval_ids = self.server.node_evaluate(body["NodeID"])
        return {"EvalIDs": eval_ids}

    def node_get(self, body) -> Dict[str, Any]:
        state = self.server.state

        def run():
            node = state.node_by_id(body["NodeID"])
            return (to_dict(node) if node else None,
                    state.get_index("nodes"))

        result, index = blocking_query(
            state, [Item(node=body["NodeID"])],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Node": result, "Index": index}

    def node_list(self, body) -> Dict[str, Any]:
        state = self.server.state

        def run():
            nodes = [to_dict(n) for n in state.nodes()]
            return nodes, state.get_index("nodes")

        result, index = blocking_query(
            state, [Item(table="nodes")],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Nodes": result, "Index": index}

    def node_get_allocs(self, body) -> Dict[str, Any]:
        """Full allocations for a node, blocking (reference:
        node_endpoint.go:416-472 GetAllocs)."""
        state = self.server.state

        def run():
            allocs = state.allocs_by_node(body["NodeID"])
            idx = max([a.ModifyIndex for a in allocs],
                      default=state.get_index("allocs"))
            return [to_dict(a) for a in allocs], idx

        result, index = blocking_query(
            state, [Item(alloc_node=body["NodeID"])],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Allocs": result, "Index": index}

    def node_get_client_allocs(self, body) -> Dict[str, Any]:
        """alloc_id -> AllocModifyIndex map, blocking — the client's cheap
        pull signal (reference: node_endpoint.go:474-528). Served off the
        columnar-aware index map so sweep-placed allocs never materialize
        for a poll that only compares indexes."""
        state = self.server.state
        node_id = body["NodeID"]

        def run():
            return state.client_alloc_map(node_id)

        result, index = blocking_query(
            state, [Item(alloc_node=node_id)],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Allocs": result, "Index": index}

    def node_update_alloc(self, body) -> Dict[str, Any]:
        allocs = [from_dict(Allocation, a) for a in body["Allocs"]]
        index = self.server.node_update_allocs(allocs)
        return {"Index": index}

    # ----------------------------------------------------------------- eval
    def eval_get(self, body) -> Dict[str, Any]:
        state = self.server.state

        def run():
            ev = state.eval_by_id(body["EvalID"])
            return (to_dict(ev) if ev else None,
                    state.get_index("evals"))

        result, index = blocking_query(
            state, [Item(eval=body["EvalID"])],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Eval": result, "Index": index}

    def eval_list(self, body) -> Dict[str, Any]:
        state = self.server.state
        return {"Evaluations": [to_dict(e) for e in state.evals()],
                "Index": state.get_index("evals")}

    def eval_allocations(self, body) -> Dict[str, Any]:
        state = self.server.state
        allocs = state.allocs_by_eval(body["EvalID"])
        return {"Allocations": [to_dict(a) for a in allocs],
                "Index": state.get_index("allocs")}

    def eval_dequeue(self, body) -> Dict[str, Any]:
        """(reference: eval_endpoint.go:68 — leader-brokered dequeue)"""
        if not self.server.eval_broker.enabled():
            raise NotLeaderError(self.status_leader(body) or None)
        ev, token = self.server.eval_broker.dequeue(
            body["Schedulers"], body.get("Timeout", 0.5))
        # WaitIndex: the leader's committed index at dequeue time. The
        # worker's scheduling snapshot must include every commit that
        # preceded this dequeue (ModifyIndex alone misses plans committed
        # after this eval was CREATED but before it was dequeued — a
        # duplicate eval would double-place its job from a stale follower
        # replica). Under federation the broker's per-eval RELEASE FLOOR
        # replaces the global latest index: per-job serialization makes
        # it a sufficient bound, and a follower worker then only waits
        # for replication up to the floor instead of chasing the
        # leader's every mid-storm commit (follower-snapshot scheduling).
        wait_index = None
        if ev is not None:
            wait_index = self.server.eval_broker.release_floor(ev.ID)
        if wait_index is None:
            wait_index = self.server.state.latest_index()
        return {"Eval": to_dict(ev) if ev else None, "Token": token,
                "WaitIndex": wait_index}

    def eval_ack(self, body) -> Dict[str, Any]:
        if not self.server.eval_broker.enabled():
            raise NotLeaderError(self.status_leader(body) or None)
        self.server.eval_broker.ack(body["EvalID"], body["Token"])
        return {}

    def eval_nack(self, body) -> Dict[str, Any]:
        if not self.server.eval_broker.enabled():
            raise NotLeaderError(self.status_leader(body) or None)
        self.server.eval_broker.nack(body["EvalID"], body["Token"])
        return {}

    def _local_backend(self):
        """The leader-side worker seam: Eval.Update / Plan.Submit delegate
        to the SAME code path local workers use, so stale-token and reset
        semantics cannot diverge between in-process and RPC scheduling."""
        from nomad_tpu.server.worker import LocalBackend
        return LocalBackend(self.server.raft, self.server.eval_broker,
                            self.server.plan_queue)

    def eval_update(self, body) -> Dict[str, Any]:
        """Worker-side eval create/update/reblock through consensus
        (reference: Eval.Update/Create/Reblock, eval_endpoint.go:98-187 —
        one endpoint here since all three are an EvalUpdate apply plus an
        outstanding-token refresh). A stale token raises out of
        outstanding_reset BEFORE the apply — the FSM applies EvalUpdate
        unconditionally, so this pre-check is the write barrier."""
        if not self.server.eval_broker.enabled():
            raise NotLeaderError(self.status_leader(body) or None)
        backend = self._local_backend()
        backend.eval_update(list(body["Evals"]),
                            body.get("EvalToken", ""),
                            body.get("ResetID", ""))
        return {"Index": self.server.state.latest_index()}

    # ----------------------------------------------------------------- plan
    def plan_submit(self, body) -> Dict[str, Any]:
        """Leader-brokered plan submission for remote scheduling workers
        (reference: Plan.Submit, plan_endpoint.go:16-35). Blocks until the
        plan applier responds; the result's RefreshIndex tells the remote
        worker how far its local replica must catch up. A stale/unknown
        EvalToken raises out of the broker reset exactly as it does for a
        local worker; the applier's own token check remains the commit-time
        authority (plan_apply.py)."""
        if not self.server.plan_queue.enabled():
            raise NotLeaderError(self.status_leader(body) or None)
        plan = from_dict(Plan, body["Plan"])
        result = self._local_backend().submit_plan(plan)
        return {"Result": to_dict(result) if result is not None else None}

    # ---------------------------------------------------------------- alloc
    def alloc_list(self, body) -> Dict[str, Any]:
        state = self.server.state

        def run():
            allocs = [to_dict(a) for a in state.allocs()]
            return allocs, state.get_index("allocs")

        result, index = blocking_query(
            state, [Item(table="allocs")],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Allocations": result, "Index": index}

    def alloc_get(self, body) -> Dict[str, Any]:
        state = self.server.state
        alloc = state.alloc_by_id(body["AllocID"])
        return {"Alloc": to_dict(alloc) if alloc else None,
                "Index": state.get_index("allocs")}

    def alloc_get_many(self, body) -> Dict[str, Any]:
        state = self.server.state
        allocs = [state.alloc_by_id(aid) for aid in body["AllocIDs"]]
        return {"Allocs": [to_dict(a) for a in allocs if a is not None],
                "Index": state.get_index("allocs")}

    # ------------------------------------------------------ service registry
    def service_sync(self, body) -> Dict[str, Any]:
        """Batched registry sync from one node's service manager (write;
        forwards to the leader via NotLeaderError like every other write)."""
        from nomad_tpu.structs import ServiceRegistration

        upserts = [from_dict(ServiceRegistration, r)
                   if isinstance(r, dict) else r
                   for r in body.get("Upserts", ())]
        index = self.server.service_sync(upserts, list(body.get("Deletes",
                                                                ())))
        return {"Index": index}

    def service_list(self, body) -> Dict[str, Any]:
        state = self.server.state

        def run():
            regs = [to_dict(s) for s in state.services()]
            return regs, state.get_index("services")

        result, index = blocking_query(
            state, [Item(table="services")],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Services": result, "Index": index}

    def service_get(self, body) -> Dict[str, Any]:
        """Instances of one service name, blocking — the discovery query."""
        state = self.server.state
        name = body["ServiceName"]

        def run():
            regs = state.services_by_name(name)
            # Table index, not max(ModifyIndex): deregistering the newest
            # instance must not make the reported index regress (a watcher
            # would never see the delete).
            return [to_dict(r) for r in regs], state.get_index("services")

        result, index = blocking_query(
            state, [Item(service_name=name)],
            body.get("MinQueryIndex", 0), body.get("MaxQueryTime", 0), run)
        return {"Services": result, "Index": index}

    # --------------------------------------------------------------- region
    def region_list(self, body) -> List[str]:
        if self.region_lister is not None:
            return sorted(self.region_lister())
        return [self.server.config.region]

    def federation_health(self, body) -> Dict[str, Any]:
        """This region's QoS tier health (depths, SLO burn, admission
        thresholds, node count) — polled cross-region by federation
        leaders to build the global admission/SLO-burn view
        (federation/qos.py)."""
        return health_payload(self.server)

    # --------------------------------------------------------------- system
    def system_gc(self, body) -> Dict[str, Any]:
        self.server.force_gc()
        return {}
