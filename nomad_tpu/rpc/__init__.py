"""Network RPC plane (reference: nomad/rpc.go, nomad/pool.go,
nomad/raft_rpc.go — a single TCP port multiplexing byte-prefixed streams:
Nomad msgpack-RPC, Raft traffic, and multiplexed sessions).

Design: every connection opens with one stream-type byte. The NOMAD stream
carries length-prefixed msgpack frames `{Seq, Method, Body}` /
`{Seq, Error, Body}`; requests are sequence-multiplexed so one connection
sustains many concurrent in-flight RPCs (the role yamux plays in the
reference, pool.go:111). The RAFT stream carries the same framing but
dispatches into the local RaftNode, letting consensus ride the shared port
(reference: raft_rpc.go RaftLayer).

Server-side, each request is handled on a worker thread so blocking queries
(watch-based, max 300s, reference rpc.go:294-349) never head-of-line block
the connection.
"""

from .wire import (RPC_NOMAD, RPC_RAFT, MessageCodec, recv_frame, send_frame)
from .pool import ConnPool, RPCError
from .server import RPCServer
from .transport import TCPTransport
from .endpoints import Endpoints, blocking_query

__all__ = [
    "RPC_NOMAD", "RPC_RAFT", "MessageCodec", "recv_frame", "send_frame",
    "ConnPool", "RPCError", "RPCServer", "TCPTransport", "Endpoints",
    "blocking_query",
]
