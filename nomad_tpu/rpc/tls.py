"""TLS for the single-port RPC mux (reference: nomad/rpc.go:25-30 reserves
the rpcTLS stream byte; handleConn:88-132 unwraps it and re-reads the inner
stream type; TLSConfig in nomad/config.go).

Mutual TLS: the server presents its cert and (verify_incoming) requires a
client cert signed by the same CA; outgoing connections present the node
cert and verify the server against the CA. One CA per cluster region is the
deployment model.
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass
class TLSConfig:
    """(reference: nomad/config.go TLSConfig)"""

    enable_rpc: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    # Require client certs signed by the CA (mutual TLS) and refuse
    # plaintext streams entirely.
    verify_incoming: bool = True
    # Verify the server cert's hostname on outgoing connections. Off by
    # default: cluster members dial each other by IP:port and certs are
    # typically issued per-role, not per-host (reference default).
    verify_server_hostname: bool = False


def server_context(cfg: TLSConfig) -> Optional[ssl.SSLContext]:
    if not cfg.enable_rpc:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    if cfg.ca_file:
        ctx.load_verify_locations(cfg.ca_file)
    if cfg.verify_incoming:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_context(cfg: TLSConfig) -> Optional[ssl.SSLContext]:
    if not cfg.enable_rpc:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if cfg.ca_file:
        ctx.load_verify_locations(cfg.ca_file)
    ctx.check_hostname = cfg.verify_server_hostname
    ctx.verify_mode = ssl.CERT_REQUIRED
    if cfg.cert_file:
        ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    return ctx
