"""TCP raft transport: raft RPCs over the shared port's RAFT stream
(reference: nomad/raft_rpc.go RaftLayer carving raft traffic out of the
single listener). Node ids ARE advertised "host:port" addresses, exactly as
the reference's raft peer list stores addresses (server.go:608-712).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from nomad_tpu.raft.transport import TransportError
from nomad_tpu.telemetry import trace

from .pool import ConnPool, ConnError, RPCError
from .wire import RPC_RAFT


class TCPTransport:
    """Implements the raft Transport protocol over a ConnPool. The receiving
    side is the RPCServer's raft_handler, registered via `register`."""

    def __init__(self, pool: Optional[ConnPool] = None,
                 request_timeout: float = 5.0):
        self.pool = pool or ConnPool(stream_type=RPC_RAFT)
        self.request_timeout = request_timeout
        self._handler: Optional[Callable] = None
        self.node_id: Optional[str] = None

    def register(self, node_id: str, handler) -> None:
        self.node_id = node_id
        self._handler = handler

    def deregister(self, node_id: str) -> None:
        self._handler = None

    def handle(self, method: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Entry point wired into RPCServer(raft_handler=...)."""
        if self._handler is None:
            raise TransportError("raft not initialized")
        return self._handler(method, payload)

    def send(self, target: str, method: str, payload: Dict[str, Any]
             ) -> Dict[str, Any]:
        # Child-only span: raft replication threads carry no ambient
        # trace, but a traced caller blocking on consensus (apply_command
        # under a plan apply) sees its peer round trips.
        with trace.span("raft.rpc." + method):
            try:
                return self.pool.call(target, method, payload,
                                      timeout=self.request_timeout)
            except (ConnError, OSError, TimeoutError) as exc:
                raise TransportError(f"raft rpc to {target} failed: {exc}")
            except RPCError as exc:
                raise TransportError(str(exc))
