"""ClusterServer: a network-served control-plane node — RPC listener, raft
over TCP, endpoint dispatch — the composition the reference performs in
NewServer (nomad/server.go:166-263: setupRPC + setupRaft on one port).

Two-phase boot because raft peers are addresses: bind the listener first
(learning the port), then `connect(peers)` to build the Server and start
serving. Gossip-driven joins use the raft membership API afterwards.
"""

from __future__ import annotations

from typing import List, Optional

from .endpoints import Endpoints
from .pool import ConnPool
from .server import RPCServer
from .transport import TCPTransport


class ClusterServer:
    def __init__(self, config, bind_addr: str = "127.0.0.1", port: int = 0):
        self.config = config
        self.rpc_server = RPCServer(bind_addr, port)
        self.addr = self.rpc_server.addr
        config.node_id = self.addr
        self.server = None
        self.endpoints: Optional[Endpoints] = None
        self.transport: Optional[TCPTransport] = None

    def connect(self, peers: List[str], log_store=None, raft_config=None,
                region_router=None, region_lister=None) -> None:
        from nomad_tpu.server.server import Server

        self.transport = TCPTransport()
        self.server = Server(self.config, transport=self.transport,
                             peers=list(peers), log_store=log_store,
                             raft_config=raft_config)
        self.endpoints = Endpoints(self.server,
                                   region_router=region_router,
                                   region_lister=region_lister)
        self.rpc_server.rpc_handler = self.endpoints.handle
        self.rpc_server.raft_handler = self.transport.handle

    def start(self) -> None:
        if self.server is None:
            raise RuntimeError("connect() before start()")
        self.rpc_server.start()
        self.server.start()

    def shutdown(self) -> None:
        if self.server is not None:
            self.server.shutdown()
        self.rpc_server.shutdown()
        if self.endpoints is not None:
            self.endpoints.pool.close()
        if self.transport is not None:
            self.transport.pool.close()
