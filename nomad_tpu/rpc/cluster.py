"""ClusterServer: a network-served control-plane node — RPC listener, raft
over TCP, endpoint dispatch — the composition the reference performs in
NewServer (nomad/server.go:166-263: setupRPC + setupRaft on one port).

Two-phase boot because raft peers are addresses: bind the listener first
(learning the port), then `connect(peers)` to build the Server and start
serving. Gossip-driven joins use the raft membership API afterwards.
"""

from __future__ import annotations

from typing import List, Optional

from .endpoints import Endpoints
from .pool import ConnPool
from .server import RPCServer
from .transport import TCPTransport


class ClusterServer:
    def __init__(self, config, bind_addr: str = "127.0.0.1", port: int = 0,
                 tls=None):
        """tls: optional rpc.tls.TLSConfig — every stream on the shared
        port (application RPC and raft) then rides the TLS mux byte, and
        with verify_incoming plaintext connections are refused outright
        (reference: rpc.go:25-30,88-132 + config.go TLSConfig)."""
        from .tls import client_context, server_context

        self.config = config
        self.bind_addr = bind_addr
        self.tls = tls
        self._client_tls = client_context(tls) if tls else None
        self.rpc_server = RPCServer(
            bind_addr, port,
            tls_context=server_context(tls) if tls else None,
            require_tls=bool(tls and tls.enable_rpc and tls.verify_incoming))
        self.addr = self.rpc_server.addr
        config.node_id = self.addr
        self.server = None
        self.endpoints: Optional[Endpoints] = None
        self.transport: Optional[TCPTransport] = None
        self.membership = None

    def connect(self, peers: List[str], log_store=None, raft_config=None,
                region_router=None, region_lister=None) -> None:
        from nomad_tpu.server.server import Server

        from .pool import ConnPool
        from .wire import RPC_NOMAD, RPC_RAFT

        self.transport = TCPTransport(
            pool=ConnPool(stream_type=RPC_RAFT,
                          tls_context=self._client_tls))
        self.server = Server(self.config, transport=self.transport,
                             peers=list(peers), log_store=log_store,
                             raft_config=raft_config)
        self.endpoints = Endpoints(self.server,
                                   pool=ConnPool(
                                       stream_type=RPC_NOMAD,
                                       tls_context=self._client_tls),
                                   region_router=region_router,
                                   region_lister=region_lister)
        self.rpc_server.rpc_handler = self.endpoints.handle
        self.rpc_server.raft_handler = self.transport.handle

    def enable_gossip(self, node_name: str, gossip_port: int = 0,
                      join: Optional[List[str]] = None,
                      gossip_config=None):
        """Attach the membership plane (reference: setupSerf,
        nomad/server.go:714-752). Call after connect(), before/after start().
        Returns the ServerMembership; its gossip addr is
        `membership.memberlist.addr:port` for other servers to join."""
        from nomad_tpu.server.membership import ServerMembership

        if self.server is None:
            raise RuntimeError("connect() before enable_gossip()")
        self.membership = ServerMembership(
            self.server, rpc_addr=self.addr, node_name=node_name,
            bind_addr=self.bind_addr, gossip_port=gossip_port,
            gossip_config=gossip_config, tls_context=self._client_tls)
        # Route cross-region RPCs through the gossip view.
        self.endpoints.region_router = self.membership.region_router
        self.endpoints.region_lister = self.membership.region_lister
        self.endpoints.membership = self.membership
        if getattr(self.server, "fed_health", None) is not None:
            # Federation: the leader's health loop polls every other
            # region's Federation.Health through the membership plane's
            # WAN pool into the shared view (federation/qos.py).
            health = self.server.fed_health
            membership = self.membership
            self.server.fed_poll = (
                lambda: membership.poll_federation_health(health))
        self.membership.start()
        if join:
            self.membership.retry_join(join)
        return self.membership

    def start(self) -> None:
        if self.server is None:
            raise RuntimeError("connect() before start()")
        self.rpc_server.start()
        self.server.start()
        # Scheduling workers on every server: followers dequeue and submit
        # plans over leader RPC (reference: worker.go run on all servers).
        if (self.config.distributed_workers
                and self.config.num_schedulers > 0):
            self.server.start_remote_workers(self.endpoints.pool)

    def shutdown(self) -> None:
        if self.membership is not None:
            self.membership.shutdown()
        if self.server is not None:
            self.server.shutdown()
        self.rpc_server.shutdown()
        if self.endpoints is not None:
            self.endpoints.pool.close()
        if self.transport is not None:
            self.transport.pool.close()
