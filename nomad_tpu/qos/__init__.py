"""QoS subsystem: priority lanes, deadline-aware windows, admission
control, and alloc preemption for the served scheduling path.

See README "QoS & SLO serving" for the operator view. Everything here is
behind ``QoSConfig.enabled`` — disabled (the default), the served path is
bit-identical to the pre-QoS FIFO behavior.
"""

from .admission import AdmissionController, QoSBackpressureError
from .preemption import (
    ALLOC_PREEMPTED,
    PreemptedOption,
    attempt_preemption,
    find_preemption,
)
from .tiers import (
    N_TIERS,
    TIER_HIGH,
    TIER_LOW,
    TIER_NAMES,
    TIER_NORMAL,
    QoSConfig,
    QoSCounters,
    qos_enabled,
)

__all__ = [
    "ALLOC_PREEMPTED",
    "AdmissionController",
    "N_TIERS",
    "PreemptedOption",
    "QoSBackpressureError",
    "QoSConfig",
    "QoSCounters",
    "TIER_HIGH",
    "TIER_LOW",
    "TIER_NAMES",
    "TIER_NORMAL",
    "attempt_preemption",
    "find_preemption",
    "qos_enabled",
]
