"""Alloc preemption: high-tier placements may evict lower-tier allocs.

A capability extension beyond the reference (Nomad v0.4 stops at
priority-ordered dequeue): when a HIGH-tier placement finds no feasible
capacity, the scheduler looks for a node where evicting strictly
lower-tier allocations frees enough room, ranks eviction candidates by
(victim job priority ascending, then youngest first — least work lost),
and emits evictions + the placement in ONE plan. Atomicity is the plan
applier's per-node verify: a node's evictions and placements commit
together or not at all (plan_apply.evaluate_plan skips BOTH sides of a
node that fails its fit re-check), and the whole group lands as one raft
entry — there is no window where a victim was stopped but the
high-priority alloc never arrived. The FSM applies NodeUpdate (evictions)
before NodeAllocation (placements), so the state store observes
stop-then-place in order; evicted allocs are terminal immediately
(DesiredStatus=evict), which is what frees the tensor-usage row at commit.

Plans that preempt carry a ``_preempt`` descriptor
(``{node_id: [victim alloc ids]}``) so the applier's
``plan.preempt.commit`` failpoint and the chaos/overlap tests can see
them; a worker killed mid-commit nacks, the broker redelivers the eval,
and the retry re-plans against committed state — exactly-once, no lost
evictions, no duplicate allocs. Like the system sweep's ``_sweep``, the
descriptor is an IN-PROCESS annotation (it does not cross the Plan.Submit
wire from remote workers) — atomicity never depends on it: the applier's
per-node verify drops a node's evictions and placements together with or
without the marker.

Scope guards (all conservative, all fall back to the blocked-eval path):

- only service/batch jobs preempt, and only allocs whose job maps to a
  strictly LOWER tier (never high-on-high churn);
- task groups asking network resources never preempt (port offers are
  per-node host state the freed capacity math can't model);
- at most ``qos.max_victims`` evictions per placed instance.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

from nomad_tpu.structs import Allocation, Job, Resources
from nomad_tpu.structs.funcs import allocs_fit
from nomad_tpu.structs.structs import (
    AllocDesiredStatusEvict,
    JobDefaultPriority,
    NodeStatusReady,
    TaskGroup,
)
from nomad_tpu.telemetry import metrics, trace

from .tiers import TIER_HIGH, QoSConfig, QoSCounters

logger = logging.getLogger("nomad.qos.preempt")

ALLOC_PREEMPTED = "alloc preempted by higher-priority job"

# Cap on nodes *with evictable load* fully costed per failed instance:
# preemption runs on the rare exhausted-capacity slow path, but a 50k-node
# sweep of per-alloc fit math would still be a tail stall.
_MAX_CANDIDATES = 64


class PreemptedOption:
    """Duck-type of scheduler SelectedOption for a preempted placement
    (build_placement_allocs only reads ``node`` and ``task_resources``)."""

    __slots__ = ("node", "score", "task_resources", "victims")

    def __init__(self, node, task_resources, victims):
        self.node = node
        self.score = 0.0
        self.task_resources = task_resources
        self.victims = victims


def _tg_asks_network(tg: TaskGroup) -> bool:
    for task in tg.Tasks:
        r = task.Resources
        if r is not None and r.Networks:
            return True
    return False


_probe_seq = 0


def _probe_alloc(tg: TaskGroup) -> Allocation:
    """A throwaway alloc carrying the TG's per-task resources, so the
    eviction fit check runs the SAME accounting (structs.allocs_fit) the
    plan applier re-verifies with. Unique IDs: probes standing in for a
    window's earlier placements coexist in one live list."""
    global _probe_seq
    _probe_seq += 1
    probe = Allocation(
        ID=f"_preempt_probe_{_probe_seq}",
        TaskResources={t.Name: (t.Resources if t.Resources is not None
                                else Resources()) for t in tg.Tasks},
    )
    # Probes occupy capacity in the fit math but must never be CHOSEN as
    # victims (they stand in for this very eval's placements).
    probe._qos_probe = True
    return probe


def find_preemption(state, plan, job: Job, tg: TaskGroup,
                    nodes: Sequence, qos: QoSConfig,
                    job_prio_cache: Optional[Dict[str, int]] = None,
                    pending: Optional[Dict[str, List[Allocation]]] = None
                    ) -> Optional[PreemptedOption]:
    """Pick (node, minimal victim set) for one failed TG instance, or
    None. ``plan`` is consulted so victims already claimed by this eval
    are accounted; ``pending`` carries per-node probe allocs for
    placements this eval has CHOSEN but not yet written into the plan
    (stack selections and earlier preemption picks — without them,
    sibling instances of a Count>=2 job double-book one node's freed
    capacity and the applier bounces the whole node every retry).
    Neither input is mutated here."""
    from nomad_tpu.scheduler.util import task_group_constraints
    from nomad_tpu.tensor.constraints import (
        node_has_drivers,
        node_meets_constraints,
    )

    if _tg_asks_network(tg):
        return None
    placing_tier = qos.tier_of(job.Priority)
    cons = task_group_constraints(tg)
    probe = _probe_alloc(tg)
    prio_of = job_prio_cache if job_prio_cache is not None else {}

    def victim_priority(alloc: Allocation) -> int:
        prio = prio_of.get(alloc.JobID)
        if prio is None:
            victim_job = state.job_by_id(alloc.JobID)
            prio = (victim_job.Priority if victim_job is not None
                    else JobDefaultPriority)
            prio_of[alloc.JobID] = prio
        return prio

    best: Optional[PreemptedOption] = None
    costed = 0
    for node in nodes:
        if node.Status != NodeStatusReady or node.Drain:
            continue
        # In-plan bookkeeping: allocs this eval already placed here count
        # as live (both plan entries and not-yet-planned `pending`
        # probes); allocs it already evicts are gone.
        evicting = {a.ID for a in plan.NodeUpdate.get(node.ID, ())}
        live = [a for a in state.allocs_by_node_terminal(node.ID, False)
                if a.ID not in evicting]
        live.extend(plan.NodeAllocation.get(node.ID, ()))
        if pending:
            live.extend(pending.get(node.ID, ()))
        evictable = [
            a for a in live
            if a.JobID != job.ID
            and not getattr(a, "_qos_probe", False)
            and qos.tier_of(victim_priority(a)) > placing_tier
        ]
        if not evictable:
            continue
        # Constraint feasibility first — evicting from a node the TG can
        # never run on frees nothing. (Capacity was the reason placement
        # failed, but constraints decide which nodes are candidates.)
        if not (node_meets_constraints(node, job.Constraints)
                and node_meets_constraints(node, cons.constraints)
                and node_has_drivers(node, cons.drivers)):
            continue
        costed += 1
        evict_ids = {v.ID for v in evictable}
        keep = [a for a in live if a.ID not in evict_ids]
        try:
            fit, _, _ = allocs_fit(node, keep + [probe])
        except ValueError:
            continue
        if not fit:
            continue  # even a full sweep of the tier can't make room
        # Minimal victim set: lowest-priority first; among equals the
        # YOUNGEST (highest CreateIndex) — least completed work lost.
        ranked = sorted(evictable,
                        key=lambda a: (victim_priority(a), -a.CreateIndex))
        victims: List[Allocation] = []
        remaining = list(live)
        for victim in ranked:
            if len(victims) >= qos.max_victims:
                victims = []
                break
            victims.append(victim)
            remaining = [a for a in remaining if a.ID != victim.ID]
            try:
                fit, _, _ = allocs_fit(node, remaining + [probe])
            except ValueError:
                fit = False
            if fit:
                break
        else:
            victims = []
        if not victims:
            continue
        if best is None or len(victims) < len(best.victims):
            best = PreemptedOption(
                node=node,
                task_resources={t.Name: (t.Resources.copy()
                                         if t.Resources is not None
                                         else Resources())
                                for t in tg.Tasks},
                victims=victims)
            if len(victims) == 1:
                break  # cannot do better
        if costed >= _MAX_CANDIDATES:
            break
    return best


def attempt_preemption(state, plan, eval_id: str, job: Job, place,
                       options: List, nodes: Sequence, qos: QoSConfig,
                       counters: Optional[QoSCounters] = None,
                       log: Optional[logging.Logger] = None) -> List:
    """Fill failed slots in ``options`` by preempting lower-tier allocs.
    Mutates ``plan`` (victim evictions + ``_preempt`` descriptor) and
    returns the patched options list; build_placement_allocs then emits
    the placements exactly as if the stack had selected them."""
    log = log or logger
    if qos.tier_of(job.Priority) != TIER_HIGH:
        return options
    out = list(options)
    prio_cache: Dict[str, int] = {}
    # Placements this eval has already CHOSEN but not yet written into
    # the plan: the stack's successful selections, plus each preemption
    # pick as it lands. Without these, sibling instances of a Count>=2
    # job all "find" the same freed capacity and the applier bounces the
    # node on every retry.
    pending: Dict[str, List[Allocation]] = {}
    for tup, option in zip(place, options):
        if option is not None:
            pending.setdefault(option.node.ID, []).append(
                _probe_alloc(tup.TaskGroup))
    for i, (tup, option) in enumerate(zip(place, options)):
        if option is not None:
            continue
        if counters is not None:
            counters.incr("preempt_attempts")
        metrics.incr_counter(("nomad", "qos", "preempt", "attempts"))
        pick = find_preemption(state, plan, job, tup.TaskGroup, nodes, qos,
                               job_prio_cache=prio_cache, pending=pending)
        if pick is None:
            continue
        for victim in pick.victims:
            plan.append_update(victim, AllocDesiredStatusEvict,
                               ALLOC_PREEMPTED)
        descriptor = getattr(plan, "_preempt", None)
        if descriptor is None:
            descriptor = plan._preempt = {}
            plan._preempt_counts = {}
        descriptor.setdefault(pick.node.ID, []).extend(
            v.ID for v in pick.victims)
        # Instances placed VIA preemption per node: a node can also carry
        # this plan's normally-selected placements, and the commit-side
        # counters must not claim those as preemptions.
        plan._preempt_counts[pick.node.ID] = \
            plan._preempt_counts.get(pick.node.ID, 0) + 1
        out[i] = pick
        pending.setdefault(pick.node.ID, []).append(
            _probe_alloc(tup.TaskGroup))
        # placed/evictions counters are COMMIT-side (plan_apply counts
        # them when the verified plan lands): a rejected preemption plan
        # must not inflate "landed" numbers.
        trace.add_event("qos.preempt", eval=eval_id, node=pick.node.ID,
                        victims=len(pick.victims))
        log.debug("eval %s: preempting %d alloc(s) on node %s for job %s",
                  eval_id, len(pick.victims), pick.node.ID, job.ID)
    return out
