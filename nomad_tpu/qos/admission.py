"""Admission control at submission ingress (the Eval.enqueue seam).

When the broker's backlog for a tier crosses its configured depth, or a
HIGHER tier is burning its latency SLO, new low-tier submissions are shed
*before* they cost a raft write, an eval, and a window slot. The shed
surfaces to the submitter as :class:`QoSBackpressureError` — typed, so it
crosses the RPC wire with ``remote_type`` intact and maps to HTTP 429 —
and the API client retries it with the shared RetryPolicy (api/client.py).

Design notes:

- The controller is STATELESS policy over broker introspection
  (``tier_depths`` / ``slo_burn``): the broker already knows queue depth
  and deadline misses, so admission adds no bookkeeping to the hot path.
- Only *submission* ingress is gated (Job.Register / Job.Evaluate with a
  user trigger). Internally generated evals — node updates, deregisters,
  blocked-eval requeues, periodic launches — always pass: shedding a
  deregister or a capacity-retry would wedge cluster reconciliation.
- High tier is never shed by the burn rule (there is no higher tier to
  protect) and by default has unlimited depth.
"""

from __future__ import annotations

from typing import Optional

from nomad_tpu.resilience import failpoints
from nomad_tpu.telemetry import metrics

from .tiers import TIER_NAMES, QoSConfig, QoSCounters, qos_enabled


class QoSBackpressureError(Exception):
    """A submission was shed by admission control. Retryable: nothing was
    written, so the submitter backs off and re-sends (the API client does
    this automatically with RetryPolicy). ``retry_after`` is an advisory
    backoff hint in seconds."""

    def __init__(self, tier: str, reason: str, retry_after: float = 0.5):
        super().__init__(
            f"submission shed ({tier} tier): {reason}; "
            f"retry after {retry_after:g}s")
        self.tier = tier
        self.reason = reason
        self.retry_after = retry_after


class AdmissionController:
    """Backlog/SLO-burn admission policy over the broker's tier state.

    Federated mode additionally consults the polled global view
    (federation/qos.py FederationHealth): a cross-region forward whose
    HOME region is already shedding the submission's tier is shed at
    THIS edge — same typed error, no WAN hop — so a storm region sheds
    its own load (local ``admit``) while remote edges stop feeding it
    (``admit_forward``), and no other region's high tier ever waits on
    a doomed forward."""

    def __init__(self, qos: Optional[QoSConfig], broker,
                 counters: Optional[QoSCounters] = None,
                 fed=None, fed_health=None):
        self.qos = qos
        self.broker = broker
        self.counters = counters or QoSCounters()
        # FederationConfig + FederationHealth (both None when federation
        # is off — admit_forward is then a no-op, bit-identical path).
        self.fed = fed
        self.fed_health = fed_health

    def _shed(self, tier: int, reason: str,
              retry_after: float) -> "QoSBackpressureError":
        self.counters.incr("shed")
        metrics.incr_counter(("nomad", "qos", "admission", "shed"))
        return QoSBackpressureError(TIER_NAMES[tier], reason, retry_after)

    def admit(self, priority: int) -> None:
        """Gate one submission; raises :class:`QoSBackpressureError` to
        shed it. A no-op unless QoS is enabled."""
        if not qos_enabled(self.qos):
            return
        qos = self.qos
        tier = qos.tier_of(priority)
        # Failure seam: "drop" forces a shed (the backpressure path under
        # test), "error" surfaces as a failed submission, "delay" models a
        # slow admission check (the "delays" half of shed-or-delay).
        if failpoints.fire("broker.admission") == "drop":
            raise self._shed(tier, "admission failpoint", 0.5)
        depths = self.broker.tier_depths()
        limit = qos.admit_depth[tier]
        if limit and depths[tier] >= limit:
            raise self._shed(
                tier, f"tier backlog {depths[tier]} >= {limit}",
                min(5.0, 0.25 * (1 + depths[tier] / max(1, limit))))
        if tier > 0:
            burn = self.broker.slo_burn()
            for higher in range(tier):
                if burn[higher] > qos.burn_shed and depths[higher]:
                    raise self._shed(
                        tier,
                        f"{TIER_NAMES[higher]} tier burning SLO "
                        f"({burn[higher]:.0%} of recent completions over "
                        f"deadline)", 1.0)
        self.counters.incr("admitted")
        metrics.incr_counter(("nomad", "qos", "admission", "admit"))

    def admit_forward(self, region: str, priority: int) -> None:
        """Gate one cross-region forward against the target region's
        cached health; raises :class:`QoSBackpressureError` to shed at
        the local edge. No-op unless QoS + federation remote-shed are on
        and a fresh health entry exists (stale/unknown = forward and let
        the home region decide)."""
        if not qos_enabled(self.qos) or self.fed_health is None:
            return
        if self.fed is None or not getattr(self.fed, "remote_shed", False):
            return
        tier = self.qos.tier_of(priority)
        reason = self.fed_health.region_shedding(region, tier)
        if reason is not None:
            self.counters.incr("forward_shed")
            metrics.incr_counter(("nomad", "rpc", "forward", "shed"))
            raise QoSBackpressureError(TIER_NAMES[tier], reason, 1.0)
