"""QoS tier model: priority lanes, deadline budgets, and shared counters.

The served path (broker -> worker windows -> plan queue) orders work by raw
``Priority`` but treats every eval as latency-equivalent. For multi-tenant
serving the product is BOUNDED TAILS, not just throughput: a Priority=100
eval must not wait out a 10k-eval Priority=1 storm. This module defines the
tier mapping the whole QoS subsystem shares:

  high   (Priority >= high_floor)  interactive / SLO-bearing traffic
  normal (in between)              default batch of work
  low    (Priority <= low_ceiling) best-effort / backfill

Three mechanisms hang off it (see README "QoS & SLO serving"):

- **Tiered lanes** in the EvalBroker: high drains first; lower tiers age
  one tier per ``aging_s`` seconds queued, so a saturating high-tier storm
  can delay but never permanently starve them.
- **Deadline-aware windows** in the PipelinedWorker: each window inherits a
  latency budget from its oldest eval's tier deadline and cuts the batch
  fill short rather than blowing it (``window_fill``).
- **Admission control + preemption** (qos/admission.py, qos/preemption.py)
  read the same tier mapping so "low tier" means one thing everywhere.

``enabled=False`` (the default) must leave the served path bit-identical
to the pre-QoS FIFO behavior — every consumer guards on it before touching
tier logic, and the equivalence test in tests/test_qos.py holds the line.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from nomad_tpu.analysis import guarded_by

TIER_HIGH = 0
TIER_NORMAL = 1
TIER_LOW = 2
N_TIERS = 3
TIER_NAMES = ("high", "normal", "low")


@dataclass
class QoSConfig:
    """Knobs for the QoS subsystem. One instance is shared (read-only
    after boot) by the broker, workers, admission controller, scheduler
    preemption, and the sched-stats surface."""

    enabled: bool = False
    # Priority -> tier mapping. JobMaxPriority is 100, default 50.
    high_floor: int = 70
    low_ceiling: int = 30
    # Anti-starvation: a queued eval's EFFECTIVE tier rises one level per
    # aging_s seconds waited, so saturating high-tier load can delay lower
    # tiers but never park them forever. 0 disables aging.
    aging_s: float = 2.0
    # Per-tier end-to-end latency budget (seconds), high -> low. Drives
    # deadline-aware window sizing and the SLO-burn counters.
    deadlines_s: Tuple[float, float, float] = (0.25, 1.0, 5.0)
    # Admission control: shed a tier's submissions once its ready backlog
    # reaches this depth (0 = unlimited). High tier is deliberately
    # unlimited by default — admission exists to protect it.
    admit_depth: Tuple[int, int, int] = (0, 8192, 2048)
    # Shed submissions BELOW a tier once that tier's rolling deadline-miss
    # fraction exceeds this (the SLO-burn signal).
    burn_shed: float = 0.5
    # Rolling window (completions) the per-tier burn fraction is computed
    # over.
    burn_window: int = 128
    # Alloc preemption for high-tier placements that find no feasible
    # capacity (qos/preemption.py).
    preemption: bool = True
    # Most allocs one placement may evict; bounds the blast radius of a
    # single high-tier instance.
    max_victims: int = 8

    def tier_of(self, priority: int) -> int:
        if priority >= self.high_floor:
            return TIER_HIGH
        if priority <= self.low_ceiling:
            return TIER_LOW
        return TIER_NORMAL

    def deadline_s(self, priority: int) -> float:
        return self.deadlines_s[self.tier_of(priority)]

    def window_fill(self, age_s: float, priority: int, max_fill: int,
                    default_fill: float) -> Tuple[int, float]:
        """Deadline-aware window sizing: scale how many more evals a
        window may take and how long it may linger for stragglers by the
        oldest queued eval's REMAINING tier budget. Returns
        ``(fill_count, fill_timeout_s)``.

        A window's oldest eval has already waited ``age_s``; every extra
        eval batched behind it adds dispatch+drain serialization before
        its ack. With the budget nearly spent the window dispatches small
        and immediately — trading batch efficiency for the tier's
        deadline, which is exactly the trade QoS exists to make."""
        deadline = self.deadlines_s[self.tier_of(priority)]
        remaining = deadline - age_s
        if remaining <= 0:
            # Budget blown: dispatch the smallest useful window, now.
            return max(1, max_fill // 8), 0.0
        frac = min(1.0, remaining / deadline)
        # ceil, not floor: a freshly-dequeued eval (age ~ms) must keep the
        # FULL window — flooring would report a 1-eval "cut" on every
        # healthy window and poison the window_cuts signal.
        count = max(1, math.ceil(max_fill * frac))
        return count, min(default_fill, remaining / 4.0)


class QoSCounters:
    """Cross-thread QoS flow counters (admission verdicts, preemption
    outcomes, window cuts), shared by the server's admission controller,
    the scheduler's preemption path, and the workers; read by the
    sched-stats endpoint and bench.py."""

    _concurrency = guarded_by("_lock", "_counts")

    FIELDS = ("admitted", "shed", "delayed",
              "preempt_attempts", "preempt_placed", "preempt_evictions",
              "window_cuts", "forward_shed")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {k: 0 for k in self.FIELDS}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


def qos_enabled(qos: Optional[QoSConfig]) -> bool:
    """The one guard every hot-path consumer uses: QoS logic only runs
    behind an explicit opt-in, so the disabled path stays bit-identical
    to the pre-QoS behavior."""
    return qos is not None and qos.enabled
