"""RaftNode: leader election, log replication, commit, snapshots
(reference behavior: hashicorp/raft as consumed by nomad/server.go:608-712 —
leader election feeding leaderCh, raftApply in nomad/rpc.go:262, snapshot
restore + peer membership changes in nomad/leader.go:421-459).

Threading model: one ticker thread (election timeouts + leader heartbeat
pacing), one replicator thread per peer (woken by appends, paced by the
heartbeat interval), one apply thread (feeds committed entries to the FSM and
resolves apply futures). All shared state behind a single RLock; FSM applies
run outside the lock.
"""

from __future__ import annotations

import json
import logging
import queue
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import msgpack

from nomad_tpu.analysis import guarded_by, requires_lock
from nomad_tpu.resilience import failpoints

from .log import EntryType, LogEntry
from .transport import TransportError

LOG = logging.getLogger("nomad_tpu.raft")

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"
SHUTDOWN = "shutdown"


class NotLeaderError(Exception):
    def __init__(self, leader_hint: Optional[str] = None):
        super().__init__(f"node is not the leader (leader={leader_hint})")
        self.leader_hint = leader_hint


class ApplyTimeout(Exception):
    pass


@dataclass
class RaftConfig:
    """(reference: raft.Config tightened the same way the reference's tests
    tighten it, nomad/server_test.go:46-52 — 50ms election in tests)"""
    heartbeat_interval: float = 0.05
    election_timeout_min: float = 0.15
    election_timeout_max: float = 0.30
    apply_timeout: float = 10.0
    snapshot_threshold: int = 8192   # entries applied since last snapshot
    trailing_logs: int = 128         # kept after compaction for catch-up
    max_append_entries: int = 64


@dataclass
class _Future:
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[Exception] = None


class RaftNode:
    _concurrency = guarded_by(
        "_lock", "_role", "_term", "_voted_for", "_leader_id", "_peers",
        "_commit_index", "_last_applied", "_snap_index", "_snap_term",
        "_applied_since_snap", "_next_index", "_match_index", "_futures",
        "_election_deadline", "_shutdown", "_electable", "_repl_conds",
        "_install_staging")

    def __init__(self, node_id: str, peers: List[str], log_store,
                 transport,
                 apply_fn: Callable[[int, int, bytes], Any],
                 snapshot_fn: Optional[Callable[[], bytes]] = None,
                 restore_fn: Optional[Callable[[bytes], None]] = None,
                 config: Optional[RaftConfig] = None,
                 on_leader_change: Optional[Callable[[bool], None]] = None,
                 electable: bool = True,
                 snapshot_stream_fn: Optional[Callable[[], Any]] = None,
                 restore_stream_fn: Optional[Callable[[Any], None]] = None,
                 digest_checkpoint_fn: Optional[
                     Callable[[], Optional[Tuple[int, str]]]] = None,
                 digest_verify_fn: Optional[
                     Callable[[int, str], bool]] = None,
                 digest_quarantine_fn: Optional[Callable[[], None]] = None):
        self.id = node_id
        self.config = config or RaftConfig()
        self.log = log_store
        self.transport = transport
        self.apply_fn = apply_fn            # (index, entry_type, data) -> Any
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        # Streaming snapshots (when both stream fns are provided): persist
        # runs chunk-by-chunk on a dedicated thread OFF the apply loop
        # (the capture is an O(1) MVCC pin under the locks; serialization
        # streams against the pinned watermark while later entries keep
        # applying), InstallSnapshot ships the same chunks as a sequence
        # of bounded RPCs, and restore loads chunk-by-chunk into staging
        # state with ONE atomic cutover — a stream torn at any chunk
        # boundary leaves the previous snapshot and the live FSM intact.
        self.snapshot_stream_fn = snapshot_stream_fn
        # restore_stream_fn takes an iterable of raw chunk blobs (bytes).
        self.restore_stream_fn = restore_stream_fn
        # Replica-digest exchange (analysis/replica_digest.py): the leader
        # piggybacks its newest digest checkpoint on AppendEntries; a
        # follower whose chain disagrees at the same applied index is
        # quarantined to snapshot-reinstall recovery. All three hooks are
        # optional — absent, replication is byte-identical to before.
        self.digest_checkpoint_fn = digest_checkpoint_fn
        self.digest_verify_fn = digest_verify_fn
        self.digest_quarantine_fn = digest_quarantine_fn
        self.on_leader_change = on_leader_change

        self._lock = threading.RLock()
        self._role = FOLLOWER
        self._term = int(self.log.get_stable("term", 0))
        self._voted_for = self.log.get_stable("voted_for")
        self._leader_id: Optional[str] = None
        self._peers: List[str] = list(peers)
        if node_id not in self._peers:
            self._peers.append(node_id)
        # Gossip-driven deployments boot dormant (no elections) until either
        # bootstrap_cluster() fires on bootstrap-expect or a replicated
        # Config entry admits us to an existing cluster (reference:
        # maybeBootstrap, nomad/serf.go:80-139 — servers without peers.json
        # wait for the expect quorum before their first election).
        self._electable = electable
        # A DURABLY STORED peer set overrides both: it is the cluster
        # configuration this node already belonged to before a restart
        # (the reference's peers.json in hashicorp/raft's stable store).
        # Without this, a restarted cluster is dead — every server's
        # bootstrap-expect probe sees an existing cluster (log > 0) and
        # defers forever, while nobody is electable.
        stored = self.log.get_stable("peers")
        if stored:
            try:
                if isinstance(stored, bytes):
                    stored = stored.decode()
                restored = [str(p) for p in json.loads(stored)]
            except (ValueError, TypeError, UnicodeDecodeError):
                LOG.warning("%s: stored peer set unreadable (%r); booting "
                            "dormant", node_id, stored)
                restored = []
            if restored:
                self._peers = restored
                if node_id in self._peers:
                    self._electable = True

        self._commit_index = 0
        self._last_applied = 0
        self._snap_index = 0
        self._snap_term = 0
        self._applied_since_snap = 0

        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self._futures: Dict[int, _Future] = {}

        self._election_deadline = 0.0
        # Event mirror of _shutdown for shutdown-aware sleeps: loops that
        # pace with a wait() must wake the instant shutdown() is called.
        self._stop_event = threading.Event()
        # Streaming persist coordination: the apply loop signals the snap
        # thread; take_snapshot() runs synchronously under the same mutex.
        self._snap_wake = threading.Event()
        self._snap_mutex = threading.Lock()
        # In-flight chunked InstallSnapshot streams, keyed by
        # (leader, index, term): ordered chunk buffers, discarded on any
        # out-of-order arrival (the leader restarts the stream).
        self._install_staging: Dict[Tuple[str, int, int], List[bytes]] = {}
        self._leader_events: "queue.Queue[Optional[bool]]" = queue.Queue()
        self._fsm_lock = threading.Lock()  # serializes apply_fn vs restore_fn
        self._apply_cond = threading.Condition(self._lock)
        self._repl_conds: Dict[str, threading.Condition] = {}
        self._threads: List[threading.Thread] = []
        self._shutdown = False

        self._restore_from_disk()
        self.transport.register(node_id, self._handle_rpc)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self._reset_election_timer()
        t = threading.Thread(target=self._ticker, daemon=True,
                             name=f"raft-tick-{self.id}")
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._apply_loop, daemon=True,
                             name=f"raft-apply-{self.id}")
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._notify_loop, daemon=True,
                             name=f"raft-notify-{self.id}")
        t.start()
        self._threads.append(t)
        if self.snapshot_stream_fn is not None:
            t = threading.Thread(target=self._snap_loop, daemon=True,
                                 name=f"raft-snap-{self.id}")
            t.start()
            self._threads.append(t)

    def _snap_loop(self) -> None:
        """Dedicated streaming-persist thread: the apply loop only SIGNALS
        when the threshold trips; the O(rows) serialization and disk write
        happen here, against a pinned MVCC watermark, while applies keep
        committing."""
        while True:
            self._snap_wake.wait()
            # clear() BEFORE the shutdown check: clearing after could wipe
            # a shutdown() wake that landed in between, parking this
            # thread forever and stalling shutdown at its join. A cleared
            # threshold wake costs nothing — the threshold is re-checked
            # inside _snapshot_stream_once anyway.
            self._snap_wake.clear()
            with self._lock:
                if self._shutdown:
                    return
            with self._snap_mutex:
                try:
                    self._snapshot_stream_once()
                except Exception:
                    LOG.exception("streaming snapshot persist failed")

    def _notify_loop(self) -> None:
        """Delivers leadership transitions serially, in order (reference:
        the leaderCh consumed by monitorLeadership, nomad/leader.go:24-56)."""
        while True:
            ev = self._leader_events.get()
            if ev is None:
                return
            if self.on_leader_change:
                try:
                    self.on_leader_change(ev)
                except Exception:
                    LOG.exception("leader-change callback failed")

    def shutdown(self) -> None:
        self._stop_event.set()
        with self._lock:
            self._shutdown = True
            was_leader = self._role == LEADER
            self._role = SHUTDOWN
            self._apply_cond.notify_all()
            for c in self._repl_conds.values():
                c.notify_all()
            for fut in self._futures.values():
                fut.error = NotLeaderError(None)
                fut.event.set()
            self._futures.clear()
        # After _shutdown is visible: the snap thread wakes, observes it,
        # and exits (set before would let it clear the event and re-park).
        self._snap_wake.set()
        self.transport.deregister(self.id)
        if was_leader:
            self._leader_events.put(False)
        self._leader_events.put(None)
        # Join our loops before returning: the apply loop drives the FSM,
        # whose state commits touch the tensor index (JAX device arrays) —
        # a daemon thread left mid-dispatch at interpreter exit aborts XLA
        # teardown. Skip the current thread: shutdown can be reached from
        # the notify loop's own leader-change callback.
        deadline = time.monotonic() + 30.0
        for t in self._threads:
            if t.is_alive() and t is not threading.current_thread():
                t.join(timeout=max(0.1, deadline - time.monotonic()))
        self._threads = []

    def _latest_snapshot_any(self) -> Optional[Tuple[str, int, int, Any]]:
        """Newest durable snapshot in either representation:
        ("chunks", index, term, [meta, chunk...]) or
        ("blob", index, term, blob)."""
        chunked = None
        getter = getattr(self.log, "latest_snapshot_chunks", None)
        if getter is not None:
            chunked = getter()
        blob = self.log.latest_snapshot()
        if chunked is not None and (blob is None or chunked[0] >= blob[0]):
            return ("chunks",) + chunked
        if blob is not None:
            return ("blob",) + blob
        return None

    @requires_lock("_lock")
    def _restore_from_disk(self) -> None:
        snap = self._latest_snapshot_any()
        if snap is not None:
            kind, index, term, payload = snap
            if kind == "chunks" and self.restore_stream_fn is None:
                # Refuse the snapshot rather than advance the indices
                # over a SKIPPED restore: the covered entries were
                # compacted away, so claiming applied-through-index with
                # an empty FSM would serve silently divergent state.
                LOG.error("%s: chunked snapshot on disk but no stream "
                          "restore configured; ignoring it and booting "
                          "from the retained log only", self.id)
                snap = None
        if snap is not None:
            kind, index, term, payload = snap
            if kind == "chunks":
                meta = msgpack.unpackb(payload[0], raw=False)
            else:
                meta = msgpack.unpackb(payload, raw=False)
            self._snap_index, self._snap_term = index, term
            self._commit_index = self._last_applied = index
            if meta.get("peers"):
                self._peers = list(meta["peers"])
                if self.id not in self._peers:
                    self._peers.append(self.id)
            if kind == "chunks":
                self.restore_stream_fn(iter(payload[1:]))
            elif self.restore_fn is not None:
                self.restore_fn(meta["data"])
        # Config entries in the retained log tail may supersede the snapshot.
        for e in self.log.get_range(self.log.first_index(),
                                    self.log.last_index()):
            if e.Type == EntryType.Config:
                self._set_peers_locked(msgpack.unpackb(e.Data, raw=False))

    # ----------------------------------------------------------- properties
    @property
    def role(self) -> str:
        with self._lock:
            return self._role

    @property
    def term(self) -> int:
        with self._lock:
            return self._term

    @property
    def leader_id(self) -> Optional[str]:
        with self._lock:
            return self._leader_id if self._role != LEADER else self.id

    def is_leader(self) -> bool:
        with self._lock:
            return self._role == LEADER

    @property
    def last_index(self) -> int:
        with self._lock:  # RLock: cheap re-entry from locked callers
            return max(self.log.last_index(), self._snap_index)

    @property
    def applied_index(self) -> int:
        with self._lock:
            return self._last_applied

    @property
    def commit_index(self) -> int:
        with self._lock:
            return self._commit_index

    def peers(self) -> List[str]:
        with self._lock:
            return list(self._peers)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._role, "term": self._term,
                "leader": self.leader_id, "commit_index": self._commit_index,
                "applied_index": self._last_applied,
                "last_log_index": self.last_index,
                "num_peers": len(self._peers),
                "snapshot_index": self._snap_index,
                # True once this node holds a real cluster configuration:
                # explicit peers at construction, bootstrap_cluster(), or
                # admission via a committed Config entry. Virgin gossip
                # servers are False — the bootstrap-expect probe keys off
                # this, NOT off the peer set (which always contains self).
                "configured": self._electable,
            }

    # -------------------------------------------------------------- helpers
    @requires_lock("_lock")
    def _last_log_info(self) -> Tuple[int, int]:
        last = self.log.last_index()
        if last == 0:
            return self._snap_index, self._snap_term
        e = self.log.get_entry(last)
        return last, e.Term if e else self._snap_term

    @requires_lock("_lock")
    def _term_at(self, index: int) -> Optional[int]:
        if index == 0:
            return 0
        if index == self._snap_index:
            return self._snap_term
        e = self.log.get_entry(index)
        return e.Term if e else None

    @requires_lock("_lock")
    def _reset_election_timer(self) -> None:
        spread = (self.config.election_timeout_max
                  - self.config.election_timeout_min)
        self._election_deadline = (time.monotonic()
                                   + self.config.election_timeout_min
                                   + random.random() * spread)

    @requires_lock("_lock")
    def _save_term_vote(self) -> None:
        self.log.set_stable("term", self._term)
        self.log.set_stable("voted_for", self._voted_for)

    @requires_lock("_lock")
    def _step_down(self, term: int, leader: Optional[str] = None) -> None:
        """Caller holds the lock."""
        was_leader = self._role == LEADER
        if term > self._term:
            self._term = term
            self._voted_for = None
            self._save_term_vote()
        self._role = FOLLOWER
        if leader is not None:
            self._leader_id = leader
        self._reset_election_timer()
        if was_leader:
            for fut in self._futures.values():
                fut.error = NotLeaderError(self._leader_id)
                fut.event.set()
            self._futures.clear()
            self._leader_events.put(False)

    def _save_peers_locked(self) -> None:
        """Persist the peer set so a restart rejoins its cluster instead of
        booting as a dormant virgin (reference: hashicorp/raft peers.json).
        Skips the disk write when unchanged — startup log replay walks
        every historical Config entry and each stable write is a full
        rewrite + fsync."""
        encoded = json.dumps(self._peers)
        if encoded == getattr(self, "_saved_peers", None):
            return
        try:
            self.log.set_stable("peers", encoded)
            self._saved_peers = encoded
        except Exception:
            LOG.exception("failed to persist peer set")

    def _set_peers_locked(self, peers: List[str]) -> None:
        self._peers = list(peers)
        self._save_peers_locked()
        if self.id in self._peers:
            # A committed Config entry naming us means a live cluster has
            # admitted us — we may now stand for election.
            self._electable = True
        if self.id not in self._peers and self._role == LEADER:
            # Removed ourselves: step down after the entry commits.
            pass
        for p in self._peers:
            if p != self.id and p not in self._next_index:
                self._next_index[p] = self.last_index + 1
                self._match_index[p] = 0
                if self._role == LEADER:
                    self._start_replicator(p)

    # ---------------------------------------------------------------- tick
    def _ticker(self) -> None:
        while True:
            with self._lock:
                if self._shutdown:
                    return
                role = self._role
                deadline = self._election_deadline
                electable = self._electable
            now = time.monotonic()
            if (role in (FOLLOWER, CANDIDATE) and now >= deadline
                    and electable):
                self._run_election()
            if self._stop_event.wait(0.01):  # shutdown-aware pacing
                return

    # ------------------------------------------------------------- election
    def _run_election(self) -> None:
        with self._lock:
            if self._shutdown or self._role == LEADER:
                return
            self._role = CANDIDATE
            self._term += 1
            self._voted_for = self.id
            self._save_term_vote()
            self._reset_election_timer()
            term = self._term
            last_idx, last_term = self._last_log_info()
            peers = [p for p in self._peers if p != self.id]
            votes_needed = len(self._peers) // 2 + 1
        LOG.debug("%s starting election term=%d", self.id, term)

        votes = [1]  # our own
        vote_lock = threading.Lock()
        done = threading.Event()

        def ask(peer: str):
            try:
                if failpoints.fire("raft.request_vote") == "drop":
                    raise TransportError(
                        f"vote request to {peer} dropped (failpoint)")
                resp = self.transport.send(peer, "raft.request_vote", {
                    "Term": term, "Candidate": self.id,
                    "LastLogIndex": last_idx, "LastLogTerm": last_term,
                })
            except (TransportError, failpoints.FailpointError):
                return
            with self._lock:
                if resp["Term"] > self._term:
                    self._step_down(resp["Term"])
                    done.set()
                    return
            if resp.get("Granted"):
                with vote_lock:
                    votes[0] += 1
                    if votes[0] >= votes_needed:
                        done.set()

        threads = [threading.Thread(target=ask, args=(p,), daemon=True,
                                    name=f"raft-vote-{self.id}-{p}")
                   for p in peers]
        for t in threads:
            t.start()
        if not peers:
            done.set()
        done.wait(timeout=self.config.election_timeout_min)
        with vote_lock:
            won = votes[0] >= votes_needed
        with self._lock:
            if won and self._role == CANDIDATE and self._term == term:
                self._become_leader()

    @requires_lock("_lock")
    def _become_leader(self) -> None:
        """Caller holds the lock."""
        LOG.info("%s became leader term=%d", self.id, self._term)
        self._role = LEADER
        self._leader_id = self.id
        last = self.last_index
        for p in self._peers:
            if p == self.id:
                continue
            self._next_index[p] = last + 1
            self._match_index[p] = 0
        # Barrier noop commits everything from prior terms (leader.go:60).
        self._append_locked(EntryType.Noop, b"")
        # Persist the peer set as a replicated Config entry so every
        # follower — including gossip-bootstrap stragglers whose own
        # bootstrap_cluster never fired — durably learns the membership
        # (the v0-raft peers.json role, here carried by the log itself).
        self._append_locked(EntryType.Config, msgpack.packb(
            list(self._peers), use_bin_type=True))
        for p in self._peers:
            if p != self.id:
                self._start_replicator(p)
        self._leader_events.put(True)

    # ---------------------------------------------------------- replication
    @requires_lock("_lock")
    def _start_replicator(self, peer: str) -> None:
        cond = self._repl_conds.get(peer)
        if cond is None:
            cond = threading.Condition(self._lock)
            self._repl_conds[peer] = cond
        t = threading.Thread(target=self._replicate_loop, args=(peer,),
                             daemon=True, name=f"raft-repl-{self.id}-{peer}")
        t.start()
        self._threads.append(t)

    def _replicate_loop(self, peer: str) -> None:
        with self._lock:
            cond = self._repl_conds[peer]
        term_started = self.term
        while True:
            with self._lock:
                if (self._shutdown or self._role != LEADER
                        or self._term != term_started
                        or peer not in self._peers):
                    return
            try:
                self._replicate_once(peer)
            except (TransportError, failpoints.FailpointError):
                pass
            except Exception:
                # A replicator thread must never die permanently; log and
                # retry on the next pacing tick.
                LOG.exception("replication to %s failed", peer)
            with self._lock:
                if self._shutdown or self._role != LEADER:
                    return
                behind = self._next_index.get(peer, 1) <= self.last_index
                if not behind:
                    cond.wait(timeout=self.config.heartbeat_interval)

    def _replicate_once(self, peer: str) -> None:
        with self._lock:
            if self._role != LEADER:
                return
            term = self._term
            next_idx = self._next_index.get(peer, self.last_index + 1)
            first = self.log.first_index()
            need_snapshot = (self._snap_index > 0 and next_idx <= self._snap_index
                             and (first == 0 or next_idx < first))
            if need_snapshot:
                snap = self._latest_snapshot_any()
                if snap is None:
                    # Log compacted past next_idx but no snapshot on disk yet
                    # (store_snapshot in flight): retry on the next tick.
                    return
            else:
                prev_idx = next_idx - 1
                prev_term = self._term_at(prev_idx)
                if prev_term is None:
                    snap = self._latest_snapshot_any()
                    if snap is None:
                        return
                    need_snapshot = True
                else:
                    hi = min(self.log.last_index(),
                             next_idx + self.config.max_append_entries - 1)
                    entries = self.log.get_range(next_idx, hi)
                    commit = self._commit_index

        if need_snapshot and snap is not None:
            self._send_snapshot(peer, term, snap)
            return

        payload = {
            "Term": term, "Leader": self.id,
            "PrevLogIndex": prev_idx, "PrevLogTerm": prev_term,
            "Entries": [(e.Index, e.Term, e.Type, e.Data) for e in entries],
            "LeaderCommit": commit,
        }
        if self.digest_checkpoint_fn is not None:
            # Piggyback the newest digest checkpoint (outside _lock — the
            # digest has its own lock). Followers that have folded the
            # same index compare; everyone else ignores it.
            cp = self.digest_checkpoint_fn()
            if cp is not None:
                payload["VerifyIndex"], payload["VerifyDigest"] = cp
        if failpoints.fire("raft.append_entries") == "drop":
            raise TransportError(
                f"append_entries to {peer} dropped (failpoint)")
        resp = self.transport.send(peer, "raft.append_entries", payload)
        with self._lock:
            if resp["Term"] > self._term:
                self._step_down(resp["Term"])
                return
            if self._role != LEADER or self._term != term:
                return
            if resp.get("Success"):
                if entries:
                    self._match_index[peer] = entries[-1].Index
                    self._next_index[peer] = entries[-1].Index + 1
                else:
                    self._match_index[peer] = max(
                        self._match_index.get(peer, 0), prev_idx)
                self._leader_advance_commit()
            else:
                hint = resp.get("LastIndex")
                if hint is not None:
                    self._next_index[peer] = max(1, min(next_idx - 1, hint + 1))
                else:
                    self._next_index[peer] = max(1, next_idx - 1)

    def _send_snapshot(self, peer: str, term: int,
                       snap: Tuple[str, int, int, Any]) -> None:
        """Ship one snapshot to a lagging peer. Chunked snapshots stream
        as a SEQUENCE of bounded InstallSnapshot RPCs (seq-numbered; the
        follower stages them and installs atomically on the last chunk) —
        a 1M-row store never rides one RPC. The `raft.install_snapshot`
        failpoint sits on every chunk hop: drop = a lost chunk (the
        follower's stream goes stale and the next round restarts it)."""
        kind, s_index, s_term, payload = snap
        if kind == "blob":
            resp = self.transport.send(peer, "raft.install_snapshot", {
                "Term": term, "Leader": self.id,
                "LastIndex": s_index, "LastTerm": s_term, "Data": payload,
            })
            with self._lock:
                if resp["Term"] > self._term:
                    self._step_down(resp["Term"])
                    return
                self._next_index[peer] = s_index + 1
                self._match_index[peer] = s_index
            return
        chunks = payload
        total = len(chunks)
        for seq, chunk in enumerate(chunks):
            if failpoints.fire("raft.install_snapshot") == "drop":
                raise TransportError(
                    f"install_snapshot chunk {seq}/{total} to {peer} "
                    "dropped (failpoint)")
            resp = self.transport.send(peer, "raft.install_snapshot", {
                "Term": term, "Leader": self.id,
                "LastIndex": s_index, "LastTerm": s_term,
                "Seq": seq, "Total": total, "Chunk": chunk,
            })
            with self._lock:
                if resp["Term"] > self._term:
                    self._step_down(resp["Term"])
                    return
                if self._role != LEADER or self._term != term:
                    return
            if resp.get("Reject"):
                # Follower lost the stream (restart, reordering): give up
                # this round; the replicator retries from chunk 0.
                return
        with self._lock:
            if self._role == LEADER and self._term == term:
                self._next_index[peer] = s_index + 1
                self._match_index[peer] = s_index

    @requires_lock("_lock")
    def _leader_advance_commit(self) -> None:
        """Caller holds the lock. Advance commit to the majority match index,
        but only over entries from the current term (Raft §5.4.2)."""
        matches = sorted(
            [self.last_index]
            + [self._match_index.get(p, 0) for p in self._peers
               if p != self.id])
        majority_idx = matches[(len(matches) - 1) // 2]
        if majority_idx <= self._commit_index:
            return
        t = self._term_at(majority_idx)
        if t == self._term:
            self._commit_index = majority_idx
            self._apply_cond.notify_all()

    # -------------------------------------------------------------- appends
    def _append_locked(self, etype: int, data: bytes) -> int:
        index = self.last_index + 1
        entry = LogEntry(Index=index, Term=self._term, Type=etype, Data=data)
        self.log.store_entries([entry])
        if etype == EntryType.Config:
            self._set_peers_locked(msgpack.unpackb(data, raw=False))
        for cond in self._repl_conds.values():
            cond.notify_all()
        self._leader_advance_commit()
        return index

    def apply_command(self, data: bytes,
                      timeout: Optional[float] = None) -> Tuple[int, Any]:
        """Replicate one command; block until it is applied to the local FSM.
        Returns (index, fsm_result). Raises NotLeaderError on non-leaders
        (reference: Server.raftApply, nomad/rpc.go:262-276). A traced
        caller sees the full consensus wait as a raft.apply child span."""
        from nomad_tpu.telemetry import trace

        with trace.span("raft.apply", bytes=len(data)):
            fut = _Future()
            with self._lock:
                if self._role != LEADER:
                    raise NotLeaderError(self._leader_id)
                index = self._append_locked(EntryType.Command, data)
                self._futures[index] = fut
            self._wait_applied(index, fut, timeout, "apply")
            return index, fut.result

    def _wait_applied(self, index: int, fut: _Future,
                      timeout: Optional[float], what: str) -> None:
        """Block until the entry at `index` is applied (future resolved);
        drop the future on timeout so it cannot leak."""
        if not fut.event.wait(timeout or self.config.apply_timeout):
            with self._lock:
                self._futures.pop(index, None)
            raise ApplyTimeout(f"{what} at index {index} timed out")
        if fut.error is not None:
            raise fut.error

    def barrier(self, timeout: Optional[float] = None) -> int:
        """Append + commit a noop; returns its index once applied
        (reference: raft.Barrier in nomad/leader.go:60)."""
        fut = _Future()
        with self._lock:
            if self._role != LEADER:
                raise NotLeaderError(self._leader_id)
            index = self._append_locked(EntryType.Noop, b"")
            self._futures[index] = fut
        self._wait_applied(index, fut, timeout, "barrier")
        return index

    # ----------------------------------------------------------- membership
    def add_peer(self, peer_id: str, timeout: Optional[float] = None) -> None:
        """Single-server membership change (reference: raft.AddPeer driven by
        Serf reconciliation, nomad/leader.go:421-447)."""
        self._config_change(
            lambda peers: peers + [peer_id] if peer_id not in peers else None,
            timeout)

    def remove_peer(self, peer_id: str,
                    timeout: Optional[float] = None) -> None:
        """(reference: raft.RemovePeer, nomad/leader.go:449-459)"""
        self._config_change(
            lambda peers: [p for p in peers if p != peer_id]
            if peer_id in peers else None,
            timeout)

    def bootstrap_cluster(self, peers: List[str]) -> bool:
        """One-time cluster formation from gossip discovery: set the initial
        peer set and become electable. Only legal on a virgin node (empty
        log, no snapshot) — an existing cluster manages membership through
        Config entries instead. Every expect-server calls this with the same
        discovered set; the usual election then picks one leader (reference:
        maybeBootstrap's SetPeers, nomad/serf.go:80-139)."""
        with self._lock:
            # Empty log + no snapshot + no configuration = virgin. The
            # peer set ALWAYS contains self (set at construction), so the
            # tests are "knows peers beyond itself" and "already electable"
            # — not peer-set truthiness. (A bumped term alone — e.g. we
            # granted a vote to an already-bootstrapped peer — does not
            # disqualify: the log/config decide whether a cluster exists.)
            if (self.last_index > 0 or self._snap_index > 0
                    or self._electable
                    or any(p != self.id for p in self._peers)):
                return False
            self._peers = list(peers)
            if self.id not in self._peers:
                self._peers.append(self.id)
            self._save_peers_locked()
            self._electable = True
            self._reset_election_timer()
            return True

    @property
    def electable(self) -> bool:
        with self._lock:
            return self._electable

    def _config_change(self, mutate: Callable[[List[str]],
                                              Optional[List[str]]],
                       timeout: Optional[float]) -> None:
        fut = _Future()
        with self._lock:
            # Leadership check, peer-base read, and append all happen in one
            # critical section: a stale base would let two concurrent
            # membership changes silently drop one, and a now-follower must
            # not write an entry the consistency check would never truncate.
            if self._role != LEADER:
                raise NotLeaderError(self._leader_id)
            peers = mutate(list(self._peers))
            if peers is None:  # already in the desired state
                return
            data = msgpack.packb(peers, use_bin_type=True)
            index = self._append_locked(EntryType.Config, data)
            self._futures[index] = fut
        self._wait_applied(index, fut, timeout, "config change")

    # ------------------------------------------------------------ RPC sides
    def _handle_rpc(self, method: str, payload: Dict[str, Any]
                    ) -> Dict[str, Any]:
        if method == "raft.request_vote":
            return self._on_request_vote(payload)
        if method == "raft.append_entries":
            return self._on_append_entries(payload)
        if method == "raft.install_snapshot":
            return self._on_install_snapshot(payload)
        raise ValueError(f"unknown raft rpc {method}")

    def _on_request_vote(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            if req["Term"] > self._term:
                self._step_down(req["Term"])
            granted = False
            if req["Term"] == self._term and self._role != LEADER:
                up_to_date = False
                last_idx, last_term = self._last_log_info()
                if (req["LastLogTerm"], req["LastLogIndex"]) >= (last_term,
                                                                 last_idx):
                    up_to_date = True
                if up_to_date and self._voted_for in (None, req["Candidate"]):
                    granted = True
                    self._voted_for = req["Candidate"]
                    self._save_term_vote()
                    self._reset_election_timer()
            return {"Term": self._term, "Granted": granted}

    def _on_append_entries(self, req: Dict[str, Any]) -> Dict[str, Any]:
        resp = self._append_entries_locked(req)
        if (resp.get("Success") and self.digest_verify_fn is not None
                and "VerifyIndex" in req):
            # Verify OUTSIDE self._lock: the digest takes its own lock,
            # and a divergence quarantine needs the full
            # _snap_mutex -> _fsm_lock -> _lock order — taking either
            # while holding _lock would invert the apply loop's order.
            ok = self.digest_verify_fn(int(req["VerifyIndex"]),
                                       req["VerifyDigest"])
            if not ok:
                self._quarantine_divergence(int(req["VerifyIndex"]))
                with self._lock:
                    return {"Term": self._term, "Success": False,
                            "LastIndex": 0, "Diverged": True}
        return resp

    def _append_entries_locked(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            if req["Term"] < self._term:
                return {"Term": self._term, "Success": False,
                        "LastIndex": self.last_index}
            if req["Term"] > self._term or self._role != FOLLOWER:
                self._step_down(req["Term"], leader=req["Leader"])
            self._leader_id = req["Leader"]
            self._reset_election_timer()

            prev_idx, prev_term = req["PrevLogIndex"], req["PrevLogTerm"]
            if prev_idx > 0:
                local_term = self._term_at(prev_idx)
                if local_term is None or local_term != prev_term:
                    return {"Term": self._term, "Success": False,
                            "LastIndex": min(self.last_index, prev_idx - 1)}

            entries = [LogEntry(Index=i, Term=t, Type=ty, Data=d)
                       for (i, t, ty, d) in req["Entries"]]
            to_store = []
            for e in entries:
                local = self.log.get_entry(e.Index)
                if local is not None and local.Term != e.Term:
                    # Conflict: truncate our suffix, drop stale futures.
                    self.log.delete_range(e.Index, self.log.last_index())
                    to_store.append(e)
                elif local is None and e.Index > self._snap_index:
                    to_store.append(e)
            if to_store:
                self.log.store_entries(to_store)
                for e in to_store:
                    if e.Type == EntryType.Config:
                        self._set_peers_locked(
                            msgpack.unpackb(e.Data, raw=False))
            if req["LeaderCommit"] > self._commit_index:
                self._commit_index = min(req["LeaderCommit"], self.last_index)
                self._apply_cond.notify_all()
            return {"Term": self._term, "Success": True,
                    "LastIndex": self.last_index}

    def _quarantine_divergence(self, index: int) -> None:
        """This replica's FSM digest disagrees with the leader's at
        `index`: its state is no longer a function of the log, so nothing
        derived from it can be trusted. Recovery = become a blank
        follower: wipe the local log and snapshot bookkeeping, reset the
        FSM to empty (atomic restore({}) cutover), and reset the digest
        chain to genesis. The leader's back-probe then either replays the
        full log (chain re-derives canonically from genesis) or streams
        an InstallSnapshot (chain reseeds from the snapshot's value) —
        both converge on verified state within one catch-up round."""
        LOG.error("%s: replica state digest DIVERGED at index %d; "
                  "quarantining to snapshot-reinstall recovery",
                  self.id, index)
        # Same order as every snapshot-install path:
        # _snap_mutex -> _fsm_lock -> _lock.
        with self._snap_mutex, self._fsm_lock:
            with self._lock:
                if self._shutdown:
                    return
                self.log.delete_range(self.log.first_index(),
                                      self.log.last_index())
                self._install_staging.clear()
                self._snap_index = 0
                self._snap_term = 0
                self._commit_index = 0
                self._last_applied = 0
                self._applied_since_snap = 0
                quarantine = self.digest_quarantine_fn
            # FSM wipe outside _lock (it takes the store's own locks)
            # but still under _fsm_lock, serialized against the apply
            # loop and any in-flight install.
            if quarantine is not None:
                quarantine()

    def _on_install_snapshot(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            if req["Term"] < self._term:
                return {"Term": self._term}
            if req["Term"] > self._term or self._role != FOLLOWER:
                self._step_down(req["Term"], leader=req["Leader"])
            self._leader_id = req["Leader"]
            self._reset_election_timer()
        if "Chunk" in req:
            return self._on_install_snapshot_chunk(req)
        # _snap_mutex first (same order as every streaming-persist
        # caller): a legacy blob install on a streaming-configured node
        # must not interleave with an in-flight chunked persist in the
        # shared snapshot tmp file, nor be republished-over by a lagging
        # older persist. Then _fsm_lock (same order as the apply loop)
        # so restore_fn can't interleave with an in-flight apply_fn.
        with self._snap_mutex, self._fsm_lock:
            with self._lock:
                index, term = req["LastIndex"], req["LastTerm"]
                if index <= self._last_applied:
                    return {"Term": self._term}
                blob = req["Data"]
                # Fire BEFORE any state mutation: an injected restore
                # failure must model a cleanly-rejected install (leader
                # re-sends later), not a half-applied one.
                if failpoints.fire("raft.snapshot.restore") == "drop":
                    raise failpoints.FailpointError("raft.snapshot.restore")
                self.log.store_snapshot(index, term, blob)
                self.log.delete_range(self.log.first_index(),
                                      self.log.last_index())
                meta = msgpack.unpackb(blob, raw=False)
                self._snap_index, self._snap_term = index, term
                # Never regress a commit index that is already ahead of the
                # snapshot (possible when AppendEntries advanced it first).
                self._commit_index = max(self._commit_index, index)
                self._last_applied = index
                self._applied_since_snap = 0
                if meta.get("peers"):
                    self._set_peers_locked(meta["peers"])
                restore = self.restore_fn
            if restore is not None:
                restore(meta["data"])
        return {"Term": self.term}

    def _on_install_snapshot_chunk(self, req: Dict[str, Any]
                                   ) -> Dict[str, Any]:
        """One hop of a streamed InstallSnapshot. Chunks stage in order;
        anything out of order rejects the stream (the leader restarts it
        from chunk 0). Only the FINAL chunk installs — and the install
        itself is atomic: the FSM restore loads staging tables and cuts
        over in one swap, so a stream torn at ANY chunk boundary leaves
        the follower's state and prior snapshot untouched."""
        key = (req["Leader"], req["LastIndex"], req["LastTerm"])
        seq, total = int(req["Seq"]), int(req["Total"])
        chunk = req["Chunk"]
        with self._lock:
            if req["LastIndex"] <= self._last_applied:
                # Already covered locally; ack so the leader advances.
                self._install_staging.pop(key, None)
                return {"Term": self._term}
            if seq == 0:
                # A new stream supersedes every staged one: only one
                # leader can be streaming at a time, so any other key is
                # an abandoned stream (leader died mid-install) that
                # would otherwise leak its chunks forever.
                self._install_staging.clear()
                self._install_staging[key] = [chunk]
            else:
                buf = self._install_staging.get(key)
                if buf is None or len(buf) != seq:
                    self._install_staging.pop(key, None)
                    return {"Term": self._term, "Reject": True}
                buf.append(chunk)
            if seq != total - 1:
                return {"Term": self._term}
            chunks = self._install_staging.pop(key)
        try:
            self._finish_chunked_install(int(req["LastIndex"]),
                                         int(req["LastTerm"]), chunks)
        except Exception:
            # Torn install (injected restore fault, bad chunk): prior
            # state intact by construction; reject so the leader retries.
            LOG.exception("%s: chunked snapshot install failed", self.id)
            return {"Term": self.term, "Reject": True}
        return {"Term": self.term}

    def _finish_chunked_install(self, index: int, term: int,
                                chunks: List[bytes]) -> None:
        from nomad_tpu.telemetry import metrics

        t0 = time.monotonic()
        # _snap_mutex FIRST (the order every streaming-persist caller
        # uses: _snap_mutex -> _fsm_lock -> _lock): an install running
        # concurrently with the persist thread could otherwise interleave
        # writes in the shared snapshot tmp file, have the persist's
        # lagging publish overwrite this NEWER snapshot after the log was
        # wiped, or have our Restore table swap invalidate the persist's
        # pinned MVCC view mid-encode.
        with self._snap_mutex, self._fsm_lock:
            with self._lock:
                if index <= self._last_applied:
                    return
                # Fire BEFORE any state mutation, like the monolithic
                # path: an injected failure models a cleanly-rejected
                # install, never a half-applied one.
                if failpoints.fire("raft.snapshot.restore") == "drop":
                    raise failpoints.FailpointError("raft.snapshot.restore")
                meta = msgpack.unpackb(chunks[0], raw=False)
                restore_stream = self.restore_stream_fn
            if restore_stream is None:
                # Refuse rather than wipe the log around a skipped FSM
                # restore (silent permanent divergence): the reject makes
                # the leader retry, and the operator sees why.
                raise RuntimeError(
                    "chunked snapshot received but no stream restore "
                    "configured")
            # 1) FSM cutover FIRST (atomic: staging tables swap in one
            #    commit). If this raises, nothing below ran — log, disk
            #    snapshot, and indices are all still the old world.
            restore_stream(iter(chunks[1:]))
            with self._lock:
                # 2) In-memory indices (pure memory, cannot fail): once
                #    the FSM holds the snapshot state, the apply loop
                #    must never re-apply retained entries <= index onto
                #    it, durable persist or not.
                self._snap_index, self._snap_term = index, term
                self._commit_index = max(self._commit_index, index)
                self._last_applied = index
                self._applied_since_snap = 0
                if meta.get("peers"):
                    self._set_peers_locked(meta["peers"])
                # 3) Durable snapshot + log wipe, best-effort: a failed
                #    persist (disk full) degrades like the persist
                #    failpoint — the log is kept, this process is fully
                #    consistent in memory, and a restart replays the old
                #    snapshot + whatever log it has (the leader re-sends
                #    the install for any gap).
                try:
                    self.log.store_snapshot_chunks(index, term, chunks)
                    self.log.delete_range(self.log.first_index(),
                                          self.log.last_index())
                except Exception:
                    LOG.exception(
                        "%s: chunked snapshot installed in memory but "
                        "durable persist failed; keeping the log",
                        self.id)
        metrics.measure_since(("nomad", "raft", "snapshot", "install_ms"),
                              t0)

    # ----------------------------------------------------------- apply loop
    def _apply_loop(self) -> None:
        while True:
            with self._lock:
                while (not self._shutdown
                       and self._last_applied >= self._commit_index):
                    self._apply_cond.wait(timeout=0.5)
                if self._shutdown:
                    return
                lo = self._last_applied + 1
                hi = self._commit_index
                entries = self.log.get_range(lo, hi)
                if not entries:
                    # commit_index can run ahead of the local log right after
                    # an InstallSnapshot wiped it; wait for replication to
                    # refill instead of busy-spinning.
                    self._apply_cond.wait(timeout=0.05)
                    continue
            for e in entries:
                # _fsm_lock serializes apply_fn with InstallSnapshot's
                # restore_fn; the index recheck discards batch entries a
                # concurrent snapshot restore already covers.
                with self._fsm_lock:
                    with self._lock:
                        stale = (self._shutdown
                                 or e.Index != self._last_applied + 1)
                    if stale:
                        break
                    result: Any = None
                    error: Optional[Exception] = None
                    if e.Type == EntryType.Command:
                        try:
                            result = self.apply_fn(e.Index, EntryType.Command,
                                                   e.Data)
                        except Exception as exc:  # surface to caller
                            error = exc
                            LOG.exception("fsm apply failed at %d", e.Index)
                    with self._lock:
                        self._last_applied = e.Index
                        self._applied_since_snap += 1
                        fut = self._futures.pop(e.Index, None)
                        if fut is not None:
                            fut.result = result
                            fut.error = error
                            fut.event.set()
                        if (e.Type == EntryType.Config
                                and self.id not in self._peers
                                and self._role == LEADER):
                            self._step_down(self._term)
            self._maybe_snapshot()

    # ------------------------------------------------------------ snapshots
    def _maybe_snapshot(self) -> None:
        with self._lock:
            if ((self.snapshot_fn is None
                 and self.snapshot_stream_fn is None)
                    or self._applied_since_snap < self.config.snapshot_threshold):
                return
            if self.snapshot_stream_fn is not None:
                # Streaming mode: hand off to the dedicated persist
                # thread — the apply loop pays one event set, nothing
                # else. The thread re-checks the threshold itself.
                self._snap_wake.set()
                return
        # _fsm_lock first (same order as the apply loop / InstallSnapshot) so
        # the snapshot blob and its recorded index cannot tear across a
        # concurrent apply_fn/restore_fn — restore would otherwise re-apply
        # entries the blob already contains.
        with self._fsm_lock:
            with self._lock:
                if (self.snapshot_fn is None
                        or self._applied_since_snap
                        < self.config.snapshot_threshold):
                    return
                index = self._last_applied
                term = self._term_at(index) or self._term
                peers = list(self._peers)
                self._applied_since_snap = 0
            data = self.snapshot_fn()
        blob = msgpack.packb({"data": data, "peers": peers},
                             use_bin_type=True)
        with self._lock:
            if index <= self._snap_index:
                return
            try:
                # Failure seam: the durable write of the snapshot blob
                # (disk full, torn store, injected fault). Drop = the
                # write never happened.
                if failpoints.fire("raft.snapshot.persist") == "drop":
                    raise failpoints.FailpointError("raft.snapshot.persist")
                self.log.store_snapshot(index, term, blob)
            except Exception:
                # Graceful degradation: the FSM is intact and the log was
                # NOT truncated, so nothing is lost — re-arm the counter
                # and retry at the next apply instead of taking down the
                # apply loop that called us.
                self._applied_since_snap = self.config.snapshot_threshold
                LOG.exception("snapshot persist failed at index %d; "
                              "keeping the full log and retrying", index)
                return
            self._snap_index, self._snap_term = index, term
            keep_from = max(self.log.first_index(),
                            index - self.config.trailing_logs + 1)
            if keep_from > self.log.first_index():
                self.log.delete_range(self.log.first_index(), keep_from - 1)

    def _snapshot_stream_once(self) -> None:
        """One streaming snapshot: pin the FSM at its applied index (an
        O(1) MVCC watermark under the locks), then — with BOTH locks
        released, applies continuing — encode and persist chunk by chunk.
        The `raft.snapshot.chunk` failpoint sits on every chunk: any
        injected fault (or torn stream) aborts the persist with the
        previous snapshot fully intact, and the counter re-arms so the
        next apply retries."""
        from nomad_tpu.telemetry import metrics

        with self._fsm_lock:
            with self._lock:
                if (self.snapshot_stream_fn is None
                        or self._applied_since_snap
                        < self.config.snapshot_threshold):
                    return
                if self._last_applied <= self._snap_index:
                    self._applied_since_snap = 0
                    return
                index = self._last_applied
                term = self._term_at(index) or self._term
                peers = list(self._peers)
                self._applied_since_snap = 0
            # Still under _fsm_lock: the pin inside snapshot_stream_fn is
            # taken with no apply interleaving, so watermark == index.
            stream = self.snapshot_stream_fn()

        t0 = time.monotonic()
        n_chunks = [0]

        def encoded():
            yield msgpack.packb({"peers": peers}, use_bin_type=True)
            for chunk in stream:
                # drop = torn stream: the chunk never reaches the store,
                # and a snapshot missing a chunk must never install —
                # abort the whole persist (old snapshot kept).
                if failpoints.fire("raft.snapshot.chunk") == "drop":
                    raise failpoints.FailpointError(
                        "raft.snapshot.chunk",
                        "snapshot chunk dropped (torn stream)")
                n_chunks[0] += 1
                yield msgpack.packb(chunk, use_bin_type=True)

        with self._lock:
            if index <= self._snap_index:
                # A newer snapshot landed since the pin (a chunked
                # install — serialized by _snap_mutex, so never MID-
                # persist, but possibly between wake and pin): never
                # publish an older one over it.
                return
        try:
            # Same durable-write seam as the monolithic path: an injected
            # persist failure degrades gracefully (log kept, retry at the
            # next apply), whichever representation is being written.
            if failpoints.fire("raft.snapshot.persist") == "drop":
                raise failpoints.FailpointError("raft.snapshot.persist")
            self.log.store_snapshot_chunks(index, term, encoded())
        except Exception:
            with self._lock:
                self._applied_since_snap = self.config.snapshot_threshold
            LOG.exception("streaming snapshot persist failed at index %d; "
                          "keeping the full log and retrying", index)
            return
        metrics.incr_counter(("nomad", "raft", "snapshot", "chunks"),
                             n_chunks[0])
        metrics.measure_since(("nomad", "raft", "snapshot", "persist_ms"),
                              t0)
        with self._lock:
            if index <= self._snap_index:
                return
            self._snap_index, self._snap_term = index, term
            keep_from = max(self.log.first_index(),
                            index - self.config.trailing_logs + 1)
            if keep_from > self.log.first_index():
                self.log.delete_range(self.log.first_index(), keep_from - 1)

    def take_snapshot(self) -> int:
        """Force a snapshot now; returns its index (reference: the snapshot
        path exercised by fsm tests, nomad/fsm.go:430)."""
        with self._lock:
            self._applied_since_snap = self.config.snapshot_threshold
        if self.snapshot_stream_fn is not None:
            # Synchronous streaming persist, serialized against the snap
            # thread so two persists never interleave in the tmp file.
            with self._snap_mutex:
                self._snapshot_stream_once()
        else:
            self._maybe_snapshot()
        with self._lock:
            return self._snap_index
