"""Raft consensus: the consistency plane of the distributed backend
(reference: hashicorp/raft as wired in nomad/server.go:608-712 setupRaft,
nomad/fsm.go, nomad/raft_rpc.go).

The reference replicates every state mutation through a Raft log into the
FSM; leadership transitions drive the server's leader-singleton services
(reference: nomad/leader.go:24-170). This package is an original Raft
implementation with the same observable behavior: leader election with
randomized timeouts, log replication with consistency checks, commit
advancement over the majority match index, FSM snapshots with log
truncation, InstallSnapshot for lagging followers, and single-server
membership changes.

Layering:
  log.py       — LogEntry + LogStore (in-memory, file-backed, C++ mmap)
  transport.py — Transport protocol; in-memory loopback + TCP (via rpc plane)
  node.py      — RaftNode state machine (follower/candidate/leader)
  backend.py   — RaftBackend: the `raft.apply(msg_type, payload)` seam the
                 Server uses (drop-in for fsm.DevRaft)
"""

from .log import LogEntry, InMemLogStore, FileLogStore, EntryType
from .node import RaftNode, RaftConfig, NotLeaderError
from .transport import InMemTransport
from .backend import RaftBackend

__all__ = [
    "LogEntry", "InMemLogStore", "FileLogStore", "EntryType",
    "RaftNode", "RaftConfig", "NotLeaderError",
    "InMemTransport", "RaftBackend",
]
