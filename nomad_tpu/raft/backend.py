"""RaftBackend: adapts a RaftNode to the `raft.apply(msg_type, payload)`
seam the Server writes through (reference: Server.raftApply nomad/rpc.go:262
— msgpack-encode a typed message, feed it through raft, return the index).

Drop-in replacement for fsm.DevRaft: same apply()/last_index surface, plus
leadership notification and barrier/snapshot passthrough.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import msgpack

from nomad_tpu.structs import to_dict

from .log import EntryType, InMemLogStore
from .node import NotLeaderError, RaftConfig, RaftNode


class RaftBackend:
    """Owns a RaftNode wired to an FSM. The Server calls apply(); followers
    receive the same entries through replication and apply them to their own
    FSM/state store replica."""

    def __init__(self, node_id: str, fsm, peers: List[str],
                 transport, log_store=None,
                 config: Optional[RaftConfig] = None,
                 on_leader_change: Optional[Callable[[bool], None]] = None,
                 electable: bool = True):
        self.fsm = fsm
        self.node = RaftNode(
            node_id=node_id,
            peers=peers,
            log_store=log_store or InMemLogStore(),
            transport=transport,
            apply_fn=self._fsm_apply,
            snapshot_fn=self._fsm_snapshot,
            restore_fn=self._fsm_restore,
            # Streaming snapshots: chunked persist off the apply path,
            # chunked InstallSnapshot, chunk-by-chunk restore with one
            # atomic cutover (README "Failover & streaming snapshots").
            snapshot_stream_fn=self._fsm_snapshot_stream,
            restore_stream_fn=self._fsm_restore_stream,
            # Replica-digest exchange: checkpoint piggyback on
            # AppendEntries, follower verification, and the divergence
            # quarantine's FSM wipe. All no-ops while fsm.digest is None.
            digest_checkpoint_fn=self._digest_checkpoint,
            digest_verify_fn=self._digest_verify,
            digest_quarantine_fn=self._digest_quarantine,
            config=config,
            on_leader_change=on_leader_change,
            electable=electable,
        )

    def start(self) -> None:
        self.node.start()

    def shutdown(self) -> None:
        self.node.shutdown()

    # ------------------------------------------------------------- fsm glue
    def _fsm_apply(self, index: int, etype: int, data: bytes) -> Any:
        """(reference: nomadFSM.Apply dispatch by MessageType, fsm.go:99-144)"""
        from nomad_tpu.server.fsm import MessageType  # avoid import cycle
        msg_type, payload = msgpack.unpackb(data, raw=False)
        return self.fsm.apply(index, MessageType(msg_type), payload)

    def _fsm_snapshot(self) -> bytes:
        return msgpack.packb(self.fsm.snapshot(), use_bin_type=True)

    def _fsm_restore(self, blob: bytes) -> None:
        self.fsm.restore(msgpack.unpackb(blob, raw=False))

    def _fsm_snapshot_stream(self):
        """Chunk-dict generator, MVCC-pinned eagerly (the raft layer calls
        this under its FSM lock so the pin matches the captured index)."""
        return self.fsm.snapshot_chunks()

    def _fsm_restore_stream(self, raw_chunks) -> None:
        """raw_chunks: iterable of msgpack chunk blobs. Decoding stays
        lazy so the atomic-cutover guarantee covers decode faults too."""
        self.fsm.restore_chunks(
            msgpack.unpackb(c, raw=False) for c in raw_chunks)

    # ---------------------------------------------------------- digest glue
    def _digest_checkpoint(self):
        digest = getattr(self.fsm, "digest", None)
        return None if digest is None else digest.checkpoint()

    def _digest_verify(self, index: int, expected_hex: str) -> bool:
        digest = getattr(self.fsm, "digest", None)
        if digest is None:
            return True
        from nomad_tpu.analysis.replica_digest import ReplicaDivergenceError
        try:
            digest.verify(index, expected_hex)
            return True
        except ReplicaDivergenceError:
            return False

    def _digest_quarantine(self) -> None:
        """Divergence recovery: atomic cutover to an EMPTY store (the
        corrupt state must not survive in any read surface) and a digest
        chain back at genesis — the leader's catch-up re-derives both."""
        self.fsm.restore({})
        digest = getattr(self.fsm, "digest", None)
        if digest is not None:
            digest.reset()

    # ----------------------------------------------------------- apply seam
    def apply(self, msg_type, payload: Dict[str, Any]) -> int:
        """Replicate + apply one mutation; returns its raft index. Raises
        NotLeaderError on non-leaders so RPC endpoints can forward
        (reference: rpc.go:177-242 forward + structs.ErrNoLeader)."""
        data = msgpack.packb((int(msg_type), to_dict(payload)),
                             use_bin_type=True)
        index, result = self.node.apply_command(data)
        if isinstance(result, Exception):
            raise result
        return index

    @property
    def last_index(self) -> int:
        return self.node.last_index

    # ------------------------------------------------------------- exposure
    def is_leader(self) -> bool:
        return self.node.is_leader()

    @property
    def leader_id(self) -> Optional[str]:
        return self.node.leader_id

    def barrier(self, timeout: Optional[float] = None) -> int:
        return self.node.barrier(timeout)

    # ----------------------------------------------------- membership seam
    # (driven by the gossip plane, server/membership.py — the reference
    # equivalents are raft.AddPeer/RemovePeer/SetPeers from nomad/leader.go
    # reconcileMember and nomad/serf.go maybeBootstrap)
    def add_peer(self, peer_id: str, timeout: Optional[float] = None) -> None:
        self.node.add_peer(peer_id, timeout)

    def remove_peer(self, peer_id: str,
                    timeout: Optional[float] = None) -> None:
        self.node.remove_peer(peer_id, timeout)

    def bootstrap_cluster(self, peers: List[str]) -> bool:
        return self.node.bootstrap_cluster(peers)

    @property
    def peers(self) -> List[str]:
        return self.node.peers()

    def stats(self) -> Dict[str, Any]:
        return self.node.stats()
