"""Native raft log backend: ctypes over native/liblogstore.so.

Same "NTL2" CRC-framed segment format as FileLogStore (raft/log.py), same
directory layout (stable kv + snapshot side files stay Python — they are
tiny and rewritten whole). The native side owns the hot path: CRC-framed
group appends with one fdatasync per raft batch, mmap-scanned validated
replay, atomic compaction rewrite (reference role: raft-boltdb,
nomad/server.go:640-650 — a native store under a scripting control plane).

`make_log_store(directory)` picks the native backend when the library is
built (make -C native) and falls back to the pure-Python FileLogStore
otherwise; the shared format makes switching free in either direction.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
from typing import List, Optional

from .log import FileLogStore, LogEntry, _MAGIC

LOG = logging.getLogger("nomad.raft.log")

_U32 = struct.Struct("<I")
_LIB = None
_LIB_TRIED = False


def _lib_path() -> str:
    override = os.environ.get("NOMAD_TPU_LOGSTORE", "")
    if override == "python":
        return ""
    if override:
        return override
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "native", "bin", "liblogstore.so")


def load_liblogstore():
    """The loaded library, or None (not built / load failure)."""
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    path = _lib_path()
    if not path or not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        LOG.warning("liblogstore load failed (%s); using Python store", e)
        return None
    lib.lgs_open.restype = ctypes.c_void_p
    lib.lgs_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                             ctypes.c_int]
    lib.lgs_replay.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.lgs_replay.argtypes = [ctypes.c_void_p,
                               ctypes.POINTER(ctypes.c_long),
                               ctypes.c_char_p, ctypes.c_int]
    lib.lgs_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.lgs_append.restype = ctypes.c_int
    lib.lgs_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_long]
    lib.lgs_rewrite.restype = ctypes.c_int
    lib.lgs_rewrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_long]
    lib.lgs_close.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def _frames(entries: List[LogEntry]) -> bytes:
    """[u32 len][payload] concatenation — the native batch input."""
    buf = bytearray()
    for e in entries:
        rec = e.pack()
        buf += _U32.pack(len(rec)) + rec
    return bytes(buf)


class NativeLogStore(FileLogStore):
    """FileLogStore with the segment-file hot path moved into C++."""

    def __init__(self, directory: str, lib=None):
        self._lib = lib or load_liblogstore()
        if self._lib is None:
            raise RuntimeError("liblogstore.so not available")
        self._handle: Optional[ctypes.c_void_p] = None
        super().__init__(directory)
        # The native fd owns all segment writes; a Python append handle
        # would just pin the old inode across native rewrites.
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # ------------------------------------------------------------ internals
    def _open_native(self) -> None:
        err = ctypes.create_string_buffer(256)
        handle = self._lib.lgs_open(self._log_path.encode(), err, 256)
        if not handle:
            raise RuntimeError(
                f"liblogstore open failed: {err.value.decode()}")
        self._handle = ctypes.c_void_p(handle)

    def _replay(self) -> None:
        # Side files (stable kv, snapshot) and LEGACY headerless segments
        # stay on the Python path: upgrade once, then go native.
        if os.path.exists(self._log_path):
            with open(self._log_path, "rb") as fh:
                head = fh.read(4)
            if head and head != _MAGIC:
                super()._replay()  # sets _needs_upgrade
                return
        self._load_side_files()
        self._open_native()
        n = ctypes.c_long()
        err = ctypes.create_string_buffer(256)
        buf = self._lib.lgs_replay(self._handle, ctypes.byref(n), err, 256)
        if not buf:
            raise RuntimeError(
                f"liblogstore replay failed: {err.value.decode()}")
        try:
            raw = ctypes.string_at(buf, n.value)
        finally:
            self._lib.lgs_free(buf)
        entries = []
        off = 0
        while off + 4 <= len(raw):
            (length,) = _U32.unpack_from(raw, off)
            entries.append(LogEntry.unpack(raw[off + 4:off + 4 + length]))
            off += 4 + length
        # InMemLogStore grandparent applies the entries.
        super(FileLogStore, self).store_entries(entries)

    # ------------------------------------------------------------ overrides
    def _append_file(self, entries: List[LogEntry]) -> None:
        frames = _frames(entries)
        rc = self._lib.lgs_append(self._handle, frames, len(frames))
        if rc != 0:
            raise OSError(f"liblogstore append failed (rc={rc})")

    def _rewrite_file(self) -> None:
        if self._handle is None:
            # Constructor path for a legacy upgrade: do the Python rewrite
            # (writes v2 format), then open natively.
            super()._rewrite_file()
            self._fh.close()
            self._fh = None
            self._open_native()
            return
        with self._lock:
            entries = [self._entries[i] for i in sorted(self._entries)]
        frames = _frames(entries)
        rc = self._lib.lgs_rewrite(self._handle, frames, len(frames))
        if rc != 0:
            raise OSError(f"liblogstore rewrite failed (rc={rc})")

    def close(self) -> None:
        if self._handle is not None:
            self._lib.lgs_close(self._handle)
            self._handle = None
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def make_log_store(directory: str):
    """Native when built, Python otherwise — same on-disk format."""
    if load_liblogstore() is not None:
        try:
            return NativeLogStore(directory)
        except Exception:
            LOG.exception("native log store failed; using Python store")
    return FileLogStore(directory)
