"""Raft transport abstraction (reference: nomad/raft_rpc.go RaftLayer — a
byte-prefixed stream carved out of the shared RPC port, and the in-memory
transport used by DevMode, server.go:618-626).

Two implementations:
  InMemTransport — loopback registry for in-process multi-node tests, with
                   fault injection (partitions, drops) for failover suites.
  (TCP)          — provided by nomad_tpu.rpc: Raft messages ride the shared
                   multiplexed RPC port under a dedicated stream prefix.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Protocol


class TransportError(Exception):
    """Peer unreachable / partitioned / dropped."""


class Transport(Protocol):
    def send(self, target: str, method: str, payload: Dict[str, Any]
             ) -> Dict[str, Any]: ...
    def register(self, node_id: str,
                 handler: Callable[[str, Dict[str, Any]], Dict[str, Any]]
                 ) -> None: ...
    def deregister(self, node_id: str) -> None: ...


class InMemTransport:
    """Shared loopback registry. Construct one per test cluster and hand the
    same instance to every RaftNode (reference test shape:
    nomad/server_test.go:82-93 testJoin over loopback)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handlers: Dict[str, Callable] = {}
        self._partitions: Dict[str, set] = {}   # node -> set of blocked peers
        self._down: set = set()

    def register(self, node_id: str, handler) -> None:
        with self._lock:
            self._handlers[node_id] = handler

    def deregister(self, node_id: str) -> None:
        with self._lock:
            self._handlers.pop(node_id, None)

    # -------------------------------------------------------- fault control
    def partition(self, a: str, b: str) -> None:
        """Symmetric partition between a and b."""
        with self._lock:
            self._partitions.setdefault(a, set()).add(b)
            self._partitions.setdefault(b, set()).add(a)

    def heal(self, a: Optional[str] = None, b: Optional[str] = None) -> None:
        with self._lock:
            if a is None:
                self._partitions.clear()
                self._down.clear()
            elif b is None:
                self._partitions.pop(a, None)
                for s in self._partitions.values():
                    s.discard(a)
                self._down.discard(a)
            else:
                self._partitions.get(a, set()).discard(b)
                self._partitions.get(b, set()).discard(a)

    def take_down(self, node_id: str) -> None:
        with self._lock:
            self._down.add(node_id)

    def bring_up(self, node_id: str) -> None:
        with self._lock:
            self._down.discard(node_id)

    # -------------------------------------------------------------- sending
    def send(self, target: str, method: str, payload: Dict[str, Any],
             source: Optional[str] = None) -> Dict[str, Any]:
        with self._lock:
            handler = self._handlers.get(target)
            blocked = (target in self._down
                       or (source is not None and source in self._down)
                       or (source is not None
                           and target in self._partitions.get(source, ())))
        if handler is None or blocked:
            raise TransportError(f"peer {target} unreachable")
        return handler(method, payload)


class BoundTransport:
    """A per-node view of a shared transport that stamps the source id, so
    partitions affect both directions."""

    def __init__(self, inner: InMemTransport, node_id: str):
        self.inner = inner
        self.node_id = node_id

    def register(self, node_id: str, handler) -> None:
        self.inner.register(node_id, handler)

    def deregister(self, node_id: str) -> None:
        self.inner.deregister(node_id)

    def send(self, target: str, method: str, payload: Dict[str, Any]
             ) -> Dict[str, Any]:
        return self.inner.send(target, method, payload, source=self.node_id)
