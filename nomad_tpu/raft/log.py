"""Raft log + stable storage (reference: the raft-boltdb LogStore/StableStore
pair wired in nomad/server.go:640-663, and the two retained FSM snapshots,
server.go:50 snapshotsRetained).

Three backends behind one interface:
  InMemLogStore  — tests and dev mode
  FileLogStore   — append-only msgpack segment file + snapshot files
  (native)       — C++ mmap segment log, see nomad_tpu/native/loglib
"""

from __future__ import annotations

import enum
import os
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import msgpack


class EntryType(enum.IntEnum):
    Command = 0
    Noop = 1        # barrier entry appended on leadership (leader.go:60)
    Config = 2      # membership change (single-server-at-a-time)


@dataclass
class LogEntry:
    Index: int
    Term: int
    Type: int = EntryType.Command
    Data: bytes = b""

    def pack(self) -> bytes:
        return msgpack.packb((self.Index, self.Term, self.Type, self.Data),
                             use_bin_type=True)

    @staticmethod
    def unpack(raw: bytes) -> "LogEntry":
        i, t, ty, d = msgpack.unpackb(raw, raw=False)
        return LogEntry(Index=i, Term=t, Type=ty, Data=d)


class InMemLogStore:
    """Log + stable store kept in memory (reference: raft.NewInmemStore used
    by DevMode, nomad/server.go:612-616)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[int, LogEntry] = {}
        self._first = 0
        self._last = 0
        self._stable: Dict[str, Any] = {}
        self._snapshot: Optional[Tuple[int, int, bytes]] = None

    # ------------------------------------------------------------- log part
    def first_index(self) -> int:
        with self._lock:
            return self._first

    def last_index(self) -> int:
        with self._lock:
            return self._last

    def get_entry(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            return self._entries.get(index)

    def get_range(self, lo: int, hi: int) -> List[LogEntry]:
        """Entries with lo <= index <= hi, in order; missing ones skipped."""
        with self._lock:
            return [self._entries[i] for i in range(lo, hi + 1)
                    if i in self._entries]

    def store_entries(self, entries: List[LogEntry]) -> None:
        with self._lock:
            for e in entries:
                self._entries[e.Index] = e
                if self._first == 0 or e.Index < self._first:
                    self._first = e.Index
                if e.Index > self._last:
                    self._last = e.Index

    def delete_range(self, lo: int, hi: int) -> None:
        with self._lock:
            for i in range(lo, hi + 1):
                self._entries.pop(i, None)
            if lo <= self._first:
                self._first = hi + 1 if self._entries else 0
            if hi >= self._last:
                self._last = lo - 1 if self._entries else 0
            if not self._entries:
                self._first = self._last = 0

    # ---------------------------------------------------------- stable part
    def set_stable(self, key: str, value: Any) -> None:
        with self._lock:
            self._stable[key] = value

    def get_stable(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._stable.get(key, default)

    # -------------------------------------------------------- snapshot part
    def store_snapshot(self, index: int, term: int, data: bytes) -> None:
        with self._lock:
            self._snapshot = (index, term, data)

    def latest_snapshot(self) -> Optional[Tuple[int, int, bytes]]:
        with self._lock:
            return self._snapshot

    def close(self) -> None:
        pass


_FRAME = struct.Struct("<I")  # little-endian u32 length prefix


class FileLogStore(InMemLogStore):
    """Durable log store: an append-only length-prefixed msgpack segment file
    plus side files for stable kv and snapshots. The in-memory index is the
    read path; the file is the write-ahead durability path (reference role:
    raft-boltdb, nomad/server.go:640-650).

    Compaction happens at snapshot time: delete_range(prefix) rewrites the
    segment with only the retained suffix.
    """

    def __init__(self, directory: str):
        super().__init__()
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._log_path = os.path.join(directory, "raft.log")
        self._stable_path = os.path.join(directory, "stable.mp")
        self._snap_path = os.path.join(directory, "snapshot.mp")
        self._replay()
        self._fh = open(self._log_path, "ab")

    # ----------------------------------------------------------- durability
    def _replay(self) -> None:
        if os.path.exists(self._stable_path):
            with open(self._stable_path, "rb") as fh:
                self._stable = msgpack.unpackb(fh.read(), raw=False)
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as fh:
                idx, term, data = msgpack.unpackb(fh.read(), raw=False)
                self._snapshot = (idx, term, data)
        if not os.path.exists(self._log_path):
            return
        with open(self._log_path, "rb") as fh:
            raw = fh.read()
        off, n = 0, len(raw)
        entries = []
        while off + 4 <= n:
            (length,) = _FRAME.unpack_from(raw, off)
            if off + 4 + length > n:  # torn tail write: drop it
                break
            entries.append(LogEntry.unpack(raw[off + 4:off + 4 + length]))
            off += 4 + length
        super().store_entries(entries)

    def _append_file(self, entries: List[LogEntry]) -> None:
        buf = bytearray()
        for e in entries:
            rec = e.pack()
            buf += _FRAME.pack(len(rec)) + rec
        self._fh.write(bytes(buf))
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def _rewrite_file(self) -> None:
        tmp = self._log_path + ".tmp"
        with open(tmp, "wb") as fh:
            for i in sorted(self._entries):
                rec = self._entries[i].pack()
                fh.write(_FRAME.pack(len(rec)) + rec)
            fh.flush()
            os.fsync(fh.fileno())
        self._fh.close()
        os.replace(tmp, self._log_path)
        self._fh = open(self._log_path, "ab")

    # ------------------------------------------------------------ overrides
    def store_entries(self, entries: List[LogEntry]) -> None:
        super().store_entries(entries)
        self._append_file(entries)

    def delete_range(self, lo: int, hi: int) -> None:
        super().delete_range(lo, hi)
        self._rewrite_file()

    def set_stable(self, key: str, value: Any) -> None:
        super().set_stable(key, value)
        tmp = self._stable_path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(msgpack.packb(self._stable, use_bin_type=True))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._stable_path)

    def store_snapshot(self, index: int, term: int, data: bytes) -> None:
        super().store_snapshot(index, term, data)
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(msgpack.packb((index, term, data), use_bin_type=True))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._snap_path)

    def close(self) -> None:
        self._fh.close()
