"""Raft log + stable storage (reference: the raft-boltdb LogStore/StableStore
pair wired in nomad/server.go:640-663, and the two retained FSM snapshots,
server.go:50 snapshotsRetained).

Three backends behind one interface:
  InMemLogStore   — tests and dev mode
  FileLogStore    — CRC-framed append-only segment file + snapshot files
  NativeLogStore  — the same format with the hot path in C++
                    (native/logstore.cc via raft/native_log.py)
"""

from __future__ import annotations

import enum
import logging
import os
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import msgpack

from nomad_tpu.analysis import guarded_by
from nomad_tpu.resilience import failpoints

LOG = logging.getLogger("nomad.raft.log")

# Segment format v2: magic header, then [u32 len][u32 crc32(payload)]
# [payload] records. The CRC catches mid-file corruption (a torn or
# bit-flipped record truncates the log there instead of feeding garbage
# into raft replay); legacy headerless files are parsed without CRC and
# upgraded at the first rewrite.
_MAGIC = b"NTL2"
# Chunked snapshot file: magic, then the same CRC framing — record 0 is
# msgpack((index, term)), every later record one snapshot chunk. Written
# incrementally to a tmp file and published by one atomic os.replace, so
# the on-disk snapshot is either the complete old one or the complete
# new one; a CRC mismatch on load discards the file (raft falls back to
# full log replay).
_SNAP_MAGIC = b"NTS1"


class EntryType(enum.IntEnum):
    Command = 0
    Noop = 1        # barrier entry appended on leadership (leader.go:60)
    Config = 2      # membership change (single-server-at-a-time)


@dataclass
class LogEntry:
    Index: int
    Term: int
    Type: int = EntryType.Command
    Data: bytes = b""

    def pack(self) -> bytes:
        return msgpack.packb((self.Index, self.Term, self.Type, self.Data),
                             use_bin_type=True)

    @staticmethod
    def unpack(raw: bytes) -> "LogEntry":
        i, t, ty, d = msgpack.unpackb(raw, raw=False)
        return LogEntry(Index=i, Term=t, Type=ty, Data=d)


class InMemLogStore:
    """Log + stable store kept in memory (reference: raft.NewInmemStore used
    by DevMode, nomad/server.go:612-616)."""

    _concurrency = guarded_by("_lock", "_entries", "_first", "_last",
                              "_stable", "_snapshot", "_snapshot_chunks")

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[int, LogEntry] = {}
        self._first = 0
        self._last = 0
        self._stable: Dict[str, Any] = {}
        self._snapshot: Optional[Tuple[int, int, bytes]] = None
        # Chunked (streaming) snapshot: (index, term, [chunk bytes...]).
        # Exactly one of _snapshot/_snapshot_chunks is set — whichever
        # persist path ran last wins.
        self._snapshot_chunks: Optional[Tuple[int, int, List[bytes]]] = None

    # ------------------------------------------------------------- log part
    def first_index(self) -> int:
        with self._lock:
            return self._first

    def last_index(self) -> int:
        with self._lock:
            return self._last

    def get_entry(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            return self._entries.get(index)

    def get_range(self, lo: int, hi: int) -> List[LogEntry]:
        """Entries with lo <= index <= hi, in order; missing ones skipped."""
        with self._lock:
            return [self._entries[i] for i in range(lo, hi + 1)
                    if i in self._entries]

    def store_entries(self, entries: List[LogEntry]) -> None:
        with self._lock:
            for e in entries:
                self._entries[e.Index] = e
                if self._first == 0 or e.Index < self._first:
                    self._first = e.Index
                if e.Index > self._last:
                    self._last = e.Index

    def delete_range(self, lo: int, hi: int) -> None:
        with self._lock:
            for i in range(lo, hi + 1):
                self._entries.pop(i, None)
            if lo <= self._first:
                self._first = hi + 1 if self._entries else 0
            if hi >= self._last:
                self._last = lo - 1 if self._entries else 0
            if not self._entries:
                self._first = self._last = 0

    # ---------------------------------------------------------- stable part
    def set_stable(self, key: str, value: Any) -> None:
        with self._lock:
            self._stable[key] = value

    def get_stable(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._stable.get(key, default)

    # -------------------------------------------------------- snapshot part
    def store_snapshot(self, index: int, term: int, data: bytes) -> None:
        with self._lock:
            self._snapshot = (index, term, data)
            self._snapshot_chunks = None

    def latest_snapshot(self) -> Optional[Tuple[int, int, bytes]]:
        with self._lock:
            return self._snapshot

    def store_snapshot_chunks(self, index: int, term: int, chunks) -> None:
        """Consume a chunk iterator and install the snapshot ATOMICALLY on
        success. The iterator is drained BEFORE any state changes, so a
        torn stream (the producer raises mid-iteration — e.g. the
        `raft.snapshot.chunk` failpoint) leaves the previous snapshot
        fully intact."""
        staged = [bytes(c) for c in chunks]
        with self._lock:
            self._snapshot_chunks = (index, term, staged)
            self._snapshot = None

    def latest_snapshot_chunks(self) -> Optional[Tuple[int, int, List[bytes]]]:
        with self._lock:
            return self._snapshot_chunks

    def close(self) -> None:
        pass


_FRAME = struct.Struct("<I")  # little-endian u32 length prefix


class FileLogStore(InMemLogStore):
    """Durable log store: an append-only length-prefixed msgpack segment file
    plus side files for stable kv and snapshots. The in-memory index is the
    read path; the file is the write-ahead durability path (reference role:
    raft-boltdb, nomad/server.go:640-650).

    Compaction happens at snapshot time: delete_range(prefix) rewrites the
    segment with only the retained suffix.
    """

    def __init__(self, directory: str):
        super().__init__()
        self.dir = directory
        # Serializes stable-kv persists end-to-end (snapshot + tmp write +
        # replace). Distinct from _lock so the in-memory store stays
        # readable during the fsync.
        self._stable_io_lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        self._log_path = os.path.join(directory, "raft.log")
        self._stable_path = os.path.join(directory, "stable.mp")
        self._snap_path = os.path.join(directory, "snapshot.mp")
        self._needs_upgrade = False
        self._replay()
        if self._needs_upgrade or not os.path.exists(self._log_path):
            # New file or legacy format: (re)write with the v2 CRC header.
            self._fh = None
            self._rewrite_file()
        else:
            self._fh = open(self._log_path, "ab")

    # ----------------------------------------------------------- durability
    def _load_side_files(self) -> None:
        """Stable kv + snapshot side files — THE single loader, shared with
        the native backend so side-file handling can't drift."""
        if os.path.exists(self._stable_path):
            with open(self._stable_path, "rb") as fh:
                self._stable = msgpack.unpackb(fh.read(), raw=False)
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as fh:
                raw = fh.read()
            if raw.startswith(_SNAP_MAGIC):
                records = self._parse_snap_frames(raw)
                if records:
                    idx, term = msgpack.unpackb(records[0], raw=False)
                    self._snapshot_chunks = (idx, term, records[1:])
            else:  # legacy monolithic format
                idx, term, data = msgpack.unpackb(raw, raw=False)
                self._snapshot = (idx, term, data)

    @staticmethod
    def _parse_snap_frames(raw: bytes) -> List[bytes]:
        """CRC-checked records of a chunked snapshot file; [] on any
        corruption (the file was published atomically, so damage means
        bit rot — discard rather than restore garbage)."""
        records: List[bytes] = []
        off, n = len(_SNAP_MAGIC), len(raw)
        while off < n:
            if off + 8 > n:
                LOG.error("snapshot file: truncated frame header; "
                          "discarding snapshot")
                return []
            (length,) = _FRAME.unpack_from(raw, off)
            (crc,) = _FRAME.unpack_from(raw, off + 4)
            end = off + 8 + length
            if end > n:
                LOG.error("snapshot file: truncated record; discarding "
                          "snapshot")
                return []
            payload = raw[off + 8:end]
            if zlib.crc32(payload) != crc:
                LOG.error("snapshot file: CRC mismatch at offset %d; "
                          "discarding snapshot", off)
                return []
            records.append(payload)
            off = end
        return records

    def _replay(self) -> None:
        self._load_side_files()
        if not os.path.exists(self._log_path):
            return
        with open(self._log_path, "rb") as fh:
            raw = fh.read()
        entries = []
        if raw.startswith(_MAGIC):
            off, n = len(_MAGIC), len(raw)
            while off + 8 <= n:
                (length,) = _FRAME.unpack_from(raw, off)
                (crc,) = _FRAME.unpack_from(raw, off + 4)
                end = off + 8 + length
                if end > n:  # torn tail write: drop it
                    break
                payload = raw[off + 8:end]
                if zlib.crc32(payload) != crc:
                    LOG.error("raft log: CRC mismatch at offset %d; "
                              "truncating %d trailing bytes", off, n - off)
                    break
                entries.append(LogEntry.unpack(payload))
                off = end
            if off < n:
                # Drop the corrupt/torn tail ON DISK too, so appends don't
                # land after garbage.
                with open(self._log_path, "r+b") as fh:
                    fh.truncate(off)
        else:  # legacy headerless format (no CRC)
            off, n = 0, len(raw)
            while off + 4 <= n:
                (length,) = _FRAME.unpack_from(raw, off)
                if off + 4 + length > n:  # torn tail write: drop it
                    break
                entries.append(
                    LogEntry.unpack(raw[off + 4:off + 4 + length]))
                off += 4 + length
            self._needs_upgrade = True
        super().store_entries(entries)

    def _append_file(self, entries: List[LogEntry]) -> None:
        buf = bytearray()
        for e in entries:
            rec = e.pack()
            buf += _FRAME.pack(len(rec)) + _FRAME.pack(zlib.crc32(rec)) + rec
        self._fh.write(bytes(buf))
        self._fh.flush()
        # error = a failing disk (append raises up through store_entries);
        # drop = a lying disk: bytes buffered, durability skipped.
        if failpoints.fire("raft.fsync") == "drop":
            return
        os.fsync(self._fh.fileno())

    def _rewrite_file(self) -> None:
        # Snapshot under the lock: replication appends run concurrently
        # with snapshot-path compaction.
        with self._lock:
            entries = [self._entries[i] for i in sorted(self._entries)]
        tmp = self._log_path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(_MAGIC)
            for e in entries:
                rec = e.pack()
                fh.write(_FRAME.pack(len(rec))
                         + _FRAME.pack(zlib.crc32(rec)) + rec)
            fh.flush()
            os.fsync(fh.fileno())
        if self._fh is not None:
            self._fh.close()
        os.replace(tmp, self._log_path)
        self._fh = open(self._log_path, "ab")
        self._needs_upgrade = False

    # ------------------------------------------------------------ overrides
    def store_entries(self, entries: List[LogEntry]) -> None:
        super().store_entries(entries)
        self._append_file(entries)

    def delete_range(self, lo: int, hi: int) -> None:
        super().delete_range(lo, hi)
        self._rewrite_file()

    def set_stable(self, key: str, value: Any) -> None:
        super().set_stable(key, value)
        # One persist at a time: the snapshot is taken under _lock (packb
        # over the live dict racing a concurrent writer would raise or
        # write a torn kv file), and the tmp-write + replace run under the
        # io lock so two writers can't interleave in the shared tmp file.
        # Whoever snapshots last snapshots AFTER both in-memory updates,
        # so the final on-disk state contains every key.
        with self._stable_io_lock:
            with self._lock:
                blob = msgpack.packb(self._stable, use_bin_type=True)
            tmp = self._stable_path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self._stable_path)

    def store_snapshot(self, index: int, term: int, data: bytes) -> None:
        super().store_snapshot(index, term, data)
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(msgpack.packb((index, term, data), use_bin_type=True))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._snap_path)

    def store_snapshot_chunks(self, index: int, term: int, chunks) -> None:
        """Streaming persist: each chunk is framed and written to the tmp
        file AS IT ARRIVES, fsync'd once at the end, and published by one
        atomic os.replace. A producer that raises mid-stream (torn
        stream, injected chunk fault) leaves the tmp file unpublished and
        the previous snapshot — in memory and on disk — intact. The chunk
        LIST is retained in memory after publish (like the monolithic
        blob) so InstallSnapshot can stream it to lagging peers without
        re-reading the file; what streaming bounds is the ENCODE side —
        no single chunk scales with store size."""
        tmp = self._snap_path + ".tmp"
        staged: List[bytes] = []
        try:
            with open(tmp, "wb") as fh:
                fh.write(_SNAP_MAGIC)
                meta = msgpack.packb((index, term), use_bin_type=True)
                fh.write(_FRAME.pack(len(meta))
                         + _FRAME.pack(zlib.crc32(meta)) + meta)
                for chunk in chunks:
                    chunk = bytes(chunk)
                    fh.write(_FRAME.pack(len(chunk))
                             + _FRAME.pack(zlib.crc32(chunk)) + chunk)
                    staged.append(chunk)
                fh.flush()
                os.fsync(fh.fileno())
        except BaseException:
            try:
                os.remove(tmp)
            # lint: allow(swallow, best-effort tmp cleanup on a failed persist)
            except OSError:
                pass
            raise
        os.replace(tmp, self._snap_path)
        with self._lock:
            self._snapshot_chunks = (index, term, staged)
            self._snapshot = None

    def close(self) -> None:
        self._fh.close()
