"""HTTP API (reference: command/agent/http.go + *_endpoint.go).

Serves the /v1 API over a stdlib threading HTTP server: jobs, nodes,
allocations, evaluations, client fs/stats, agent, status, regions, system GC,
with blocking-query support (`index` + `wait` params) wired to state-store
watches and the same JSON envelope/headers as the reference (X-Nomad-Index,
error text bodies, 4xx/5xx codes).
"""

from __future__ import annotations

import json
import logging
import re
import sys
import threading
import traceback
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from nomad_tpu.qos import QoSBackpressureError
from nomad_tpu.rpc.pool import RPCError
from nomad_tpu.state.watch import Item
from nomad_tpu.structs import Job, from_dict, job_stub, to_dict

logger = logging.getLogger("nomad.http")

MAX_WAIT = 300.0  # blocking query cap (reference: rpc.go:33-43)


class CodedError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class HTTPServer:
    def __init__(self, agent, host: str = "127.0.0.1", port: int = 4646):
        self.agent = agent
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        handler = _make_handler(self.agent)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="http")
        self._thread.start()
        logger.info("http: listening on %s:%d", self.host, self.port)

    def shutdown(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


def _accepts_gzip(header: str) -> bool:
    """True when the Accept-Encoding header permits gzip — a bare substring
    match would treat the explicit refusal 'gzip;q=0' as acceptance."""
    for part in header.split(","):
        token, _, params = part.strip().partition(";")
        if token.strip().lower() not in ("gzip", "*"):
            continue
        q = 1.0
        for p in params.split(";"):
            k, _, v = p.strip().partition("=")
            if k.strip().lower() == "q":
                try:
                    q = float(v)
                except ValueError:
                    q = 0.0
        return q > 0
    return False


def _make_handler(agent):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through logging
            logger.debug("http: " + fmt, *args)

        def _respond(self, obj: Any, index: Optional[int] = None,
                     code: int = 200) -> None:
            if isinstance(obj, bytes):
                # Binary payloads (the cProfile-compatible profile blob):
                # no JSON wrapping, no gzip (already dense marshal data).
                self.send_response(code)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(obj)))
                self.end_headers()
                self.wfile.write(obj)
                return
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            # gzip for clients that accept it (reference: every handler is
            # gzip-wrapped, command/agent/http.go:70-80) — list responses
            # like /v1/allocations run to megabytes of JSON. Small bodies
            # skip it: the header+CPU overhead beats the saved bytes.
            if _accepts_gzip(self.headers.get("Accept-Encoding", "")) \
                    and len(body) >= 1024:
                import gzip as _gzip

                body = _gzip.compress(body, compresslevel=1)
                self.send_header("Content-Encoding", "gzip")
            self.send_header("Content-Length", str(len(body)))
            if index is not None:
                self.send_header("X-Nomad-Index", str(index))
                self.send_header("X-Nomad-KnownLeader", "true")
            self.end_headers()
            self.wfile.write(body)

        def _error(self, code: int, message: str) -> None:
            body = message.encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> Any:
            length = int(self.headers.get("Content-Length", 0))
            if length == 0:
                return None
            return json.loads(self.rfile.read(length))

        def _write_chunk(self, payload: bytes) -> None:
            # Manual chunked transfer-encoding: one frame per chunk so
            # consumers see complete JSON lines as they flush.
            self.wfile.write(b"%X\r\n" % len(payload) + payload + b"\r\n")
            self.wfile.flush()

        def _stream_events(self, query) -> None:
            """GET /v1/event/stream: chunked JSON-lines event frames
            (README "Event stream"). Each chunk is one frame —
            ``{"Index": N, "Events": [...]}`` — or a bare ``{}``
            heartbeat; the stream ends with a ``{"Closed": true,
            "Reason": ...}`` frame when the broker resets or shuts down.
            Streams are REGION-LOCAL: the ring is fed by this region's
            raft log, so a request naming another region is refused
            rather than forwarded (a forwarded stream could not honor
            the from_index resume contract across logs)."""
            from nomad_tpu.events import TOPICS, EventGapError

            if self.command != "GET":
                self._error(405, "method not allowed")
                return
            server = agent.server
            broker = server.fsm.events if server is not None else None
            if broker is None:
                self._error(501, "event streaming requires a server "
                                 "agent with events enabled "
                                 "(server.event_buffer_size > 0)")
                return
            q_region = query.get("region", [""])[0]
            if q_region and q_region != agent.region():
                self._error(400, f"event streams are region-local: this "
                                 f"agent serves region "
                                 f"{agent.region()!r}, not {q_region!r}")
                return
            topics: set = set()
            filters: Dict[str, set] = {}
            for spec in query.get("topic", []):
                topic, _, key = spec.partition(":")
                if topic not in TOPICS:
                    self._error(400, f"unknown topic {topic!r} "
                                     f"(known: {sorted(TOPICS)})")
                    return
                topics.add(topic)
                if key:
                    filters.setdefault(topic, set()).add(key)
            try:
                from_index = int(query.get("index", ["0"])[0])
            except ValueError:
                self._error(400, "index must be an integer")
                return
            fanout = ("fanout" in query
                      and query["fanout"][0] not in ("false", "0"))
            raw_hb = query.get("heartbeat", [""])[0]
            try:
                heartbeat = float(raw_hb) if raw_hb else 10.0
            except ValueError:
                self._error(400, f"heartbeat must be seconds, "
                                 f"got {raw_hb!r}")
                return
            if not (0.05 <= heartbeat <= 60.0):  # NaN-rejecting clamp
                heartbeat = 10.0
            try:
                sub = broker.subscribe(topics=topics or None,
                                       filters=filters,
                                       from_index=from_index,
                                       fanout=fanout)
            except EventGapError as e:
                # 416: the requested window is gone. JSON body so the
                # client can re-snapshot and resubscribe from Floor.
                self._respond({"Error": str(e), "Requested": e.requested,
                               "Floor": e.floor}, code=416)
                return
            # One long-lived response per connection: no keep-alive reuse
            # after a stream (the consumer reconnects to resume).
            self.close_connection = True
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header("X-Nomad-Region", agent.region())
            self.end_headers()
            try:
                while True:
                    frame = sub.next(timeout=heartbeat)
                    if frame is None:
                        closed, reason = sub.status()
                        if closed:
                            self._write_chunk(json.dumps(
                                {"Closed": True,
                                 "Reason": reason}).encode() + b"\n")
                            break
                        self._write_chunk(b"{}\n")  # heartbeat
                        continue
                    self._write_chunk(json.dumps(
                        frame, separators=(",", ":")).encode() + b"\n")
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass  # consumer went away; unsubscribe below
            finally:
                broker.unsubscribe(sub)

        def _dispatch(self) -> None:
            parsed = urllib.parse.urlparse(self.path)
            # keep_blank_values: bare flags like `?stale` must survive
            # parsing (parse_qs drops blank-valued params by default).
            query = urllib.parse.parse_qs(parsed.query,
                                          keep_blank_values=True)
            if parsed.path == "/v1/event/stream":
                # Streaming writes chunked frames directly to the socket;
                # it cannot go through route()/_respond (one
                # Content-Length'd body per response).
                self._stream_events(query)
                return
            try:
                result = route(agent, self.command, parsed.path, query,
                               self._body)
            except CodedError as e:
                self._error(e.code, str(e))
                return
            except QoSBackpressureError as e:
                # Admission shed: 429 so clients back off and retry
                # (api/client.py maps this to BackpressureAPIError and
                # re-sends with RetryPolicy — nothing was written).
                self._error(429, str(e))
                return
            except KeyError as e:
                self._error(404, str(e))
                return
            except RPCError as e:
                # A shed raised on a REMOTE server (client-only agent /
                # leader forward) arrives as an RPCError carrying the
                # exception class name; keep the 429 contract.
                if e.remote_type == "QoSBackpressureError":
                    self._error(429, str(e))
                    return
                logger.exception("http: request failed")
                self._error(500, str(e))
                return
            except ValueError as e:
                self._error(400, str(e))
                return
            except Exception as e:
                logger.exception("http: request failed")
                self._error(500, str(e))
                return
            if result is None:
                self._respond(None)
            else:
                obj, index = result
                self._respond(obj, index)

        do_GET = _dispatch
        do_PUT = _dispatch
        do_POST = _dispatch
        do_DELETE = _dispatch

    return Handler


# ---------------------------------------------------------------- routing


def _capture_profile(seconds: float, period: float = 0.005) -> bytes:
    """Sample every live thread's Python stack for `seconds` and return a
    pstats-compatible marshal blob (the format cProfile dumps and
    pstats.Stats loads). Per function: ct approximates wall time anywhere
    on a stack, tt time at the top of one; call counts are sample counts.
    Sampling (vs tracing) is the only approach that can observe every
    server thread without instrumenting them — the same trade the
    reference's pprof CPU profile makes."""
    import marshal

    # {(file, line, name): [cc, nc, tt, ct, {caller: ...}]}
    stats: Dict[tuple, list] = {}
    deadline = time.monotonic() + seconds
    me = threading.get_ident()
    n_samples = 0
    last = time.monotonic()
    while True:
        now = time.monotonic()
        # Credit the MEASURED inter-sample gap, not the nominal period:
        # under GIL contention or deep stacks the real gap stretches well
        # past the sleep, and a fixed credit would undercount wall time.
        dt = now - last
        last = now
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            top = True
            seen = set()
            while frame is not None:
                code = frame.f_code
                key = (code.co_filename, code.co_firstlineno, code.co_name)
                ent = stats.get(key)
                if ent is None:
                    ent = stats[key] = [0, 0, 0.0, 0.0, {}]
                ent[0] += 1
                ent[1] += 1
                if top:
                    ent[2] += dt
                    top = False
                if key not in seen:  # recursion: count wall time once
                    ent[3] += dt
                    seen.add(key)
                frame = frame.f_back
        n_samples += 1
        if now >= deadline:
            break
        # lint: allow(retry, fixed-cadence sampling profiler, not a retry)
        time.sleep(period)
    stats[("~", 0, f"<sampling-profile {n_samples} samples "
           f"@{period * 1e3:g}ms>")] = [n_samples, n_samples, 0.0, 0.0, {}]
    return marshal.dumps({k: tuple(v[:4]) + (v[4],)
                          for k, v in stats.items()})


def _parse_wait(query) -> Tuple[int, float]:
    from nomad_tpu.jobspec import parse_duration

    min_index = int(query.get("index", ["0"])[0])
    wait_raw = query.get("wait", ["0"])[0]
    try:
        wait = float(wait_raw or 0)  # bare number: seconds
    except ValueError:
        wait = parse_duration(wait_raw) / 1e9  # Go duration string
    return min_index, min(wait, MAX_WAIT)


def _blocking(state, items: List[Item], query, run: Callable[[], Tuple[Any, int]]
              ) -> Tuple[Any, int]:
    """Blocking-query wrapper (reference: rpc.go:294-349 blockingRPC)."""
    min_index, wait = _parse_wait(query)
    if min_index <= 0 or wait <= 0:
        return run()
    event = threading.Event()
    state.watch(items, event)
    try:
        deadline = time.monotonic() + wait
        while True:
            obj, index = run()
            if index > min_index:
                return obj, index
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return obj, index
            event.clear()
            event.wait(remaining)
    finally:
        state.stop_watch(items, event)


def _require_write(method: str) -> None:
    if method not in ("PUT", "POST"):
        raise CodedError(405, "method not allowed")


def route(agent, method: str, path: str, query, get_body):
    server = agent.server
    client = agent.client
    state = server.state if server is not None else None
    # A request naming another region, hitting a client-only agent, or
    # needing CONSISTENT reads on a follower is served over RPC (with
    # region/leader forwarding) instead of local state (reference: every
    # HTTP handler goes through agent.RPC and server.forward; `?stale`
    # opts into the local-replica fast path, command/agent/http.go
    # parseConsistency + nomad/rpc.go:177-221). Without the forward, a
    # read right after a write could miss it on a follower that hasn't
    # replicated yet.
    q_region = query.get("region", [""])[0]
    stale_ok = "stale" in query and query["stale"][0] not in ("false", "0")
    remote = (server is None
              or (bool(q_region) and q_region != agent.region())
              or (not stale_ok and not server.is_leader()))

    def rpc(method_name: str, body: dict):
        if q_region:
            body = dict(body, Region=q_region)
        return agent.rpc(method_name, body)

    def rpc_read(method_name: str, body: dict, key: str):
        """Forwarded read with RPC-level blocking-query params."""
        min_index, wait = _parse_wait(query)
        body = dict(body)
        if min_index:
            body["MinQueryIndex"] = min_index
            # Forward `wait` verbatim: index-without-wait returns
            # immediately on the local path and must do the same when the
            # read happens to route through a follower.
            body["MaxQueryTime"] = wait
        if stale_ok:
            body["AllowStale"] = True
        resp = rpc(method_name, body)
        return resp.get(key), resp.get("Index", 0)

    def need_server():
        if server is None:
            raise CodedError(501, "no server running on this agent")
        return server

    def need_client():
        if client is None:
            raise CodedError(501, "no client running on this agent")
        return client

    # ------------------------------ jobs
    if path == "/v1/jobs":
        if method == "GET":
            prefix = query.get("prefix", [""])[0]
            if remote:
                jobs, index = rpc_read("Job.List", {}, "Jobs")
                if prefix:
                    jobs = [j for j in jobs if j["ID"].startswith(prefix)]
                return sorted(jobs, key=lambda j: j["ID"]), index
            need_server()

            def run():
                jobs = state.jobs_by_id_prefix(prefix) if prefix else state.jobs()
                stubs = sorted((to_dict(job_stub(j)) for j in jobs),
                               key=lambda j: j["ID"])
                return stubs, state.get_index("jobs")

            return _blocking(state, [Item(table="jobs")], query, run)
        if method in ("PUT", "POST"):
            payload = get_body()
            enforce = payload.get("EnforceIndex")
            enforce_index = payload.get("JobModifyIndex") if enforce else None
            resp = rpc("Job.Register", {
                "Job": payload.get("Job"), "EnforceIndex": enforce_index})
            resp["EvalCreateIndex"] = resp["Index"]
            return resp, resp["Index"]
        raise CodedError(405, "method not allowed")

    m = re.match(r"^/v1/job/([^/]+)$", path)
    if m:
        job_id = urllib.parse.unquote(m.group(1))
        if method == "GET":
            if remote:
                job, index = rpc_read("Job.GetJob", {"JobID": job_id}, "Job")
                if job is None:
                    raise KeyError(f"job not found: {job_id}")
                return job, index
            need_server()

            def run():
                job = state.job_by_id(job_id)
                if job is None:
                    raise KeyError(f"job not found: {job_id}")
                return to_dict(job), state.get_index("jobs")

            return _blocking(state, [Item(job=job_id)], query, run)
        if method in ("PUT", "POST"):
            payload = get_body()
            resp = rpc("Job.Register", {"Job": payload.get("Job")})
            return resp, resp["Index"]
        if method == "DELETE":
            resp = rpc("Job.Deregister", {"JobID": job_id})
            return resp, resp["Index"]
        raise CodedError(405, "method not allowed")

    m = re.match(r"^/v1/job/([^/]+)/plan$", path)
    if m:
        _require_write(method)
        payload = get_body()
        job = from_dict(Job, payload.get("Job"))
        if job is None:
            raise CodedError(400, "Job must be specified")
        path_id = urllib.parse.unquote(m.group(1))
        if job.ID != path_id:
            raise CodedError(400, "Job ID does not match")
        want_diff = bool(payload.get("Diff"))
        resp = rpc("Job.Plan", {"Job": payload.get("Job"),
                                      "Diff": want_diff})
        return (resp, resp.get("JobModifyIndex", 0))

    m = re.match(r"^/v1/job/([^/]+)/allocations$", path)
    if m:
        job_id = urllib.parse.unquote(m.group(1))
        if remote:
            return rpc_read("Job.Allocations", {"JobID": job_id},
                            "Allocations")
        need_server()

        def run():
            allocs = [to_dict(a.stub()) for a in state.allocs_by_job(job_id)]
            return allocs, state.get_index("allocs")

        return _blocking(state, [Item(alloc_job=job_id)], query, run)

    m = re.match(r"^/v1/job/([^/]+)/evaluations$", path)
    if m:
        job_id = urllib.parse.unquote(m.group(1))
        if remote:
            return rpc_read("Job.Evaluations", {"JobID": job_id},
                            "Evaluations")
        need_server()

        def run():
            evals = [to_dict(e) for e in state.evals_by_job(job_id)]
            return evals, state.get_index("evals")

        return _blocking(state, [Item(table="evals")], query, run)

    m = re.match(r"^/v1/job/([^/]+)/evaluate$", path)
    if m:
        _require_write(method)
        resp = rpc("Job.Evaluate",
                         {"JobID": urllib.parse.unquote(m.group(1))})
        return (resp, resp["Index"])

    m = re.match(r"^/v1/job/([^/]+)/periodic/force$", path)
    if m:
        _require_write(method)
        rpc("Periodic.Force",
                  {"JobID": urllib.parse.unquote(m.group(1))})
        index = state.latest_index() if state is not None else 0
        return ({"Index": index}, index)

    # ------------------------------ nodes
    if path == "/v1/nodes":
        prefix = query.get("prefix", [""])[0]
        if remote:
            stubs, index = rpc_read("Node.List", {}, "Nodes")
            if prefix:
                stubs = [n for n in stubs if n["ID"].startswith(prefix)]
            return stubs, index
        need_server()


        def run():
            stubs = sorted((to_dict(n.stub()) for n in state.nodes()
                            if n.ID.startswith(prefix)),
                           key=lambda n: n["ID"])
            return stubs, state.get_index("nodes")

        return _blocking(state, [Item(table="nodes")], query, run)

    m = re.match(r"^/v1/node/([^/]+)$", path)
    if m:
        node_id = urllib.parse.unquote(m.group(1))
        if method == "GET" and remote:
            node, index = rpc_read("Node.GetNode", {"NodeID": node_id},
                                   "Node")
            if node is None:
                raise KeyError(f"node not found: {node_id}")
            return node, index
        need_server()

        def run():
            node = state.node_by_id(node_id)
            if node is None:
                raise KeyError(f"node not found: {node_id}")
            return to_dict(node), state.get_index("nodes")

        return _blocking(state, [Item(node=node_id)], query, run)

    m = re.match(r"^/v1/node/([^/]+)/allocations$", path)
    if m:
        node_id = urllib.parse.unquote(m.group(1))
        if remote:
            return rpc_read("Node.GetAllocs", {"NodeID": node_id}, "Allocs")
        need_server()

        def run():
            allocs = [to_dict(a) for a in state.allocs_by_node(node_id)]
            return allocs, state.get_index("allocs")

        return _blocking(state, [Item(alloc_node=node_id)], query, run)

    m = re.match(r"^/v1/node/([^/]+)/drain$", path)
    if m:
        _require_write(method)
        enable = query.get("enable", ["false"])[0].lower() in ("1", "true")
        resp = rpc("Node.UpdateDrain",
                         {"NodeID": urllib.parse.unquote(m.group(1)),
                          "Drain": enable})
        return (resp, resp["Index"])

    m = re.match(r"^/v1/node/([^/]+)/evaluate$", path)
    if m:
        _require_write(method)
        resp = rpc("Node.Evaluate",
                         {"NodeID": urllib.parse.unquote(m.group(1))})
        index = state.latest_index() if state is not None else 0
        return ({"EvalIDs": resp["EvalIDs"], "Index": index}, index)

    # ------------------------------ allocations
    if path == "/v1/allocations":
        prefix = query.get("prefix", [""])[0]
        if remote:
            allocs, index = rpc_read("Alloc.List", {}, "Allocations")
            if prefix:
                allocs = [a for a in allocs if a["ID"].startswith(prefix)]
            return allocs, index
        need_server()


        def run():
            allocs = sorted((to_dict(a.stub()) for a in state.allocs()
                             if a.ID.startswith(prefix)),
                            key=lambda a: a["ID"])
            return allocs, state.get_index("allocs")

        return _blocking(state, [Item(table="allocs")], query, run)

    m = re.match(r"^/v1/allocation/([^/]+)$", path)
    if m:
        alloc_id = urllib.parse.unquote(m.group(1))
        if remote:
            alloc, index = rpc_read("Alloc.GetAlloc", {"AllocID": alloc_id},
                                    "Alloc")
        else:
            need_server()
            found = state.alloc_by_id(alloc_id)
            alloc = to_dict(found) if found else None
            index = state.get_index("allocs")
        if alloc is None:
            raise KeyError(f"alloc not found: {alloc_id}")
        return alloc, index

    # ------------------------------ service registry
    if path == "/v1/services":
        if remote:
            regs, index = rpc_read("Service.List", {}, "Services")
            return sorted(regs, key=lambda s: s["ID"]), index
        need_server()

        def run():
            regs = sorted((to_dict(s) for s in state.services()),
                          key=lambda s: s["ID"])
            return regs, state.get_index("services")

        return _blocking(state, [Item(table="services")], query, run)

    m = re.match(r"^/v1/service/([^/]+)$", path)
    if m:
        name = urllib.parse.unquote(m.group(1))
        if remote:
            regs, index = rpc_read("Service.GetService",
                                   {"ServiceName": name}, "Services")
            return sorted(regs, key=lambda s: s["ID"]), index
        need_server()

        def run():
            regs = state.services_by_name(name)
            # Table index, not max(ModifyIndex): a delete must not regress
            # the reported index (see Service.GetService).
            return sorted((to_dict(r) for r in regs),
                          key=lambda s: s["ID"]), state.get_index("services")

        return _blocking(state, [Item(service_name=name)], query, run)

    # ------------------------------ evaluations
    if path == "/v1/evaluations":
        prefix = query.get("prefix", [""])[0]
        if remote:
            evals, index = rpc_read("Eval.List", {}, "Evaluations")
            if prefix:
                evals = [e for e in evals if e["ID"].startswith(prefix)]
            return sorted(evals, key=lambda e: e["ID"]), index
        need_server()


        def run():
            evals = sorted((to_dict(e) for e in state.evals()
                            if e.ID.startswith(prefix)),
                           key=lambda e: e["ID"])
            return evals, state.get_index("evals")

        return _blocking(state, [Item(table="evals")], query, run)

    m = re.match(r"^/v1/evaluation/([^/]+)$", path)
    if m:
        eval_id = urllib.parse.unquote(m.group(1))
        if remote:
            ev, index = rpc_read("Eval.GetEval", {"EvalID": eval_id}, "Eval")
            if ev is None:
                raise KeyError(f"eval not found: {eval_id}")
            return ev, index
        need_server()

        def run():
            ev = state.eval_by_id(eval_id)
            if ev is None:
                raise KeyError(f"eval not found: {eval_id}")
            return to_dict(ev), state.get_index("evals")

        return _blocking(state, [Item(eval=eval_id)], query, run)

    m = re.match(r"^/v1/evaluation/([^/]+)/allocations$", path)
    if m:
        eval_id = urllib.parse.unquote(m.group(1))
        if remote:
            return rpc_read("Eval.Allocations", {"EvalID": eval_id},
                            "Allocations")
        need_server()
        allocs = [to_dict(a.stub()) for a in state.allocs_by_eval(eval_id)]
        return allocs, state.get_index("allocs")

    # ------------------------------ client fs + stats
    m = re.match(r"^/v1/client/fs/(ls|stat|cat|readat)/([^/]+)$", path)
    if m:
        op = m.group(1)
        alloc_id = urllib.parse.unquote(m.group(2))
        fs = need_client().get_alloc_fs(alloc_id)
        if fs is None:
            raise KeyError(f"alloc not found on client: {alloc_id}")
        rel = query.get("path", ["/"])[0]
        if op == "ls":
            return [to_dict(fi) for fi in fs.list_dir(rel)], None
        if op == "stat":
            return to_dict(fs.stat(rel)), None
        offset = int(query.get("offset", ["0"])[0])
        limit = int(query.get("limit", ["-1"])[0])
        data = fs.read_at(rel, offset, limit)
        return data.decode("utf-8", "replace"), None

    if path == "/v1/client/stats":
        return need_client().stats(), None

    m = re.match(r"^/v1/client/allocation/([^/]+)/stats$", path)
    if m:
        alloc_id = urllib.parse.unquote(m.group(1))
        return need_client().alloc_stats(alloc_id), None

    # ------------------------------ agent / status / regions / system
    if path == "/v1/agent/self":
        out = {"config": agent.self_config(), "member": agent.member_info()}
        return out, None
    if path == "/v1/agent/members":
        return agent.members(), None
    if path == "/v1/agent/monitor":
        # Recent agent log lines; `after=<seq>` polls incrementally
        # (reference capability: the log streaming behind `nomad monitor`
        # / log_writer.go).
        lines = int(query.get("lines", ["200"])[0])
        after = int(query.get("after", ["0"])[0])
        entries, seq = agent.log_ring.tail(lines, after)
        return {"Lines": [line for _, line in entries], "Seq": seq}, None

    if path == "/v1/agent/debug/stacks":
        # The runtime-profiling hook, gated exactly like the reference's
        # pprof routes (command/agent/http.go registers them only when
        # debug is enabled): stack traces leak code structure, so the
        # agent must opt in.
        if not getattr(agent.config, "enable_debug", False):
            raise CodedError(404, "debug endpoints disabled "
                                  "(set enable_debug)")
        frames = sys._current_frames()
        stacks = {}
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            if frame is None:
                continue
            stacks[f"{t.name} ({t.ident})"] = traceback.format_stack(frame)
        return stacks, None

    if path == "/v1/agent/debug/profile":
        # Whole-process CPU profile capture, the analogue of the
        # reference's pprof CPU endpoint (command/agent/http.go:133-139,
        # mounted only under enable_debug). A tracing profiler would need
        # a hook in every server thread; instead a sampler walks
        # sys._current_frames() for `seconds` (5ms period) and synthesizes
        # a standard pstats marshal blob — load it with
        # pstats.Stats(path_to_saved_body). Sample counts scale to
        # seconds: ct ~ wall time a function was anywhere on a stack,
        # tt ~ time it was at the top.
        if not getattr(agent.config, "enable_debug", False):
            raise CodedError(404, "debug endpoints disabled "
                                  "(set enable_debug)")
        raw_seconds = query.get("seconds", ["2"])[0]
        try:
            seconds = float(raw_seconds)
        except ValueError:
            raise CodedError(400, f"invalid seconds value "
                                  f"{raw_seconds!r}: not a number")
        if not (0.0 < seconds <= 30.0):  # NaN-rejecting clamp
            seconds = 2.0
        return _capture_profile(seconds), None

    if path == "/v1/agent/debug/faults":
        # Fault-injection control (resilience/failpoints.py), debug-gated
        # like stacks/profile: arming a failpoint is an operational
        # hazard, so the agent must opt in. GET lists every known site
        # with its armed spec and lifetime trigger count; PUT/POST arms
        # from the shared spec grammar (?spec=... or {"Spec": ...});
        # DELETE (or {"DisarmAll": true}) heals everything.
        if not getattr(agent.config, "enable_debug", False):
            raise CodedError(404, "debug endpoints disabled "
                                  "(set enable_debug)")
        from nomad_tpu.resilience import failpoints

        if method == "GET":
            return {"Sites": failpoints.snapshot()}, None
        if method == "DELETE":
            failpoints.disarm_all()
            return {"DisarmedAll": True}, None
        _require_write(method)
        payload = get_body()
        if isinstance(payload, dict) and payload.get("DisarmAll"):
            failpoints.disarm_all()
            return {"DisarmedAll": True}, None
        spec = query.get("spec", [""])[0]
        if not spec and isinstance(payload, dict):
            spec = payload.get("Spec", "")
        if not isinstance(spec, str):
            raise CodedError(400, f"Spec must be a string, "
                                  f"got {type(spec).__name__}")
        if not spec:
            raise CodedError(400, "need ?spec=site=mode[:p=..][:count=..]"
                                  " or a {\"Spec\": ...} body")
        try:
            touched = failpoints.arm_from_spec(spec)
        except ValueError as e:
            raise CodedError(400, str(e))
        return {"Touched": touched, "Sites": failpoints.snapshot()}, None

    if path == "/v1/agent/debug/trace":
        # Evaluation-lifecycle tracing (telemetry/trace.py), debug-gated
        # like faults/stacks/profile. GET lists retained traces (or one
        # full trace with ?id=..., Chrome trace-event JSON with
        # &format=chrome); PUT reconfigures ({"Enabled":..,
        # "SampleRatio":.., "Ring":..}); DELETE clears collected traces.
        if not getattr(agent.config, "enable_debug", False):
            raise CodedError(404, "debug endpoints disabled "
                                  "(set enable_debug)")
        from nomad_tpu.telemetry import trace as _trace

        if method == "GET":
            trace_id = query.get("id", [""])[0]
            fmt = query.get("format", [""])[0]
            full = _trace.get_trace(trace_id) if trace_id else None
            if trace_id and full is None:
                # Unknown ids 404 on BOTH paths — the chrome exporter
                # would otherwise 200 an empty, useless file.
                raise KeyError(f"trace not found: {trace_id}")
            if fmt == "chrome":
                return _trace.export_chrome(trace_id or None), None
            if trace_id:
                return {"Trace": full}, None
            out = _trace.status()
            entries = _trace.traces()
            # limit/after pagination over the newest-last summary list.
            # `after` is a TraceID cursor: resume just past it. A cursor
            # whose trace was evicted restarts from the oldest retained
            # entry (the ring is bounded — stale cursors are normal in a
            # poll loop, not an error).
            after = query.get("after", [""])[0]
            if after:
                for i, entry in enumerate(entries):
                    if entry["TraceID"] == after:
                        entries = entries[i + 1:]
                        break
            raw_limit = query.get("limit", [""])[0]
            if raw_limit:
                try:
                    limit = int(raw_limit)
                except ValueError:
                    raise CodedError(400, f"limit must be an integer, "
                                          f"got {raw_limit!r}")
                if limit <= 0:
                    raise CodedError(400, f"limit must be positive, "
                                          f"got {limit}")
                if len(entries) > limit:
                    entries = entries[:limit]
                    out["NextAfter"] = entries[-1]["TraceID"]
            out["Traces"] = entries
            return out, None
        if method == "DELETE":
            _trace.clear()
            return {"Cleared": True}, None
        _require_write(method)
        payload = get_body() or {}
        if not isinstance(payload, dict):
            raise CodedError(400, "body must be a JSON object")
        try:
            _trace.configure(
                enabled=payload.get("Enabled"),
                sample_ratio=payload.get("SampleRatio"),
                ring=payload.get("Ring"))
        except (TypeError, ValueError) as e:
            raise CodedError(400, str(e))
        return _trace.status(), None

    if path == "/v1/agent/debug/sched-stats":
        # Scheduling-pipeline observability: the same per-worker stage
        # timers and flow counters bench.py prints (PipelinedWorker.stats,
        # one declared schema — see README "Serving pipeline
        # observability"). Debug-gated like stacks/profile: stage timings
        # leak workload shape, so the agent must opt in.
        if not getattr(agent.config, "enable_debug", False):
            raise CodedError(404, "debug endpoints disabled "
                                  "(set enable_debug)")
        srv = need_server()
        workers = []
        by_worker: Dict[str, Any] = {}
        totals: Dict[str, Any] = {}
        for i, w in enumerate(getattr(srv, "workers", [])):
            stats = getattr(w, "stats", None)
            # ONE snapshot feeds the worker entry, the by-name map, and
            # the totals: the worker threads mutate the live dict, and
            # two reads could make Totals disagree with Workers[].Stats
            # in the same response.
            snap = dict(stats) if stats is not None else None
            name = getattr(w, "name", None) or f"worker-{i}"
            workers.append({
                "Index": i,
                "Name": name,
                "Type": type(w).__name__,
                "Window": getattr(w, "window", None),
                "Stats": snap,
            })
            if snap is not None:
                # Per-worker stats keyed by worker name: a scaling
                # regression (one worker starved, one convoying on the
                # chain lease) is invisible in the aggregate.
                by_worker[name] = snap
                for k, v in snap.items():
                    if isinstance(v, (int, float)):
                        totals[k] = totals.get(k, 0) + v
        qos_out: Dict[str, Any] = {"Enabled": False}
        srv_qos = getattr(srv, "qos", None)
        if srv_qos is not None and srv_qos.enabled:
            # Per-tier queue depth / SLO burn / promotions from the
            # broker, plus admission + preemption flow counters — the
            # operator's view of whether tiers are actually being served
            # within their deadlines (README "QoS & SLO serving").
            qos_out = {"Enabled": True,
                       **srv.eval_broker.qos_stats(),
                       "Counters": srv.qos_counters.snapshot()}
        # Columnar-store counters: segment/live-row/promoted counts plus
        # committed batches split by commit path (system sweep vs service
        # window) — which path a storm took (README "Columnar state
        # store").
        store_out = None
        state = getattr(srv, "state", None)
        col_stats = getattr(state, "columnar_stats", None)
        if col_stats is not None:
            store_out = col_stats()
        # Federation block: local snapshot-source behavior (reuse vs
        # refresh, current age), parked foreign-region evals, and the
        # polled per-region health view (README "Federation").
        fed_out: Dict[str, Any] = {"Enabled": False}
        if getattr(srv, "fed_health", None) is not None:
            fed_out = {
                "Enabled": True,
                "Region": srv.config.region,
                "Snapshots": (srv.fed_source.stats()
                              if srv.fed_source is not None else None),
                "ForeignParked": srv.eval_broker.foreign_count(),
                "Regions": srv.fed_health.snapshot(),
            }
        # Replica-digest block: this replica's chain position, verified
        # watermark, sync mode, and fold/exchange/divergence counters
        # (README "Replica determinism"). None when digests are disabled.
        digest = getattr(getattr(srv, "fsm", None), "digest", None)
        digest_out = digest.stats() if digest is not None else None
        return {"Workers": workers, "ByWorker": by_worker,
                "Totals": totals, "QoS": qos_out, "Store": store_out,
                "Federation": fed_out, "Digest": digest_out}, None

    if path == "/v1/agent/metrics":
        # In-memory telemetry snapshot (reference shape: go-metrics
        # DisplayMetrics behind the agent metrics endpoint).
        from nomad_tpu.telemetry import metrics as _metrics
        return _metrics.snapshot(), None
    if path == "/v1/agent/join":
        _require_write(method)
        addrs = query.get("address", [])
        return {"num_joined": agent.gossip_join(addrs)}, None
    if path == "/v1/agent/force-leave":
        _require_write(method)
        node = query.get("node", [""])[0]
        return {"ok": agent.gossip_force_leave(node)}, None
    if path == "/v1/agent/servers":
        return agent.server_addresses(), None
    if path == "/v1/status/leader":
        if remote:
            return rpc("Status.Leader", {}), None
        return agent.leader_address(), None
    if path == "/v1/status/peers":
        return rpc("Status.Peers", {}), None
    if path == "/v1/regions":
        # gossip-derived region list when federated (reference:
        # Region.List over the serf peers map, region_endpoint.go)
        try:
            return sorted(agent.rpc("Region.List", {})), None
        except ValueError:
            return [agent.region()], None
    if path == "/v1/system/gc":
        _require_write(method)
        rpc("System.GC", {})
        return None
    raise CodedError(404, f"no handler for {path}")
