"""Agent: one process running a server, a client, or both (reference:
command/agent/agent.go:61-675).

Dev mode mirrors the reference's `-dev` flag: in-memory single-node server
(always leader) + client in the same process with raw_exec enabled
(reference: command/agent/command.go DevConfig).
"""

from __future__ import annotations

import logging
import os
import socket
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from nomad_tpu.client import Client, ClientConfig, InProcServerChannel
from nomad_tpu.server import Server, ServerConfig

from .http import HTTPServer

logger = logging.getLogger("nomad.agent")


@dataclass
class AgentConfig:
    """(reference: command/agent/config.go)"""

    region: str = "global"
    datacenter: str = "dc1"
    node_name: str = ""
    data_dir: str = ""
    bind_addr: str = "127.0.0.1"
    http_port: int = 4646
    # Networked server mode (reference: Ports{RPC: 4647, Serf: 4648})
    rpc_port: int = 4647
    serf_port: int = 4648
    bootstrap_expect: int = 1
    start_join: List[str] = field(default_factory=list)
    # Client-only agents dial these RPC addresses (reference:
    # client/config Servers list)
    servers: List[str] = field(default_factory=list)
    # ... or bootstrap them from any agent's HTTP API via the service
    # registry ("nomad-server" instances)
    server_discovery_url: str = ""
    server_enabled: bool = False
    client_enabled: bool = False
    num_schedulers: int = 2
    # Scheduler engine knobs (server{} block): windowed device-chained
    # scheduling, window size, and multi-chip mesh serving ("all" shards
    # the node tensor over every local device).
    scheduler_window: int = 32
    pipelined_scheduling: bool = True
    scheduler_mesh: str = ""
    # Event broker ring size (server{} block): retained applied-index
    # window behind /v1/event/stream; 0 disables the broker entirely
    # (README "Event stream").
    event_buffer_size: int = 4096
    # QoS knobs (server { qos { ... } }), materialized into a QoSConfig
    # at server boot; {} / enabled=false leaves QoS off.
    qos: Dict[str, Any] = field(default_factory=dict)
    # Federation knobs (server { federation { ... } }), materialized
    # into a FederationConfig at server boot; {} / enabled=false leaves
    # federation off (README "Federation").
    federation: Dict[str, Any] = field(default_factory=dict)
    node_class: str = ""
    meta: Dict[str, str] = field(default_factory=dict)
    options: Dict[str, str] = field(default_factory=dict)
    dev_mode: bool = False
    # Telemetry (reference: command/agent/config.go Telemetry block)
    statsd_addr: str = ""
    telemetry_interval: float = 10.0
    # Evaluation-lifecycle tracing (telemetry/trace.py): disarmed by
    # default — near-zero cost; the debug endpoint can toggle at runtime.
    trace_enabled: bool = False
    trace_sample_ratio: float = 1.0
    trace_ring: int = 128
    # Route agent logs to syslog too (reference: enable_syslog)
    enable_syslog: bool = False
    # Expose /v1/agent/debug/* (reference: enable_debug gating pprof)
    enable_debug: bool = False
    # TLS for the RPC mux (reference: config.go TLSConfig; tls{} block):
    # both the server listener and every outgoing pool (raft, forwarding,
    # membership probes, client heartbeats) use it.
    tls_enable_rpc: bool = False
    tls_ca_file: str = ""
    tls_cert_file: str = ""
    tls_key_file: str = ""
    tls_verify_incoming: bool = True

    @staticmethod
    def dev() -> "AgentConfig":
        return AgentConfig(
            server_enabled=True,
            client_enabled=True,
            dev_mode=True,
            enable_debug=True,
            options={"driver.raw_exec.enable": "true"},
        )


def _qos_from_config(raw: Dict[str, Any]):
    """Materialize the server{qos{...}} dict into a QoSConfig (None when
    absent/disabled is fine — ServerConfig treats both as QoS off).
    Unknown keys fail loudly at boot instead of silently configuring
    nothing."""
    if not raw:
        return None
    from nomad_tpu.qos import QoSConfig

    known = {f for f in QoSConfig.__dataclass_fields__}
    unknown = set(raw) - known
    if unknown:
        raise ValueError(f"unknown qos config keys: {sorted(unknown)}")
    kwargs = dict(raw)
    for tuple_key in ("deadlines_s", "admit_depth"):
        if tuple_key in kwargs:
            kwargs[tuple_key] = tuple(kwargs[tuple_key])
    return QoSConfig(**kwargs)


def _federation_from_config(raw: Dict[str, Any]):
    """Materialize the server{federation{...}} dict into a
    FederationConfig (None when absent — federation off). Unknown keys
    fail loudly at boot, same contract as the qos block."""
    if not raw:
        return None
    from nomad_tpu.federation import FederationConfig

    known = {f for f in FederationConfig.__dataclass_fields__}
    unknown = set(raw) - known
    if unknown:
        raise ValueError(
            f"unknown federation config keys: {sorted(unknown)}")
    return FederationConfig(**raw)


class LogRing(logging.Handler):
    """Bounded in-memory ring of recent formatted log lines, serving the
    /v1/agent/monitor endpoint (the reference streams agent logs through
    log_writer.go; a polled ring is the same capability over plain HTTP)."""

    def __init__(self, capacity: int = 2000):
        super().__init__()
        from collections import deque

        self._lines = deque(maxlen=capacity)
        self._seq = 0
        self.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        # lint: allow(swallow, cannot log a failure of the log handler itself)
        except Exception:
            return
        # One lock for seq+append: a concurrent tail() must never see a
        # Seq whose line isn't in the ring yet (the poller would use it as
        # a cursor and skip that line forever).
        with self.lock:
            self._seq += 1
            self._lines.append((self._seq, line))

    def tail(self, lines: int = 200, after: int = 0):
        with self.lock:
            snapshot = list(self._lines)
            seq = self._seq
        out = [(s, line) for s, line in snapshot if s > after]
        return (out[-lines:] if lines > 0 else []), seq


def _agent_tls(config: "AgentConfig"):
    if not config.tls_enable_rpc:
        return None
    from nomad_tpu.rpc.tls import TLSConfig

    return TLSConfig(enable_rpc=True, ca_file=config.tls_ca_file,
                     cert_file=config.tls_cert_file,
                     key_file=config.tls_key_file,
                     verify_incoming=config.tls_verify_incoming)


class Agent:
    def __init__(self, config: AgentConfig):
        self.config = config
        self.log_ring = LogRing()
        logging.getLogger().addHandler(self.log_ring)
        self.server: Optional[Server] = None
        self.cluster = None  # ClusterServer in networked mode
        self.client: Optional[Client] = None
        self.http: Optional[HTTPServer] = None
        self.rpc_endpoints = None
        self._rpc_pool = None
        if not config.data_dir:
            config.data_dir = tempfile.mkdtemp(prefix="nomad_tpu_")
        if not config.node_name:
            config.node_name = socket.gethostname()

    def start(self) -> None:
        # (reference: command/agent/command.go:556-580 setupTelemetry)
        from nomad_tpu.telemetry import metrics, trace
        metrics.configure(statsd_addr=self.config.statsd_addr,
                          collection_interval=self.config.telemetry_interval,
                          host_label=self.config.node_name)
        trace.configure(enabled=self.config.trace_enabled,
                        sample_ratio=self.config.trace_sample_ratio,
                        ring=self.config.trace_ring)
        try:
            if self.config.server_enabled:
                if self.config.dev_mode:
                    self._setup_dev_server()
                else:
                    self._setup_cluster_server()
            if self.config.client_enabled:
                self._setup_client()
            self.http = HTTPServer(self, host=self.config.bind_addr,
                                   port=self.config.http_port)
            self.http.start()
        except Exception:
            # A half-started agent must release everything it bound (RPC
            # listener, gossip sockets, client state): a caller retrying
            # start() on a transient bind failure would otherwise conflict
            # with its OWN leaked sockets forever.
            try:
                self.shutdown()
            except Exception:
                logger.debug("agent: cleanup after failed start also "
                             "failed", exc_info=True)
            # shutdown() detached the log ring; a retried start() must
            # still capture logs for the monitor endpoint.
            logging.getLogger().addHandler(self.log_ring)
            self.server = None
            self.cluster = None
            self.client = None
            self.http = None
            self.rpc_endpoints = None
            self._rpc_pool = None
            raise
        if self.server is not None:
            self._register_server_service()

    def _register_server_service(self) -> None:
        """Advertise this server in the service registry (name
        "nomad-server") so clients can bootstrap their server list from any
        agent's HTTP API. Retries in the background until a leader exists."""
        import threading

        rpc_addr = self.cluster.addr if self.cluster is not None else ""
        http_addr = f"{self.config.bind_addr}:{self.http.port}"

        from nomad_tpu.services import build_server_service_regs
        from nomad_tpu.structs import to_dict

        node_id = self.server.config.node_id or self.config.node_name or "dev"
        self._server_service_node_id = node_id
        regs = [to_dict(r) for r in build_server_service_regs(
            node_id, rpc_addr, http_addr)]

        def attempt() -> None:
            # Through the RPC dispatch so followers forward to the leader.
            from nomad_tpu.resilience.retry import Backoff, RetryPolicy

            policy = RetryPolicy(max_attempts=None, deadline=60.0,
                                 backoff=Backoff(base=0.5, cap=5.0))
            try:
                policy.call(self.rpc, "Service.Sync",
                            {"Upserts": regs, "Deletes": []})
            except Exception:
                logger.warning("agent: server self-registration timed out")

        threading.Thread(target=attempt, daemon=True,
                         name="server-self-reg").start()

    def _setup_dev_server(self) -> None:
        """(reference: agent.go:356 setupServer, DevMode branch)"""
        from nomad_tpu.rpc.endpoints import Endpoints

        sconf = ServerConfig(
            region=self.config.region,
            datacenter=self.config.datacenter,
            num_schedulers=self.config.num_schedulers,
            scheduler_window=self.config.scheduler_window,
            pipelined_scheduling=self.config.pipelined_scheduling,
            scheduler_mesh=self.config.scheduler_mesh,
            event_buffer_size=self.config.event_buffer_size,
            qos=_qos_from_config(self.config.qos),
            federation=_federation_from_config(self.config.federation),
            dev_mode=True,
        )
        self.server = Server(sconf)
        self.server.establish_leadership()
        self.rpc_endpoints = Endpoints(self.server)

    def _setup_cluster_server(self) -> None:
        """Networked server: RPC+raft listener plus the gossip membership
        plane (reference: agent.go:356 setupServer -> nomad.NewServer with
        setupRPC/setupRaft/setupSerf, server.go:166-263)."""
        from nomad_tpu.raft.native_log import make_log_store
        from nomad_tpu.rpc.cluster import ClusterServer

        sconf = ServerConfig(
            region=self.config.region,
            datacenter=self.config.datacenter,
            num_schedulers=self.config.num_schedulers,
            scheduler_window=self.config.scheduler_window,
            pipelined_scheduling=self.config.pipelined_scheduling,
            scheduler_mesh=self.config.scheduler_mesh,
            event_buffer_size=self.config.event_buffer_size,
            qos=_qos_from_config(self.config.qos),
            federation=_federation_from_config(self.config.federation),
            bootstrap_expect=self.config.bootstrap_expect,
        )
        self.cluster = ClusterServer(sconf, bind_addr=self.config.bind_addr,
                                     port=self.config.rpc_port,
                                     tls=_agent_tls(self.config))
        # Durable raft log + term/vote (reference: raft-boltdb store,
        # server.go setupRaft) — a restarted server must not re-vote in a
        # term it already voted in, nor re-bootstrap a formed cluster.
        raft_dir = os.path.join(self.config.data_dir, "raft")
        os.makedirs(raft_dir, exist_ok=True)
        # Native C++ segment log when built (make -C native), Python
        # FileLogStore otherwise — same on-disk format either way.
        self.cluster.connect([], log_store=make_log_store(raft_dir))
        self.cluster.start()
        self.cluster.enable_gossip(self.config.node_name,
                                   gossip_port=self.config.serf_port,
                                   join=self.config.start_join or None)
        self.server = self.cluster.server
        self.rpc_endpoints = self.cluster.endpoints

    def _setup_client(self) -> None:
        """(reference: agent.go:428 setupClient)"""
        cconf = ClientConfig(
            state_dir=os.path.join(self.config.data_dir, "client"),
            alloc_dir=os.path.join(self.config.data_dir, "alloc"),
            datacenter=self.config.datacenter,
            region=self.config.region,
            node_class=self.config.node_class,
            meta=dict(self.config.meta),
            options=dict(self.config.options),
            dev_mode=self.config.dev_mode,
        )
        if self.server is not None and self.cluster is None:
            channel = InProcServerChannel(self.server)
        else:
            from nomad_tpu.client.rpc import NetServerChannel, discover_servers
            servers = list(self.config.servers)
            if self.cluster is not None:
                servers.append(self.cluster.addr)
            if not servers and self.config.server_discovery_url:
                # Cold boot races server self-registration (which itself
                # waits on leader election): retry instead of crashing.
                from nomad_tpu.resilience.retry import Backoff, RetryPolicy

                def discover():
                    found = discover_servers(
                        self.config.server_discovery_url)
                    if not found:
                        raise ConnectionError("no servers registered yet")
                    return found

                try:
                    servers = RetryPolicy(
                        max_attempts=None, deadline=60.0,
                        backoff=Backoff(base=0.5, cap=5.0)).call(discover)
                # lint: allow(swallow, exhausted discovery surfaces as the ValueError below)
                except Exception:
                    servers = []
            if not servers:
                raise ValueError(
                    "client-only agents need config.servers (RPC addresses) "
                    "or server_discovery_url")
            tls = _agent_tls(self.config)
            if tls is not None:
                from nomad_tpu.rpc.tls import client_context

                channel = NetServerChannel(
                    servers, tls_context=client_context(tls))
            else:
                channel = NetServerChannel(servers)
        self.client = Client(cconf, channel)
        if self.config.node_name:
            self.client.node.Name = self.config.node_name
        self.client.start()

    def shutdown(self) -> None:
        logging.getLogger().removeHandler(self.log_ring)
        if getattr(self, "_server_service_node_id", None):
            # Graceful departure: pull this server's registry entries so
            # bootstrapping clients stop being handed its addresses. (A
            # crashed server's entries are pruned by the membership plane.)
            from nomad_tpu.services import server_service_reg_ids

            try:
                self.rpc("Service.Sync", {
                    "Upserts": [],
                    "Deletes": server_service_reg_ids(
                        self._server_service_node_id)})
            except Exception:
                logger.debug("agent: self-deregistration failed", exc_info=True)
        if self._rpc_pool is not None:
            self._rpc_pool.close()
        if self.http is not None:
            self.http.shutdown()
        if self.client is not None:
            self.client.shutdown()
        if self.cluster is not None:
            self.cluster.shutdown()
        elif self.server is not None:
            self.server.shutdown()

    # -------------------------------------------------------- http helpers
    def rpc(self, method: str, body: dict):
        """Route a request through the RPC dispatch so NotLeaderError and
        cross-region bodies forward exactly as wire RPCs do (reference: the
        HTTP agent always goes through agent.RPC -> Server.forward,
        command/agent/agent.go:597 + nomad/rpc.go:177). Client-only agents
        forward over the wire to their configured servers (reference:
        client.RPC via rpcproxy, client/client.go:332)."""
        if self.rpc_endpoints is not None:
            return self.rpc_endpoints.handle(method, body)
        servers = list(self.config.servers)
        if not servers:
            raise ValueError(
                "no server running on this agent and no servers configured")
        from nomad_tpu.rpc.pool import ConnError, ConnPool
        if self._rpc_pool is None:
            from nomad_tpu.rpc.tls import client_context

            tls = _agent_tls(self.config)
            self._rpc_pool = ConnPool(
                tls_context=client_context(tls) if tls else None)
        last_exc: Exception = ValueError("no servers reachable")
        for addr in servers:
            try:
                return self._rpc_pool.call(addr, method, body)
            except (OSError, ConnError, TimeoutError) as exc:
                last_exc = exc
        raise last_exc

    def region(self) -> str:
        return self.config.region

    def self_config(self) -> dict:
        return {
            "Region": self.config.region,
            "Datacenter": self.config.datacenter,
            "Server": self.config.server_enabled,
            "Client": self.config.client_enabled,
            "DevMode": self.config.dev_mode,
            "DataDir": self.config.data_dir,
            "EnableDebug": self.config.enable_debug,
        }

    def member_info(self) -> dict:
        if self.cluster is not None and self.cluster.membership is not None:
            ml = self.cluster.membership.memberlist.local_member()
            return {"Name": ml.name, "Addr": ml.addr, "Port": ml.port,
                    "Status": ml.state, "Tags": dict(ml.tags)}
        return {
            "Name": self.config.node_name or "local",
            "Addr": self.config.bind_addr,
            "Port": self.http.port if self.http else self.config.http_port,
            "Status": "alive",
            "Tags": {"region": self.config.region, "dc": self.config.datacenter,
                     "role": "nomad"},
        }

    def members(self) -> list:
        """(reference: /v1/agent/members, agent_endpoint.go)"""
        if self.cluster is not None and self.cluster.membership is not None:
            return self.cluster.membership.members()
        return [self.member_info()]

    def gossip_join(self, addresses: list) -> int:
        """(reference: /v1/agent/join -> serf join)"""
        if self.cluster is None or self.cluster.membership is None:
            raise ValueError("gossip not enabled (dev-mode or client agent)")
        return self.cluster.membership.join(list(addresses))

    def gossip_force_leave(self, node: str) -> bool:
        """(reference: /v1/agent/force-leave -> serf ForceLeave)"""
        if self.cluster is None or self.cluster.membership is None:
            raise ValueError("gossip not enabled (dev-mode or client agent)")
        return self.cluster.membership.force_leave(node)

    def server_addresses(self) -> list:
        if self.cluster is not None and self.cluster.membership is not None:
            addrs = sorted(p.rpc_addr
                           for p in self.cluster.membership.local_servers())
            if addrs:
                return addrs
            return [self.cluster.addr]
        port = self.http.port if self.http else self.config.http_port
        return [f"{self.config.bind_addr}:{port}"]

    def leader_address(self) -> str:
        """The current raft leader, or "" when the cluster has no leader
        (a dormant bootstrap-expect quorum, an election in flight). Never
        guess: reporting ourselves as leader masks a cluster that hasn't
        actually formed."""
        if self.cluster is None and self.server is not None:
            return self.server_addresses()[0]  # dev mode: always leader
        if self.server is not None:
            leader = getattr(self.server.raft, "leader_id", None)
            if leader:
                return leader
        return ""
