"""Agent: one process running a server, a client, or both (reference:
command/agent/agent.go:61-675).

Dev mode mirrors the reference's `-dev` flag: in-memory single-node server
(always leader) + client in the same process with raw_exec enabled
(reference: command/agent/command.go DevConfig).
"""

from __future__ import annotations

import logging
import os
import tempfile
from dataclasses import dataclass, field
from typing import Dict, Optional

from nomad_tpu.client import Client, ClientConfig, InProcServerChannel
from nomad_tpu.server import Server, ServerConfig

from .http import HTTPServer

logger = logging.getLogger("nomad.agent")


@dataclass
class AgentConfig:
    """(reference: command/agent/config.go)"""

    region: str = "global"
    datacenter: str = "dc1"
    node_name: str = ""
    data_dir: str = ""
    bind_addr: str = "127.0.0.1"
    http_port: int = 4646
    server_enabled: bool = False
    client_enabled: bool = False
    num_schedulers: int = 2
    node_class: str = ""
    meta: Dict[str, str] = field(default_factory=dict)
    options: Dict[str, str] = field(default_factory=dict)
    dev_mode: bool = False

    @staticmethod
    def dev() -> "AgentConfig":
        return AgentConfig(
            server_enabled=True,
            client_enabled=True,
            dev_mode=True,
            options={"driver.raw_exec.enable": "true"},
        )


class Agent:
    def __init__(self, config: AgentConfig):
        self.config = config
        self.server: Optional[Server] = None
        self.client: Optional[Client] = None
        self.http: Optional[HTTPServer] = None
        if not config.data_dir:
            config.data_dir = tempfile.mkdtemp(prefix="nomad_tpu_")

    def start(self) -> None:
        if self.config.server_enabled:
            self._setup_server()
        if self.config.client_enabled:
            self._setup_client()
        self.http = HTTPServer(self, host=self.config.bind_addr,
                               port=self.config.http_port)
        self.http.start()

    def _setup_server(self) -> None:
        """(reference: agent.go:356 setupServer)"""
        sconf = ServerConfig(
            region=self.config.region,
            datacenter=self.config.datacenter,
            num_schedulers=self.config.num_schedulers,
            dev_mode=self.config.dev_mode,
        )
        self.server = Server(sconf)
        self.server.establish_leadership()

    def _setup_client(self) -> None:
        """(reference: agent.go:428 setupClient)"""
        if self.server is None:
            raise ValueError(
                "client-only agents need a server address; in-process RPC "
                "requires server_enabled (wire RPC lands with multi-node)")
        cconf = ClientConfig(
            state_dir=os.path.join(self.config.data_dir, "client"),
            alloc_dir=os.path.join(self.config.data_dir, "alloc"),
            datacenter=self.config.datacenter,
            region=self.config.region,
            node_class=self.config.node_class,
            meta=dict(self.config.meta),
            options=dict(self.config.options),
            dev_mode=self.config.dev_mode,
        )
        self.client = Client(cconf, InProcServerChannel(self.server))
        if self.config.node_name:
            self.client.node.Name = self.config.node_name
        self.client.start()

    def shutdown(self) -> None:
        if self.http is not None:
            self.http.shutdown()
        if self.client is not None:
            self.client.shutdown()
        if self.server is not None:
            self.server.shutdown()

    # -------------------------------------------------------- http helpers
    def region(self) -> str:
        return self.config.region

    def self_config(self) -> dict:
        return {
            "Region": self.config.region,
            "Datacenter": self.config.datacenter,
            "Server": self.config.server_enabled,
            "Client": self.config.client_enabled,
            "DevMode": self.config.dev_mode,
            "DataDir": self.config.data_dir,
        }

    def member_info(self) -> dict:
        return {
            "Name": self.config.node_name or "local",
            "Addr": self.config.bind_addr,
            "Port": self.http.port if self.http else self.config.http_port,
            "Status": "alive",
            "Tags": {"region": self.config.region, "dc": self.config.datacenter,
                     "role": "nomad"},
        }

    def server_addresses(self) -> list:
        port = self.http.port if self.http else self.config.http_port
        return [f"{self.config.bind_addr}:{port}"]

    def leader_address(self) -> str:
        return self.server_addresses()[0]
