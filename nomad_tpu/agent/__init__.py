"""Agent: composes Server and/or Client with the HTTP API (reference:
command/agent/agent.go)."""

from .agent import Agent, AgentConfig  # noqa: F401
from .http import HTTPServer  # noqa: F401
