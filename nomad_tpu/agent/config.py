"""Agent config files: HCL/JSON (reference: command/agent/config.go,
config_parse.go).

Supports the reference's block layout:

  region = "global"
  datacenter = "dc1"
  data_dir = "/var/lib/nomad"
  bind_addr = "0.0.0.0"
  ports { http = 4646 }
  server { enabled = true  num_schedulers = 4 }
  client { enabled = true  node_class = "foo"  meta { k = "v" }
           options { "driver.raw_exec.enable" = "1" } }
"""

from __future__ import annotations

import json

from nomad_tpu.jobspec.hcl import parse as parse_hcl
from nomad_tpu.jobspec.parse import parse_duration

from .agent import AgentConfig


def load_config_file(path: str) -> AgentConfig:
    """One file, or a DIRECTORY of .hcl/.json files merged in sorted order
    (later files override; nested blocks merge key-wise) — the reference
    accepts config directories the same way (command/agent/config.go
    LoadConfigDir), and the shipped systemd unit points at /etc/nomad-tpu."""
    import os

    if os.path.isdir(path):
        merged: dict = {}
        for name in sorted(os.listdir(path)):
            if not (name.endswith(".hcl") or name.endswith(".json")):
                continue
            _merge(merged, _parse_one(os.path.join(path, name)))
        if not merged:
            raise ValueError(f"no .hcl/.json config files in {path}")
        return config_from_dict(merged)
    return config_from_dict(_parse_one(path))


def _parse_one(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        return json.loads(text)
    return parse_hcl(text)


def _merge(base: dict, extra: dict) -> None:
    for key, value in extra.items():
        if isinstance(value, dict) and isinstance(base.get(key), dict):
            _merge(base[key], value)
        else:
            base[key] = value


def config_from_dict(data: dict) -> AgentConfig:
    cfg = AgentConfig()
    cfg.region = data.get("region", cfg.region)
    cfg.datacenter = data.get("datacenter", cfg.datacenter)
    cfg.node_name = data.get("name", cfg.node_name)
    cfg.data_dir = data.get("data_dir", cfg.data_dir)
    cfg.enable_syslog = bool(data.get("enable_syslog", cfg.enable_syslog))
    cfg.enable_debug = bool(data.get("enable_debug", cfg.enable_debug))
    cfg.bind_addr = data.get("bind_addr", cfg.bind_addr)
    ports = data.get("ports") or {}
    cfg.http_port = int(ports.get("http", cfg.http_port))
    cfg.rpc_port = int(ports.get("rpc", cfg.rpc_port))
    cfg.serf_port = int(ports.get("serf", cfg.serf_port))

    server = data.get("server") or {}
    cfg.server_enabled = bool(server.get("enabled", False))
    cfg.num_schedulers = int(server.get("num_schedulers", cfg.num_schedulers))
    cfg.bootstrap_expect = int(server.get("bootstrap_expect",
                                          cfg.bootstrap_expect))
    join = server.get("start_join") or []
    cfg.start_join = [join] if isinstance(join, str) else list(join)
    cfg.scheduler_window = int(server.get("scheduler_window",
                                          cfg.scheduler_window))
    cfg.pipelined_scheduling = bool(server.get("pipelined_scheduling",
                                               cfg.pipelined_scheduling))
    cfg.scheduler_mesh = server.get("scheduler_mesh", cfg.scheduler_mesh)
    # Event broker ring size (server { event_buffer_size = 8192 });
    # 0 disables the broker and /v1/event/stream (README "Event stream").
    cfg.event_buffer_size = int(server.get("event_buffer_size",
                                           cfg.event_buffer_size))
    # QoS knobs (server { qos { enabled = true high_floor = 70 ... } });
    # passed through as a plain dict and materialized into a QoSConfig by
    # the agent (README "QoS & SLO serving" documents each knob).
    cfg.qos = dict(server.get("qos") or {})
    # Federation knobs (server { federation { enabled = true
    # max_staleness_s = 0.25 ... } }); same pass-through contract —
    # unknown keys fail at server boot (README "Federation").
    cfg.federation = dict(server.get("federation") or {})

    telemetry = data.get("telemetry") or {}
    cfg.statsd_addr = telemetry.get("statsd_address", cfg.statsd_addr)
    if "collection_interval" in telemetry:
        # Bare numbers mean SECONDS here (an interval config, not a wire
        # duration): interpreting 30 as 30ns would silently floor to the
        # sink minimum. Strings take Go duration syntax ("10s", "1m").
        raw = telemetry["collection_interval"]
        if isinstance(raw, (int, float)):
            cfg.telemetry_interval = float(raw)
        else:
            cfg.telemetry_interval = parse_duration(raw) / 1e9
    cfg.trace_enabled = bool(telemetry.get("trace", cfg.trace_enabled))
    cfg.trace_sample_ratio = float(
        telemetry.get("trace_sample_ratio", cfg.trace_sample_ratio))
    cfg.trace_ring = int(telemetry.get("trace_ring", cfg.trace_ring))

    client = data.get("client") or {}
    cfg.client_enabled = bool(client.get("enabled", False))
    cfg.node_class = client.get("node_class", "")
    servers = client.get("servers") or []
    cfg.servers = [servers] if isinstance(servers, str) else list(servers)
    cfg.server_discovery_url = client.get("server_discovery_url",
                                          cfg.server_discovery_url)
    cfg.meta = {k: str(v) for k, v in (client.get("meta") or {}).items()}
    cfg.options = {k: str(v) for k, v in (client.get("options") or {}).items()}

    # TLS for the RPC mux (reference: config.go TLSConfig; tls {} block).
    tls = data.get("tls") or {}
    cfg.tls_enable_rpc = bool(tls.get("rpc", cfg.tls_enable_rpc))
    cfg.tls_ca_file = tls.get("ca_file", cfg.tls_ca_file)
    cfg.tls_cert_file = tls.get("cert_file", cfg.tls_cert_file)
    cfg.tls_key_file = tls.get("key_file", cfg.tls_key_file)
    cfg.tls_verify_incoming = bool(tls.get("verify_incoming",
                                           cfg.tls_verify_incoming))
    return cfg
