"""Device mesh + shardings for the scheduling kernels.

Design: one logical axis 'nodes' over all chips of a region. The node-table
arrays shard along their first (node) axis; per-placement inputs (demands,
tg ids) and scalars replicate.

Two regimes use the mesh differently. The naive scan path
(place_batch_sharded, kept as the oracle/fallback) follows the
scaling-book recipe — annotate shardings, let XLA's SPMD partitioner
insert the ICI collectives for its global argmax/sum reductions — which
is correct but pays collectives per PLACEMENT. The served keyed path
does NOT hand the partitioner that choice: kernels.py's 'shard-local
mesh pipeline' (`_place_batch_keyed_mesh`) runs an explicit `shard_map`
cold stage over these same shardings with ZERO collectives in any
compiled program, exchanges only O(devices x T x k) winner-candidate
rows point-to-point, and keeps warm storm windows resident on the lead
device (`mesh_collective_audit` gates the claim in tier-1 and the
multi-chip dry run).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nomad_tpu.scheduler import kernels

NODE_AXIS = "nodes"


def scheduling_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all devices: the node axis shards across ICI."""
    devs = np.array(devices if devices is not None else jax.devices())
    return Mesh(devs.reshape(-1), (NODE_AXIS,))


def pow2_prefix(devices: Sequence[jax.Device]) -> Sequence[jax.Device]:
    """Largest power-of-two prefix of a device list — the mesh-sizing rule
    (node rows pad to powers of two, so the sharded axis must divide
    evenly). THE single definition; server boot and the multi-chip dry run
    both use it."""
    n = 1
    while n * 2 <= len(devices):
        n *= 2
    return devices[:n]


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (node) axis."""
    return NamedSharding(mesh, P(NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_node_arrays(mesh: Mesh, arrays: dict) -> dict:
    """Place the node-table arrays with the node axis split over the mesh."""
    ns = node_sharding(mesh)
    return {k: jax.device_put(v, ns) for k, v in arrays.items()}


def place_batch_sharded(mesh: Mesh, capacity, score_cap, usage, tg_masks,
                        job_counts, demands, tg_ids, valid, noise, penalty,
                        distinct_hosts, banned0) -> kernels.PlacementResult:
    """Run the placement scan with the node axis sharded over the mesh.

    tg_masks is [T, N]: sharded on its second axis; demands/tg_ids/valid are
    per-placement and replicate. The same jitted kernel is reused — XLA
    partitions it from the input shardings.
    """
    ns = node_sharding(mesh)
    ns2 = NamedSharding(mesh, P(None, NODE_AXIS))
    rep = replicated(mesh)
    args = (
        jax.device_put(capacity, ns),
        jax.device_put(score_cap, ns),
        jax.device_put(usage, ns),
        jax.device_put(tg_masks, ns2),
        jax.device_put(job_counts, ns),
        jax.device_put(demands, rep),
        jax.device_put(tg_ids, rep),
        jax.device_put(valid, rep),
        jax.device_put(noise, ns),
        jax.device_put(penalty, rep),
        jax.device_put(distinct_hosts, rep),
        jax.device_put(banned0, ns),
    )
    return kernels.place_batch(*args)
