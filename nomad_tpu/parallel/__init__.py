"""Multi-chip scale-out: the node axis over the device mesh.

The reference scales scheduling by adding servers (optimistic concurrency,
reference: nomad/worker.go) and scales the cluster by sharding nothing — each
scheduler scans all nodes. Here the node table itself shards across TPU
devices over ICI: capacity/usage/masks are laid out [N, R] with N split over
the mesh's 'nodes' axis, the placement kernel's reductions (argmax, sums)
become XLA collectives, and regions federate over DCN (one mesh per region).
"""

from .mesh import (  # noqa: F401
    node_sharding,
    place_batch_sharded,
    pow2_prefix,
    replicated,
    scheduling_mesh,
)
