"""Shared timer service: one heap, one thread, thread-pooled callbacks.

`threading.Timer` spawns a full OS thread per timer. The server schedules a
timer per tracked node heartbeat (reference: nomad/heartbeat.go uses the Go
runtime's shared timer heap via time.AfterFunc) and two per in-flight
evaluation (nack redelivery, eval_broker.go:372-416) — at 10k nodes that is
10k parked threads plus constant thread create/exit churn on the scheduling
hot path. This wheel replaces them with a single heap-ordered dispatcher;
callbacks run on a small pool so a slow callback (heartbeat expiry does a
consensus write) can't stall the wheel.

The module-level `wheel` is the process singleton; tests may construct
private wheels.
"""

from __future__ import annotations

import heapq
import itertools
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from nomad_tpu.analysis import guarded_by, requires_lock


class DaemonPool:
    """Minimal fixed-size daemon worker pool.

    concurrent.futures joins its (non-daemon) workers at interpreter exit,
    so one callback blocked on a wedged consensus write would hang process
    shutdown — the replaced threading.Timers were daemonic and never did.
    """

    def __init__(self, size: int, name: str):
        self._q: "queue.SimpleQueue[Optional[Tuple[Callable, tuple]]]" = (
            queue.SimpleQueue())
        for i in range(size):
            threading.Thread(target=self._work, daemon=True,
                             name=f"{name}-{i}").start()

    def submit(self, fn: Callable, *args: Any) -> None:
        self._q.put((fn, args))

    def _work(self) -> None:
        while True:
            fn, args = self._q.get()
            try:
                fn(*args)
            except Exception:
                import logging

                logging.getLogger("nomad.timerwheel").exception(
                    "pooled callback failed")


class TimerHandle:
    """Cancellable handle for one scheduled callback."""

    __slots__ = ("deadline", "fn", "args", "_cancelled")

    def __init__(self, deadline: float, fn: Callable, args: Tuple[Any, ...]):
        self.deadline = deadline
        self.fn = fn
        self.args = args
        self._cancelled = False

    def cancel(self) -> None:
        # Best-effort, same as threading.Timer.cancel(): a callback already
        # handed to the pool may still run.
        self._cancelled = True


class TimerWheel:
    _concurrency = guarded_by("_cond", "_heap", "_pool", "_thread")

    def __init__(self, pool_size: int = 4):
        self._heap: List[Tuple[float, int, TimerHandle]] = []
        self._seq = itertools.count()
        self._cond = threading.Condition()
        self._pool_size = pool_size
        self._pool: Optional[DaemonPool] = None
        self._thread: Optional[threading.Thread] = None

    @requires_lock("_cond")
    def _ensure_started(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._pool = DaemonPool(self._pool_size, "timer-cb")
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="timer-wheel")
            self._thread.start()

    def after(self, delay: float, fn: Callable, *args: Any) -> TimerHandle:
        """Schedule fn(*args) after `delay` seconds; returns a cancellable
        handle."""
        handle = TimerHandle(time.monotonic() + max(0.0, delay), fn, args)
        with self._cond:
            self._ensure_started()
            heapq.heappush(self._heap, (handle.deadline, next(self._seq),
                                        handle))
            self._cond.notify()
        return handle

    def _run(self) -> None:
        while True:
            with self._cond:
                while True:
                    now = time.monotonic()
                    if not self._heap:
                        self._cond.wait()
                        continue
                    deadline = self._heap[0][0]
                    if deadline <= now:
                        break
                    self._cond.wait(deadline - now)
                due: List[TimerHandle] = []
                now = time.monotonic()
                while self._heap and self._heap[0][0] <= now:
                    _, _, handle = heapq.heappop(self._heap)
                    if not handle._cancelled:
                        due.append(handle)
                pool = self._pool
            for handle in due:
                pool.submit(self._invoke, handle)

    @staticmethod
    def _invoke(handle: TimerHandle) -> None:
        if handle._cancelled:
            return
        try:
            handle.fn(*handle.args)
        except Exception:
            import logging

            logging.getLogger("nomad.timerwheel").exception(
                "timer callback failed")


# Process-global wheel (the Go runtime-timer analogue).
wheel = TimerWheel()
