"""FSM: the replicated state machine (reference: nomad/fsm.go).

Every cluster mutation is a typed message applied through the FSM. In a
replicated deployment messages flow through the Raft log; in dev mode the
DevRaft backend assigns indexes and applies directly. Either way the FSM is
the single write path into the state store, and the hook point where the
leader's eval broker / blocked-evals tracker observe state transitions
(reference: fsm.go:99-144, 158-164, 320-328).
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from nomad_tpu.analysis.replica_digest import chaos_corrupt, effect_of
from nomad_tpu.events.builders import build_events
from nomad_tpu.resilience import failpoints
from nomad_tpu.server.timetable import TimeTable
from nomad_tpu.state.state_store import StateStore, SweepSegment
from nomad_tpu.telemetry import metrics, trace
from nomad_tpu.structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    PeriodicLaunch,
    ServiceRegistration,
    from_dict,
    to_dict,
)
from nomad_tpu.structs.structs import (
    EvalStatusBlocked,
    JobStatusRunning,
    NodeStatusReady,
)


logger = logging.getLogger("nomad.fsm")

# Streaming-snapshot chunk bound: objects (or columnar rows) per chunk.
# Small enough that one chunk's encode/persist never stalls the apply
# loop noticeably; large enough that a 1M-row store is ~hundreds of
# chunks, not tens of thousands.
SNAPSHOT_CHUNK_ITEMS = 2048


def _slice_segment(seg: Dict[str, Any], lo: int, hi: int) -> Dict[str, Any]:
    """Row-slice one serialized SweepSegment. Each slice restores as its
    own segment; every read surface (by id/node/job/eval, dumps, client
    maps) is the union over segments, so the split is read-equivalent."""
    out = dict(seg)
    for key in ("AllocIDs", "Names", "NodeIDs"):
        out[key] = seg[key][lo:hi]
    if seg.get("TGIdx"):
        out["TGIdx"] = seg["TGIdx"][lo:hi]
    return out


class MessageType(enum.IntEnum):
    """(reference: structs.go:40-57 MessageType constants)"""

    NodeRegister = 0
    NodeDeregister = 1
    NodeUpdateStatus = 2
    NodeUpdateDrain = 3
    JobRegister = 4
    JobDeregister = 5
    EvalUpdate = 6
    EvalDelete = 7
    AllocUpdate = 8
    AllocClientUpdate = 9
    PeriodicLaunchType = 10
    PeriodicLaunchDelete = 11
    ServiceSync = 12
    # Columnar sweep-batch commit (beyond reference v0.4): one entry
    # carries a whole admitted system-sweep chunk as columnar arrays
    # (alloc ids, instance names, per-TG frozen templates, per-row usage
    # delta) instead of N per-alloc payloads.
    ApplySweepBatch = 13


# Metric leaf names per message type (reference: the MeasureSince keys in
# each fsm.go apply handler, fsm.go:147-430).
_MSG_METRIC = {
    MessageType.NodeRegister: "register_node",
    MessageType.NodeDeregister: "deregister_node",
    MessageType.NodeUpdateStatus: "node_status_update",
    MessageType.NodeUpdateDrain: "node_drain_update",
    MessageType.JobRegister: "register_job",
    MessageType.JobDeregister: "deregister_job",
    MessageType.EvalUpdate: "update_eval",
    MessageType.EvalDelete: "delete_eval",
    MessageType.AllocUpdate: "alloc_update",
    MessageType.AllocClientUpdate: "alloc_client_update",
    MessageType.PeriodicLaunchType: "periodic_launch",
    MessageType.PeriodicLaunchDelete: "periodic_launch_delete",
    MessageType.ServiceSync: "service_sync",
    MessageType.ApplySweepBatch: "sweep",
}


class FSM:
    """Applies typed messages to the state store."""

    def __init__(self, state: Optional[StateStore] = None):
        self.state = state or StateStore()
        # Every replica witnesses (index, time) on apply so a new leader has
        # a populated index<->time map after failover (reference: fsm.go:147
        # witnesses in Apply; fsm.go:430-551 persists it in the snapshot).
        self.timetable = TimeTable()
        # Event broker (nomad_tpu/events/): attached by the server when
        # the event stream is enabled. None keeps the apply path's event
        # cost at this one attribute check. Fed on EVERY replica, so any
        # server in the region can serve a gapless resume after failover.
        self.events = None
        # Replica state digest (analysis/replica_digest.py): attached by
        # the server when digest verification is enabled. None keeps the
        # apply path's digest cost at this one attribute check.
        self.digest = None
        # Leader-side observers (broker, blocked evals, periodic dispatch)
        # registered by the server when it holds leadership.
        self.on_eval_update: Optional[Callable[[Evaluation], None]] = None
        self.on_node_ready: Optional[Callable[[Node], None]] = None
        self.on_job_upsert: Optional[Callable[[Job], None]] = None
        self.on_job_delete: Optional[Callable[[str], None]] = None
        self.on_alloc_terminal: Optional[Callable[[Allocation], None]] = None

    def apply(self, index: int, msg_type: MessageType, payload: Dict[str, Any]) -> Any:
        """(reference: fsm.go:99-144 Apply dispatch; each handler is timed
        under nomad.fsm.<op> as in fsm.go:147 MeasureSince, and — inside
        an active trace — spanned as fsm.<op>, child-only so background
        applies never mint traces)"""
        # lint: allow(apply_pure, local metrics timer; never enters state)
        start = time.monotonic()
        # The witness is REPLICA-LOCAL wall time by design (reference:
        # fsm.go:147): each replica records when IT applied the index, for
        # operator time->index queries. It never feeds replicated tables
        # or events; snapshots ship it only as a hint map.
        # lint: allow(apply_pure, replica-local index->time witness map)
        self.timetable.witness(index, time.time())
        handler = _HANDLERS[msg_type]
        leaf = _MSG_METRIC.get(msg_type, msg_type.name.lower())
        broker = self.events
        events = None
        try:
            with trace.span("fsm." + leaf, index=index):
                result = handler(self, index, payload)
                if broker is not None:
                    # Build INSIDE the span so publish stamps this
                    # entry's fsm trace/span ids onto its events.
                    try:
                        events = build_events(self, msg_type, payload)
                    except Exception:
                        # A builder bug must not fail a consensus-
                        # committed entry (the handler already applied);
                        # the entry publishes empty and the loss shows
                        # up in the equivalence fold.
                        logger.exception(
                            "event builder failed at index %d", index)
            # Fold only SUCCESSFUL applies into the digest chain (a
            # handler exception skips this via the raise): every replica
            # applies the same entries, so every replica folds the same
            # sequence.
            if self.digest is not None:
                self._digest_fold(index, msg_type, payload)
            return result
        finally:
            # Publish in the finally — even a failed handler releases the
            # broker's index reservation (empty batch), so one poisoned
            # entry can never wedge every later subscriber.
            if broker is not None:
                broker.publish(index, events or ())
            metrics.measure_since(("nomad", "fsm", leaf), start)

    def _digest_fold(self, index: int, msg_type: MessageType,
                     payload: Dict[str, Any]) -> None:
        """Fold this entry's post-apply effect into the replica digest
        chain. Any failure here is CONTAINED: the entry is consensus-
        committed and already applied, so a broken fold must never fail
        it — the digest marks itself unsynced (verification pauses until
        the next snapshot reseed) instead."""
        digest = self.digest
        try:
            if (self.on_eval_update is None
                    and failpoints.fire("fsm.digest.mutate") == "drop"):
                # Silent store corruption, injected BEFORE the effect
                # readback: this replica folds the corrupt value while
                # healthy replicas fold the clean one — the exact
                # divergence the checkpoint exchange exists to catch.
                # NON-leader replicas only (leader-side observers are the
                # leadership tell): the leader's chain is the reference
                # the quarantined follower reinstalls from, so corrupting
                # it would make the corruption authoritative — and the
                # guard comes FIRST so a count-bounded arm is consumed
                # by a replica that will actually corrupt, never burned
                # by a leader-side skip.
                chaos_corrupt(self.state, index, int(msg_type), payload)
            digest.fold(index, int(msg_type),
                        effect_of(self.state, index, int(msg_type),
                                  payload))
        except Exception:
            logger.exception("digest fold failed at index %d", index)
            digest.mark_unsynced(f"fold failed at index {index}")

    # ------------------------------------------------------------- handlers
    def _apply_node_register(self, index: int, req: Dict[str, Any]):
        node = from_dict(Node, req["Node"]) if isinstance(req["Node"], dict) \
            else req["Node"]
        existing = self.state.node_by_id(node.ID)
        self.state.upsert_node(index, node)
        # Re-registration to ready unblocks evals by class (fsm.go:158-164).
        if (node.Status == NodeStatusReady
                and (existing is None or existing.Status != NodeStatusReady)
                and self.on_node_ready is not None):
            self.on_node_ready(node)
        return None

    def _apply_node_deregister(self, index: int, req: Dict[str, Any]):
        self.state.delete_node(index, req["NodeID"])
        return None

    def _apply_node_status_update(self, index: int, req: Dict[str, Any]):
        self.state.update_node_status(index, req["NodeID"], req["Status"])
        if req["Status"] == NodeStatusReady and self.on_node_ready is not None:
            node = self.state.node_by_id(req["NodeID"])
            if node is not None:
                self.on_node_ready(node)
        return None

    def _apply_node_drain_update(self, index: int, req: Dict[str, Any]):
        self.state.update_node_drain(index, req["NodeID"], req["Drain"])
        return None

    def _apply_job_register(self, index: int, req: Dict[str, Any]):
        job = from_dict(Job, req["Job"]) if isinstance(req["Job"], dict) \
            else req["Job"]
        self.state.upsert_job(index, job)
        if self.on_job_upsert is not None:
            self.on_job_upsert(self.state.job_by_id(job.ID))
        return None

    def _apply_job_deregister(self, index: int, req: Dict[str, Any]):
        self.state.delete_job(index, req["JobID"])
        if self.on_job_delete is not None:
            self.on_job_delete(req["JobID"])
        return None

    def _apply_eval_update(self, index: int, req: Dict[str, Any]):
        evals: List[Evaluation] = [
            from_dict(Evaluation, e) if isinstance(e, dict) else e
            for e in req["Evals"]]
        self.state.upsert_evals(index, evals)
        # Leader enqueues runnable evals / blocks blocked ones (fsm.go:320-328).
        if self.on_eval_update is not None:
            for ev in evals:
                self.on_eval_update(ev)
        return None

    def _apply_eval_delete(self, index: int, req: Dict[str, Any]):
        self.state.delete_eval(index, req.get("Evals", []), req.get("Allocs", []))
        return None

    def _apply_alloc_update(self, index: int, req: Dict[str, Any]):
        # Two shapes: {"Job", "Alloc"} for one plan (reference parity,
        # fsm.go:356 applyAllocUpdate), or {"Batch": [{"Job", "Alloc"}, ...]}
        # when the plan applier commits several verified plans as one log
        # entry — the whole group lands in ONE state-store transaction (one
        # lock/commit/notify/job-status pass), which is where the per-plan
        # apply cost goes at storm rates.
        groups = req.get("Batch")
        if groups is None:
            groups = [req]
        allocs: List[Allocation] = []
        for group in groups:
            group_allocs = [
                from_dict(Allocation, a) if isinstance(a, dict) else a
                for a in group["Alloc"]]
            # Attach the shared job if provided (plan apply normalization).
            job = group.get("Job")
            if isinstance(job, dict):
                job = from_dict(Job, job)
            for alloc in group_allocs:
                if alloc.Job is None and job is not None:
                    alloc.Job = job
            allocs.extend(group_allocs)
        self.state.upsert_allocs(index, allocs)
        return None

    def _apply_sweep_batch(self, index: int, req: Dict[str, Any]):
        """Columnar sweep-batch commit: each group is either a per-object
        {"Job","Alloc"} group (the AllocUpdate shape — mixed entries carry
        the window's ordinary plans too) or a {"Job","Sweep","Updates"}
        group whose placements land as ONE SweepSegment scatter. The
        `state.store.commit` failure seam fires in the PLAN APPLIER,
        before raft.apply — an entry that reaches this handler has
        consensus-committed and must apply deterministically on every
        replica (an injected failure here would survive in the durable
        log and duplicate the batch on replay)."""
        groups = req.get("Batch")
        if groups is None:
            groups = [req]
        obj_allocs: List[Allocation] = []
        n_sweep = 0
        n_service = 0
        # One store transaction for the WHOLE entry: a sweep group's
        # stops, its segment, and any object co-groups land in separate
        # write calls below, and a blocking query woken between them
        # could otherwise observe a torn entry (an eviction committed
        # with its replacement not yet visible — exactly what the
        # eviction+placement-one-entry contract forbids). The lock is
        # reentrant; the inner writes re-acquire freely.
        with self.state.transaction():
            for group in groups:
                job = group.get("Job")
                if isinstance(job, dict):
                    job = from_dict(Job, job)
                sweep = group.get("Sweep")
                if sweep is None:
                    group_allocs = [
                        from_dict(Allocation, a) if isinstance(a, dict)
                        else a
                        for a in group.get("Alloc", ())]
                    for alloc in group_allocs:
                        if alloc.Job is None and job is not None:
                            alloc.Job = job
                    obj_allocs.extend(group_allocs)
                    continue
                updates = [
                    from_dict(Allocation, a) if isinstance(a, dict) else a
                    for a in group.get("Updates", ())]
                for alloc in updates:
                    if alloc.Job is None and job is not None:
                        alloc.Job = job
                if updates:
                    # Stop-then-place: the plan's exact-path evictions
                    # commit before its columnar placements, same order
                    # the object path guarantees within one entry.
                    self.state.upsert_allocs(index, updates)
                templates = [
                    t if isinstance(t, Allocation)
                    else from_dict(Allocation, t)
                    for t in sweep["Templates"]]
                for t in templates:
                    if t.Job is None and job is not None:
                        t.Job = job
                row_node_ids = list(sweep["RowNodeIDs"])
                counts = np.asarray(sweep["Counts"], dtype=np.int64)
                node_per_alloc = np.repeat(
                    np.asarray(row_node_ids, dtype=object),
                    counts).tolist()
                seg = SweepSegment(
                    index=index,
                    job_id=templates[0].JobID,
                    eval_id=templates[0].EvalID,
                    templates=templates,
                    tg_idx=list(sweep["TGIdx"]),
                    alloc_ids=list(sweep["AllocIDs"]),
                    names=list(sweep["Names"]),
                    node_ids=node_per_alloc,
                    kind=sweep.get("Kind", "system"))
                self.state.apply_sweep_segment(
                    index, seg,
                    rows=np.asarray(sweep["Rows"], dtype=np.int64),
                    delta=np.asarray(sweep["Delta"], dtype=np.float32),
                    row_node_ids=row_node_ids,
                    epoch=int(sweep.get("Epoch", -1)))
                n_sweep += len(seg.alloc_ids)
                if seg.kind == "service":
                    n_service += len(seg.alloc_ids)
            if obj_allocs:
                self.state.upsert_allocs(index, obj_allocs)
        if n_sweep:
            metrics.incr_counter(("nomad", "fsm", "sweep", "allocs"),
                                 n_sweep)
        if n_service:
            # Service-window rows committed columnar, vs the system-sweep
            # rows the total above also counts — the per-path split the
            # sched-stats `Store` block surfaces.
            metrics.incr_counter(("nomad", "fsm", "sweep", "service_allocs"),
                                 n_service)
        return None

    def _apply_alloc_client_update(self, index: int, req: Dict[str, Any]):
        for a in req["Alloc"]:
            alloc = from_dict(Allocation, a) if isinstance(a, dict) else a
            # A client can report status for an alloc the server already
            # GC'd (its sync loop races system-gc). Skip it up front:
            # letting the store raise would poison the whole COALESCED
            # update batch and lose every other client's statuses riding
            # in it. (A pre-check rather than catching KeyError, which
            # would also mask listener bugs downstream of the write.)
            if self.state.alloc_by_id(alloc.ID) is None:
                logger.debug("client update for unknown alloc %s dropped",
                             alloc.ID)
                continue
            self.state.update_alloc_from_client(index, alloc)
            # Terminal client status frees capacity: unblock by node class
            # (reference: fsm.go:395-428).
            updated = self.state.alloc_by_id(alloc.ID)
            if (updated is not None and updated.terminal_status()
                    and self.on_alloc_terminal is not None):
                self.on_alloc_terminal(updated)
        return None

    def _apply_periodic_launch(self, index: int, req: Dict[str, Any]):
        launch = req["Launch"]
        if isinstance(launch, dict):
            launch = from_dict(PeriodicLaunch, launch)
        self.state.upsert_periodic_launch(index, launch)
        return None

    def _apply_periodic_launch_delete(self, index: int, req: Dict[str, Any]):
        self.state.delete_periodic_launch(index, req["JobID"])
        return None

    def _apply_service_sync(self, index: int, req: Dict[str, Any]):
        """Service registry sync: batched upserts + deregistrations from one
        node's service manager (or a server's self-registration)."""
        upserts = [from_dict(ServiceRegistration, r) if isinstance(r, dict)
                   else r for r in req.get("Upserts", ())]
        if upserts:
            self.state.upsert_services(index, upserts)
        deletes = list(req.get("Deletes", ()))
        if deletes:
            self.state.delete_services(index, deletes)
        return None

    # ------------------------------------------------------ snapshot/restore
    def snapshot(self) -> Dict[str, Any]:
        """Serialize the full FSM state (reference: fsm.go:430-551).
        Columnar sweep segments round-trip COLUMNAR ("columnar_allocs"):
        a million sweep-placed rows persist as id/name/node columns plus
        one template per task group, never as per-alloc object dicts."""
        snap = self.state.snapshot()
        chain_allocs, col_segments = snap.alloc_dump()
        return {
            "nodes": [to_dict(n) for n in snap.nodes()],
            "jobs": [to_dict(j) for j in snap.jobs()],
            "evals": [to_dict(e) for e in snap.evals()],
            "allocs": [to_dict(a) for a in chain_allocs],
            "columnar_allocs": col_segments,
            "periodic_launches": [to_dict(p) for p in snap.periodic_launches()],
            "services": [to_dict(s) for s in snap.services()],
            "indexes": {t: snap.get_index(t)
                        for t in ("nodes", "jobs", "evals", "allocs",
                                  "periodic_launch", "services")},
            "timetable": self.timetable.serialize(),
            # Chain value at the snapshot watermark: a replica restoring
            # this snapshot reseeds and keeps the chain canonical.
            "digest": (self.digest.snapshot_state()
                       if self.digest is not None else None),
        }

    def snapshot_chunks(self, chunk_items: int = SNAPSHOT_CHUNK_ITEMS):
        """Stream the FSM state as BOUNDED chunks (the streaming-snapshot
        persist path). The MVCC snapshot is pinned EAGERLY — before this
        returns — so the caller can capture the watermark under the apply
        lock and then iterate entirely off the apply path: chunks resolve
        through the pinned watermark while later raft entries keep
        committing. Each chunk is one small dict (a header, or up to
        `chunk_items` objects of one table); an oversized columnar segment
        is sliced by rows into several read-equivalent segments so no
        single chunk scales with sweep size."""
        snap = self.state.snapshot()
        timetable = self.timetable.serialize()
        # Pinned eagerly with the MVCC snapshot: the caller holds the
        # apply lock here, so the chain value matches the watermark.
        digest_state = (self.digest.snapshot_state()
                        if self.digest is not None else None)

        def batched(kind, items):
            for i in range(0, len(items), chunk_items):
                yield {"kind": kind, "items": items[i:i + chunk_items]}

        def gen():
            yield {
                "kind": "header",
                "indexes": {t: snap.get_index(t)
                            for t in ("nodes", "jobs", "evals", "allocs",
                                      "periodic_launch", "services")},
                "timetable": timetable,
                "digest": digest_state,
            }
            yield from batched("nodes", [to_dict(n) for n in snap.nodes()])
            yield from batched("jobs", [to_dict(j) for j in snap.jobs()])
            yield from batched("evals", [to_dict(e) for e in snap.evals()])
            chain_allocs, col_segments = snap.alloc_dump()
            yield from batched("allocs", [to_dict(a) for a in chain_allocs])
            # Columnar segments: group whole segments up to chunk_items
            # rows per chunk; slice a lone over-large segment by rows
            # (each slice restores as its own segment — identical on
            # every read surface, `alloc_dump` partition included).
            group: list = []
            rows = 0
            for seg in col_segments:
                n = len(seg["AllocIDs"])
                if n > chunk_items:
                    if group:
                        yield {"kind": "columnar_allocs", "items": group}
                        group, rows = [], 0
                    for i in range(0, n, chunk_items):
                        yield {"kind": "columnar_allocs",
                               "items": [_slice_segment(seg, i,
                                                        i + chunk_items)]}
                    continue
                if rows + n > chunk_items and group:
                    yield {"kind": "columnar_allocs", "items": group}
                    group, rows = [], 0
                group.append(seg)
                rows += n
            if group:
                yield {"kind": "columnar_allocs", "items": group}
            yield from batched(
                "periodic_launches",
                [to_dict(p) for p in snap.periodic_launches()])
            yield from batched("services",
                               [to_dict(s) for s in snap.services()])

        return gen()

    def restore_chunks(self, chunks) -> None:
        """Chunk-by-chunk restore with a SINGLE atomic cutover: every chunk
        loads into the Restore's staging tables; only the final commit()
        swaps them in. An iterator that raises (torn stream, injected
        chunk fault, killed install) leaves the live store — and the
        timetable — bit-identical to its pre-restore state."""
        r = self.state.restore()
        timetable = None
        digest_state = None
        loaders = {
            "nodes": (Node, r.node_restore),
            "jobs": (Job, r.job_restore),
            "evals": (Evaluation, r.eval_restore),
            "allocs": (Allocation, r.alloc_restore),
            "periodic_launches": (PeriodicLaunch, r.periodic_launch_restore),
            "services": (ServiceRegistration, r.service_restore),
        }
        for chunk in chunks:
            kind = chunk.get("kind")
            if kind == "header":
                for t, idx in (chunk.get("indexes") or {}).items():
                    r.index_restore(t, idx)
                timetable = chunk.get("timetable")
                digest_state = chunk.get("digest")
            elif kind == "columnar_allocs":
                for seg in chunk.get("items", ()):
                    r.columnar_restore(seg)
            elif kind in loaders:
                cls, load = loaders[kind]
                for item in chunk.get("items", ()):
                    load(from_dict(cls, item) if isinstance(item, dict)
                         else item)
            else:
                raise ValueError(f"unknown snapshot chunk kind {kind!r}")
        r.commit()
        if timetable:
            self.timetable.deserialize(timetable)
        if self.digest is not None:
            if digest_state:
                # Adopt the snapshot's chain value — folding resumes at
                # the watermark and the chain stays canonical.
                self.digest.reseed(digest_state["index"],
                                   digest_state["digest"])
            else:
                # Snapshot predates digests (or is an empty quarantine
                # wipe): fold but never verify until the next reseed —
                # an unverifiable chain must not raise false alarms.
                self.digest.mark_unsynced("restored snapshot without "
                                          "a digest chain value")
        if self.events is not None:
            # Snapshot install: entries below the restored watermark were
            # never applied here, so the ring cannot serve them. Raise
            # the gap floor; resuming subscribers below it re-snapshot.
            self.events.reset(self.state.latest_index())

    def restore(self, data: Dict[str, Any]) -> None:
        """(reference: fsm.go:444-551) One code path with the chunked
        restore: a monolithic snapshot dict is just a stream of
        one-table chunks."""
        def gen():
            yield {"kind": "header", "indexes": data.get("indexes", {}),
                   "timetable": data.get("timetable"),
                   "digest": data.get("digest")}
            for kind in ("nodes", "jobs", "evals", "allocs",
                         "columnar_allocs", "periodic_launches", "services"):
                items = list(data.get(kind, ()))
                if items:
                    yield {"kind": kind, "items": items}

        self.restore_chunks(gen())


_HANDLERS = {
    MessageType.NodeRegister: FSM._apply_node_register,
    MessageType.NodeDeregister: FSM._apply_node_deregister,
    MessageType.NodeUpdateStatus: FSM._apply_node_status_update,
    MessageType.NodeUpdateDrain: FSM._apply_node_drain_update,
    MessageType.JobRegister: FSM._apply_job_register,
    MessageType.JobDeregister: FSM._apply_job_deregister,
    MessageType.EvalUpdate: FSM._apply_eval_update,
    MessageType.EvalDelete: FSM._apply_eval_delete,
    MessageType.AllocUpdate: FSM._apply_alloc_update,
    MessageType.AllocClientUpdate: FSM._apply_alloc_client_update,
    MessageType.PeriodicLaunchType: FSM._apply_periodic_launch,
    MessageType.PeriodicLaunchDelete: FSM._apply_periodic_launch_delete,
    MessageType.ServiceSync: FSM._apply_service_sync,
    MessageType.ApplySweepBatch: FSM._apply_sweep_batch,
}


class DevRaft:
    """Single-node consensus stand-in: assigns monotone indexes and applies
    synchronously. The replicated log implementation plugs in behind the same
    `apply` seam (reference boot path: server.go:608 setupRaft DevMode)."""

    def __init__(self, fsm: FSM):
        self.fsm = fsm
        self._lock = threading.Lock()
        self._index = max(1, fsm.state.latest_index())

    def apply(self, msg_type: MessageType, payload: Dict[str, Any]) -> int:
        """Apply a mutation; returns the index it committed at."""
        with self._lock:
            self._index += 1
            index = self._index
            # Index assignment happens under the lock but the FSM apply
            # below runs outside it, so concurrent dev-mode applies can
            # reach the broker out of index order. Reserving HERE — still
            # in assignment order — lets the broker hold an early batch
            # until its predecessors publish, keeping the stream strictly
            # index-ordered. (The replicated backend applies in order and
            # never reserves.)
            broker = self.fsm.events
            if broker is not None:
                broker.reserve(index)
        self.fsm.apply(index, msg_type, payload)
        return index

    @property
    def last_index(self) -> int:
        return self._index
