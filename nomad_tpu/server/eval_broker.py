"""EvalBroker: leader-side priority queue of evaluations with at-least-once
delivery (reference: nomad/eval_broker.go).

Semantics mirrored: per-scheduler-type priority queues; per-JobID
serialization (one in-flight eval per job, rest held "blocked"); Ack/Nack
with nack-timeout redelivery; delivery-limit overflow into the `_failed`
queue; wait-time deferral; token-gated requeue (a scheduler reblocking its
own eval defers until the outstanding one is Ack'd/Nack'd).
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nomad_tpu.analysis import guarded_by, requires_lock
from nomad_tpu.structs import Evaluation, generate_uuid
from nomad_tpu.telemetry import trace
from nomad_tpu.timerwheel import TimerHandle, wheel

FAILED_QUEUE = "_failed"


class NotOutstandingError(Exception):
    pass


class TokenMismatchError(Exception):
    pass


class _PriorityQueue:
    """Max-priority heap of evaluations, FIFO within a priority."""

    _seq = itertools.count()

    def __init__(self) -> None:
        self._heap: List[Tuple[int, int, int, Evaluation]] = []

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(self._heap,
                       (-ev.Priority, ev.CreateIndex, next(self._seq), ev))

    def pop(self) -> Optional[Evaluation]:
        if not self._heap:
            return None
        return heapq.heappop(self._heap)[3]

    def peek(self) -> Optional[Evaluation]:
        if not self._heap:
            return None
        return self._heap[0][3]

    def __len__(self) -> int:
        return len(self._heap)


@dataclass
class _Unack:
    eval: Evaluation
    token: str
    nack_timer: TimerHandle


@dataclass
class BrokerStats:
    TotalReady: int = 0
    TotalUnacked: int = 0
    TotalBlocked: int = 0
    TotalWaiting: int = 0
    ByScheduler: Dict[str, Dict[str, int]] = field(default_factory=dict)


class EvalBroker:
    _concurrency = guarded_by(
        "_lock", "_enabled", "_evals", "_job_evals", "_blocked", "_ready",
        "_unack", "_requeue", "_time_wait", "stats")

    def __init__(self, nack_timeout: float = 60.0, delivery_limit: int = 3):
        if nack_timeout < 0:
            raise ValueError("timeout cannot be negative")
        self.nack_timeout = nack_timeout
        self.delivery_limit = delivery_limit
        self._enabled = False
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

        self._evals: Dict[str, int] = {}          # eval id -> delivery count
        self._job_evals: Dict[str, str] = {}      # job id -> in-flight eval id
        self._blocked: Dict[str, _PriorityQueue] = {}  # job id -> waiting
        self._ready: Dict[str, _PriorityQueue] = {}    # scheduler -> ready
        self._unack: Dict[str, _Unack] = {}
        self._requeue: Dict[str, Evaluation] = {}  # token -> eval
        self._time_wait: Dict[str, TimerHandle] = {}
        self.stats = BrokerStats()

    # ------------------------------------------------------------- lifecycle
    def enabled(self) -> bool:
        with self._lock:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
        if not enabled:
            self.flush()

    def flush(self) -> None:
        """(reference: eval_broker.go Flush)"""
        with self._lock:
            for unack in self._unack.values():
                unack.nack_timer.cancel()
            for timer in self._time_wait.values():
                timer.cancel()
            self._evals.clear()
            self._job_evals.clear()
            self._blocked.clear()
            self._ready.clear()
            self._unack.clear()
            self._requeue.clear()
            self._time_wait.clear()
            self.stats = BrokerStats()
            self._cond.notify_all()

    # --------------------------------------------------------------- enqueue
    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._process_enqueue(ev, "")

    def enqueue_all(self, evals: Dict[str, Tuple[Evaluation, str]]) -> None:
        """evals: eval.ID -> (eval, token) for token-gated requeues."""
        with self._lock:
            for ev, token in evals.values():
                self._process_enqueue(ev, token)

    @requires_lock("_lock")
    def _process_enqueue(self, ev: Evaluation, token: str) -> None:
        # Tracing: remember the enqueuing context (one dict write when a
        # trace is active, one truthiness check otherwise) so the worker
        # that dequeues this eval — any thread, any time — can resume it,
        # and stamp the hop on the active span.
        trace.link("eval", ev.ID)
        trace.add_event("broker.enqueue", eval=ev.ID, job=ev.JobID)
        if ev.ID in self._evals:
            if token == "":
                return
            unack = self._unack.get(ev.ID)
            if unack is not None and unack.token == token:
                self._requeue[token] = ev
            return
        if self._enabled:
            self._evals[ev.ID] = 0

        if ev.Wait > 0:
            self._time_wait[ev.ID] = wheel.after(
                ev.Wait / 1e9, self._enqueue_waiting, ev)
            self.stats.TotalWaiting += 1
            return
        self._enqueue_locked(ev, ev.Type)

    def _enqueue_waiting(self, ev: Evaluation) -> None:
        with self._lock:
            self._time_wait.pop(ev.ID, None)
            self.stats.TotalWaiting -= 1
            self._enqueue_locked(ev, ev.Type)

    def _enqueue_locked(self, ev: Evaluation, queue: str) -> None:
        if not self._enabled:
            return
        pending = self._job_evals.get(ev.JobID, "")
        if pending == "":
            self._job_evals[ev.JobID] = ev.ID
        elif pending != ev.ID:
            self._blocked.setdefault(ev.JobID, _PriorityQueue()).push(ev)
            self.stats.TotalBlocked += 1
            return
        self._ready.setdefault(queue, _PriorityQueue()).push(ev)
        self.stats.TotalReady += 1
        sched = self.stats.ByScheduler.setdefault(
            queue, {"Ready": 0, "Unacked": 0})
        sched["Ready"] += 1
        self._cond.notify_all()

    # --------------------------------------------------------------- dequeue
    def dequeue(self, schedulers: List[str], timeout: Optional[float] = None
                ) -> Tuple[Optional[Evaluation], str]:
        """Blocking dequeue of the highest-priority eligible eval.

        timeout is in seconds; None or 0 blocks indefinitely (reference
        semantics: Dequeue with timeout 0 has no timeout channel).
        """
        import time as _time

        end = None if not timeout else _time.monotonic() + timeout
        with self._lock:
            while True:
                if not self._enabled:
                    raise RuntimeError("eval broker disabled")
                got = self._scan(schedulers)
                if got is not None:
                    return got
                if end is None:
                    self._cond.wait()
                else:
                    remaining = end - _time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        return None, ""

    def dequeue_window(self, schedulers: List[str], count: int,
                       timeout: Optional[float] = None,
                       fill_timeout: float = 0.0
                       ) -> List[Tuple[Evaluation, str]]:
        """Batch dequeue of up to `count` evals as ONE window under a
        single lock hold (the N-worker fast path). Blocks like dequeue()
        for the first eligible eval, then drains whatever else is already
        ready; with fill_timeout > 0 it lingers that long for stragglers
        (an enqueue burst still landing) before returning a short window.

        Handing the whole window out inside one critical section gives
        each worker a DISJOINT eval set in one lock round — per-eval
        dequeue loops from two workers interleave-steal each other's
        window fills and convoy on the lock, so both end up dispatching
        half-size windows that each still pay a full device round trip."""
        import time as _time

        out: List[Tuple[Evaluation, str]] = []
        if count <= 0:
            return out
        end = None if not timeout else _time.monotonic() + timeout
        with self._lock:
            while True:
                if not self._enabled:
                    raise RuntimeError("eval broker disabled")
                got = self._scan(schedulers)
                if got is not None:
                    out.append(got)
                    break
                if end is None:
                    self._cond.wait()
                else:
                    remaining = end - _time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        return out
            fill_end = _time.monotonic() + fill_timeout
            while len(out) < count:
                if not self._enabled:
                    break
                got = self._scan(schedulers)
                if got is not None:
                    out.append(got)
                    continue
                remaining = fill_end - _time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    break
        return out

    @requires_lock("_lock")
    def _scan(self, schedulers: List[str]
              ) -> Optional[Tuple[Evaluation, str]]:
        eligible: List[str] = []
        eligible_priority = 0
        for sched in schedulers:
            pending = self._ready.get(sched)
            if pending is None:
                continue
            ready = pending.peek()
            if ready is None:
                continue
            if not eligible or ready.Priority > eligible_priority:
                eligible = [sched]
                eligible_priority = ready.Priority
            elif ready.Priority == eligible_priority:
                eligible.append(sched)
        if not eligible:
            return None
        return self._dequeue_for_sched(random.choice(eligible))

    @requires_lock("_lock")
    def _dequeue_for_sched(self, sched: str) -> Tuple[Evaluation, str]:
        ev = self._ready[sched].pop()
        entry = trace.linked_entry("eval", ev.ID)
        if entry is not None:
            # Synthesized queue-wait span: enqueue-link time -> now.
            trace.record_span(entry[0], "broker.wait", entry[1],
                              eval=ev.ID, scheduler=sched)
        token = generate_uuid()
        timer = wheel.after(self.nack_timeout, self.nack, ev.ID, token)
        self._unack[ev.ID] = _Unack(ev, token, timer)
        self._evals[ev.ID] = self._evals.get(ev.ID, 0) + 1
        self.stats.TotalReady -= 1
        self.stats.TotalUnacked += 1
        by = self.stats.ByScheduler[sched]
        by["Ready"] -= 1
        by["Unacked"] += 1
        return ev, token

    # --------------------------------------------------------------- ack/nack
    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._lock:
            unack = self._unack.get(eval_id)
            return unack.token if unack is not None else None

    def outstanding_reset(self, eval_id: str, token: str) -> None:
        """Reset the nack timer mid-flight (reference: OutstandingReset)."""
        with self._lock:
            unack = self._unack.get(eval_id)
            if unack is None:
                raise NotOutstandingError(eval_id)
            if unack.token != token:
                raise TokenMismatchError(eval_id)
            unack.nack_timer.cancel()
            unack.nack_timer = wheel.after(self.nack_timeout, self.nack,
                                           eval_id, token)

    def outstanding_reset_batch(self, pairs: List[Tuple[str, str]]
                                ) -> set:
        """outstanding_reset for a whole window under ONE lock hold (the
        pipelined worker re-arms every live eval's nack deadline at each
        stage entry; per-eval lock rounds from N workers convoy here and
        let deadlines lapse mid-window — the redelivery storm behind the
        `stale` counter). Returns the set of eval ids no longer
        outstanding to this caller (redelivered / token rotated) instead
        of raising — one stale eval must not abort the sweep for the
        rest of the window."""
        stale: set = set()
        with self._lock:
            for eval_id, token in pairs:
                unack = self._unack.get(eval_id)
                if unack is None or unack.token != token:
                    stale.add(eval_id)
                    continue
                unack.nack_timer.cancel()
                unack.nack_timer = wheel.after(self.nack_timeout, self.nack,
                                               eval_id, token)
        return stale

    def ack(self, eval_id: str, token: str) -> None:
        """(reference: eval_broker.go:461-519)"""
        with self._lock:
            self._ack_locked(eval_id, token)

    def ack_batch(self, pairs: List[Tuple[str, str]]
                  ) -> List[Tuple[str, Exception]]:
        """Ack a whole window's evals under ONE lock hold. Per-eval
        broker races (redelivered mid-window, token rotated) are
        returned, not raised — one lost eval must not abort the acks of
        the rest of the window."""
        failures: List[Tuple[str, Exception]] = []
        with self._lock:
            for eval_id, token in pairs:
                try:
                    self._ack_locked(eval_id, token)
                except (NotOutstandingError, TokenMismatchError) as e:
                    failures.append((eval_id, e))
        return failures

    @requires_lock("_lock")
    def _ack_locked(self, eval_id: str, token: str) -> None:
        requeued = self._requeue.pop(token, None)
        unack = self._unack.get(eval_id)
        if unack is None:
            raise NotOutstandingError(f"Evaluation ID not found: {eval_id}")
        if unack.token != token:
            raise TokenMismatchError(eval_id)
        unack.nack_timer.cancel()
        job_id = unack.eval.JobID

        self.stats.TotalUnacked -= 1
        queue = unack.eval.Type
        if self._evals.get(eval_id, 0) > self.delivery_limit:
            queue = FAILED_QUEUE
        by = self.stats.ByScheduler.get(queue)
        if by is not None:
            by["Unacked"] -= 1

        self._unack.pop(eval_id, None)
        self._evals.pop(eval_id, None)
        self._job_evals.pop(job_id, None)

        blocked = self._blocked.get(job_id)
        if blocked is not None and len(blocked):
            ev = blocked.pop()
            if not len(blocked):
                self._blocked.pop(job_id, None)
            self.stats.TotalBlocked -= 1
            self._enqueue_locked(ev, ev.Type)

        if requeued is not None:
            self._process_enqueue(requeued, "")

    def nack(self, eval_id: str, token: str) -> None:
        """(reference: eval_broker.go:520-560)"""
        with self._lock:
            self._requeue.pop(token, None)
            unack = self._unack.get(eval_id)
            if unack is None:
                raise NotOutstandingError(f"Evaluation ID not found: {eval_id}")
            if unack.token != token:
                raise TokenMismatchError(eval_id)
            unack.nack_timer.cancel()
            self._unack.pop(eval_id, None)
            self.stats.TotalUnacked -= 1
            by = self.stats.ByScheduler.get(unack.eval.Type)
            if by is not None:
                by["Unacked"] -= 1
            if self._evals.get(eval_id, 0) >= self.delivery_limit:
                self._enqueue_locked(unack.eval, FAILED_QUEUE)
            else:
                self._enqueue_locked(unack.eval, unack.eval.Type)
